"""Host-facing Solve() API.

Wraps the device kernel (ops/binpack.py) with the host plumbing the
reference spreads across its provisioner loop:

- shape bucketing + padding (jit compiles once per bucket; wildly varying
  pod counts hit a small, warm set of compiled shapes),
- bin-table overflow retry with the next bucket size,
- NodePlan decoding: bin table + assignment matrix → named NodeClaims-to-be
  (instance type, zone, capacity type, price, pod list per node), existing
  node assignments, and per-pod unschedulable reasons,
- the graceful-degradation ladder (docs/concepts/degradation.md): a batch
  whose group axis exceeds the largest compiled bucket is wave-split into
  bucket-sized waves carrying open-bin state between them; any device-path
  failure (G overflow under an injected ceiling, bin-table growth
  exhaustion, XLA compile error, device OOM) lands on a pure-host
  sequential FFD fallback (solver/oracle.py) after a bounded retry —
  adversarial input degrades latency, never availability.

The decoded NodePlan is what the provisioning controller turns into
NodeClaims and hands to the CloudProvider (the reference's scheduler →
NodeClaim → Create() flow, SURVEY.md §3.2).
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence, Tuple

import threading

import jax
import jax.numpy as jnp
import numpy as np

from .. import trace
from ..apis.resources import R
from ..errors import (SolverCapacityError, SolverDeviceError, SolverError,
                      is_retryable_solver_error)
from ..lattice.tensors import Lattice
from ..ops import binpack
from . import costmodel, taxonomy
from .explain import unplaced_reason
from .faults import FaultInjector
from .pipeline import (ResidentInputCache, StageTimer, fetch_async,
                       plan_changed)
from .problem import Problem

_G_BUCKETS = (16, 32, 64, 96, 128, 192, 256, 512, 1024, 4096)
_B_BUCKETS = (32, 128, 512, 1024, 2048, 8192)


def enable_persistent_compile_cache(cache_dir: str) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir`` so a
    RESTARTED operator never re-pays XLA compilation for bucket shapes
    it has compiled in any previous life (the cold-start SLO burn spike
    SOAK_r06 recorded — peak burn ~8 from the first-pass compile — comes
    from exactly this). The thresholds drop to zero: every kernel in the
    bucketed ladder is worth caching, and the cache key already covers
    jaxlib/backend versions so stale entries can never serve. Safe to
    call more than once; returns False (and leaves the process usable)
    on a JAX too old to support the knobs."""
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
        except Exception:
            pass   # older jaxlib: size threshold keeps its default
        return True
    except Exception:
        return False


@dataclass
class PlannedNode:
    node_pool: str
    instance_type: str
    zone: str
    capacity_type: str
    price_per_hour: float
    pods: List[str] = field(default_factory=list)
    # the bin's full feasible sets (every instance type that can hold the
    # bin's contents, cheapest-first, capped at MAX_FLEXIBLE_TYPES): the
    # launch path hands these to the cloud as CreateFleet overrides so an
    # ICE on the chosen offering falls through to the next-cheapest without
    # a re-solve (reference instance.go MaxInstanceTypes=60).
    # Sequence, not List: _feasible_sets_batch shares ONE immutable tuple
    # across same-pattern bins — consumers may REASSIGN the field
    # (provisioning.py relaxation) but must never mutate it in place
    feasible_types: Sequence[str] = field(default_factory=tuple)
    feasible_zones: Sequence[str] = field(default_factory=tuple)
    feasible_capacity_types: Sequence[str] = field(default_factory=tuple)
    # custom labels a virtual-pool bin pins on its node (the Exists-
    # operator workload segregation, solver/problem.py expansion);
    # node_pool is always the REAL pool name
    extra_labels: Dict[str, str] = field(default_factory=dict)


def _pool_out(pool) -> Tuple[str, Dict[str, str]]:
    """(real pool name, custom labels) for a possibly-virtual pool."""
    return (pool.base_name or pool.name, dict(pool.custom_labels))


MAX_FLEXIBLE_TYPES = 60  # reference pkg/providers/instance/instance.go:50


@dataclass
class NodePlan:
    new_nodes: List[PlannedNode]
    existing_assignments: Dict[str, List[str]]   # existing node name -> pods
    unschedulable: Dict[str, str]                # pod name -> reason
    new_node_cost: float                         # $/hr
    solve_seconds: float
    device_seconds: float
    warnings: List[str] = field(default_factory=list)
    # degradation-ladder provenance (docs/concepts/degradation.md): which
    # rung produced this plan, and what pushed the solve off the primary
    # device path. ``degraded_reason`` is a bounded enum ("g-overflow",
    # "b-exhausted", "device-error", "internal-error", and the sidecar
    # family "sidecar-hung" / "sidecar-unreachable" / "pool-exhausted" —
    # solver/taxonomy.py) so it can ride a metric label; the human
    # detail lands in ``warnings``.
    degraded: bool = False
    degraded_reason: str = ""
    solver_path: str = "device"                  # device | wave-split | host-ffd
    waves: int = 1
    device_retries: int = 0
    # per-stage wall-clock (ms) of the device solve, keyed by
    # solver/pipeline.py STAGES (build/upload/compute/download/decode).
    # In pipelined mode "download" is the residual wait AFTER overlapped
    # host work — the overlap evidence the bench and metrics surface.
    stage_ms: Dict[str, float] = field(default_factory=dict)
    # True when the overlapped path produced this plan (async dispatch /
    # double-buffered waves); parity tests prove the bit-identical claim
    pipelined: bool = False
    # devices in the mesh that produced this plan (1 = single-device).
    # Rides the Solve wire (serde meshDevices) and the claim provenance
    # annotation so `kpctl describe nodeclaims` shows whether the mesh
    # was engaged (docs/reference/sharding.md)
    mesh_devices: int = 1
    # max/mean per-shard pod load of this plan's split (0.0 = not
    # sharded). On the wire so a RemoteSolver caller's imbalance gauge
    # describes the sidecar that actually solved, not its local fallback
    shard_imbalance: float = 0.0

    @property
    def num_new_nodes(self) -> int:
        return len(self.new_nodes)


@dataclass
class _MicroState:
    """Retained cross-pass state of the device-resident reconcile
    microloop (docs/reference/microloop.md). ``key`` pins the layout
    this state was built under — any bucket/mesh/size drift is a cold
    restart, never a stale reuse. ``prev_dev`` is the previous pass's
    device result buffer (the changed-plan fingerprint compares against
    it ON DEVICE); ``prev_host`` its host copy, re-decoded with the
    current pass's pod names whenever the fingerprint says the packing
    did not move (the skipped-sync path). The mesh merge refinement
    retains its own result the same way."""

    key: Tuple
    prev_dev: object = None
    prev_host: Optional[np.ndarray] = None
    prev_cost: float = 0.0                       # mesh: psum'd raw cost
    prev_merge: Optional[Tuple[np.ndarray, int]] = None  # (result, B2)
    # the lattice VIEW (strong ref — an id() can never be reused stale)
    # and price version this state solved against: the merge refinement
    # reads avail/price tensors the shard-result fingerprint cannot
    # see, so a reprice or a new ICE-masked view invalidates retention
    # outright rather than risking a stale reuse
    lattice: object = None
    price_version: int = -1


class _MicroIneligible(Exception):
    """Internal: this pass cannot ride the microloop (shape, ceiling, or
    feature outside the steady-state envelope) — fall back to the
    standard solve ladder. Never surfaces to callers."""


class _CostShim:
    """Stands in for a ShardedPack when the microloop already holds the
    psum'd raw cost on the host (skipped-sync passes reuse it instead of
    re-fetching a device scalar)."""

    __slots__ = ("total_cost",)

    def __init__(self, total_cost: float):
        self.total_cost = total_cost


@dataclass
class ProbeResult:
    """Host-side aggregates of one batched what-if probe (ops/binpack.py
    pack_probe_fused). Enough to answer the consolidation criterion — "do the
    pods fit on the remaining capacity + ≤1 cheaper node?" (reference
    designs/consolidation.md) — without decoding a full NodePlan."""

    feasible: bool            # every pod placed (no leftover, no overflow)
    n_new: int                # new bins opened
    new_cost: float           # $/hr over new bins
    new_cap_type: Optional[str]  # capacity type of the single new bin
    flex: int                 # feasible-type count of that bin (spot guard)
    device_seconds: float = 0.0


def _bucket(n: int, buckets: Sequence[int], clamp: bool = False) -> int:
    for b in buckets:
        if n <= b:
            return b
    if clamp:
        # degrade gracefully: the kernel's overflow path marks what doesn't
        # fit as leftover-unschedulable rather than crashing the solve
        return buckets[-1]
    raise ValueError(f"problem size {n} exceeds the largest bucket {buckets[-1]}")


def _grow_bucket(b: int) -> Tuple[int, bool]:
    """Next bin bucket for the overflow retry; (same, False) at the top."""
    i = _B_BUCKETS.index(b)
    if i + 1 >= len(_B_BUCKETS):
        return b, False
    return _B_BUCKETS[i + 1], True


@dataclass
class _DecodeSet:
    """Host-side view of one pack result, decoded from the single fused
    device buffer (ops/binpack.py pack_packed — one device→host transfer
    instead of 18; the tunneled-TPU link charges ~100 ms per transfer)."""

    assign: np.ndarray        # [G,B] i32
    leftover: np.ndarray      # [G] i32
    np_id: np.ndarray         # [B] i32
    open: np.ndarray          # [B] bool
    fixed: np.ndarray         # [B] bool
    chosen_t: np.ndarray      # [B] i32
    chosen_z: np.ndarray      # [B] i32
    chosen_c: np.ndarray      # [B] i32
    chosen_price: np.ndarray  # [B] f32
    tmask_p: np.ndarray       # [B,ceil(T/8)] u8 packed
    zmask_p: np.ndarray       # [B,ceil(Z/8)] u8 packed
    cmask_p: np.ndarray       # [B,ceil(C/8)] u8 packed
    next_open: int
    # full-layout-only fields (the sharded tail-bin merge rebuilds bin
    # state from these; the lean single-device decode never reads them)
    npods: Optional[np.ndarray] = None      # [B] i32
    cum: Optional[np.ndarray] = None        # [B,R] f32
    alloc_cap: Optional[np.ndarray] = None  # [B,R] f32
    pm: Optional[np.ndarray] = None         # [B,A] i32
    po: Optional[np.ndarray] = None         # [B,A] bool

    def tmask(self, rows, T: int) -> np.ndarray:
        return np.unpackbits(self.tmask_p[rows], axis=1)[:, :T].astype(bool)

    def zmask(self, rows, Z: int) -> np.ndarray:
        return np.unpackbits(self.zmask_p[rows], axis=1)[:, :Z].astype(bool)

    def cmask(self, rows, C: int) -> np.ndarray:
        return np.unpackbits(self.cmask_p[rows], axis=1)[:, :C].astype(bool)


def _unpack_decode_set(buf: np.ndarray, G: int, T: int, Z: int, C: int,
                       A: int, lean: bool = False) -> _DecodeSet:
    """Inverse of ops/binpack.py _encode_decode_set (row layouts there)."""
    Tp, Zp, Cp, Ap = (T + 7) // 8, (Z + 7) // 8, (C + 7) // 8, (A + 7) // 8
    W = buf.shape[1]
    n_trailer = -(-(4 * G + 4) // W)
    B = buf.shape[0] - n_trailer
    rows = buf[:B]

    def col_i32(off: int) -> np.ndarray:
        return np.ascontiguousarray(rows[:, off: off + 4]).view(np.int32).ravel()

    def col_i16(off: int) -> np.ndarray:
        return (np.ascontiguousarray(rows[:, off: off + 2])
                .view(np.int16).ravel().astype(np.int32))

    def block_f32(off: int, n: int) -> np.ndarray:
        return np.ascontiguousarray(rows[:, off: off + 4 * n]).view(np.float32)

    trailer = np.ascontiguousarray(buf[B:]).reshape(-1)
    leftover = np.ascontiguousarray(trailer[: 4 * G]).view(np.int32).copy()
    next_open = int(np.ascontiguousarray(trailer[4 * G: 4 * G + 4]).view(np.int32)[0])

    if lean:
        o = 11 + Tp + Zp + Cp
        flags = rows[:, 10]
        return _DecodeSet(
            assign=(np.ascontiguousarray(rows[:, o: o + 2 * G])
                    .view(np.int16).astype(np.int32).T),
            leftover=leftover,
            np_id=col_i16(0), chosen_t=col_i16(2),
            chosen_z=rows[:, 4].astype(np.int32),
            chosen_c=rows[:, 5].astype(np.int32),
            chosen_price=np.ascontiguousarray(rows[:, 6:10]).view(np.float32).ravel(),
            open=(flags & 1).astype(bool), fixed=(flags & 2).astype(bool),
            tmask_p=rows[:, 11: 11 + Tp],
            zmask_p=rows[:, 11 + Tp: 11 + Tp + Zp],
            cmask_p=rows[:, 11 + Tp + Zp: o],
            next_open=next_open,
        )

    o = 26 + Tp + Zp + Cp
    assign = (np.ascontiguousarray(rows[:, o: o + 2 * G])
              .view(np.int16).astype(np.int32).T)            # [G,B]
    oc = o + 2 * G
    return _DecodeSet(
        assign=assign, leftover=leftover,
        npods=col_i32(0), np_id=col_i32(4),
        chosen_t=col_i32(8), chosen_z=col_i32(12), chosen_c=col_i32(16),
        chosen_price=np.ascontiguousarray(rows[:, 20:24]).view(np.float32).ravel(),
        open=rows[:, 24].astype(bool), fixed=rows[:, 25].astype(bool),
        tmask_p=rows[:, 26: 26 + Tp], zmask_p=rows[:, 26 + Tp: 26 + Tp + Zp],
        cmask_p=rows[:, 26 + Tp + Zp: o],
        cum=block_f32(oc, R),
        alloc_cap=block_f32(oc + 4 * R, R),
        pm=(np.ascontiguousarray(rows[:, oc + 8 * R: oc + 8 * R + 2 * A])
            .view(np.int16).astype(np.int32)),
        po=(np.unpackbits(rows[:, oc + 8 * R + 2 * A: oc + 8 * R + 2 * A + Ap],
                          axis=1)[:, :A].astype(bool)),
        next_open=next_open,
    )


def decode_sharded_pack(sp, G: int, T: int, Z: int, C: int,
                        A: int) -> List[_DecodeSet]:
    """Decode a ShardedPack's fused [D, B+n_trailer, W] buffer into one
    host-side _DecodeSet per shard (one device→host transfer for all
    shards; each shard's rows use the exact single-device layout)."""
    packed = np.asarray(sp.packed)
    return [_unpack_decode_set(packed[d], G, T, Z, C, A)
            for d in range(packed.shape[0])]


def _locked(fn):
    """Serialize a Solver entry point on the instance's solve lock
    (re-entrant: solve_relaxed → solve nests fine)."""
    import functools

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._solve_lock:
            return fn(self, *args, **kwargs)
    return wrapper


class Solver:
    """Holds the lattice resident on device; solves padded problems.

    Thread-safe: every public solve/probe entry point serializes on an
    internal RLock (see __init__)."""

    # the provisioner's steady-state delta path targets the IN-PROCESS
    # device pipeline (resident input cache + solve_delta); RemoteSolver
    # flips this off — a delta solved locally would silently bypass the
    # operator's --solver-address delegation
    supports_delta = True

    def __init__(self, lattice: Lattice, pipeline: bool = True, clock=None,
                 mesh=None):
        self.lattice = lattice
        # the device mesh every solve runs over (parallel/mesh.py
        # plan_mesh resolves the operator's --mesh/SOLVER_MESH setting at
        # boot; None = the single-device passthrough). A per-call
        # ``mesh=`` argument still overrides — tests and the multichip
        # dry-run force specific shapes that way.
        self.mesh = mesh
        # the device-retry backoff sleeps on the INJECTED clock: under
        # FakeClock a weather-driven retry steps simulated time instead
        # of stalling the deterministic stratum on a real sleep
        from ..utils.clock import WALL
        self._clock = clock if clock is not None else WALL
        # probe-gated Pallas finalization: on a TPU backend the streaming
        # cheapest-offering kernel replaces the [B,T,Z,C] XLA intermediate
        # (ops/offering_argmin.py); anywhere it cannot lower, the probe
        # fails once (cached) and the XLA form stays
        binpack.enable_pallas_argmin()
        self._alloc = jnp.asarray(lattice.alloc)
        self._avail = jnp.asarray(lattice.available)
        self._price = jnp.asarray(lattice.price)
        self._price_version = lattice.price_version
        self._tracing = False
        self._trace_step = 0
        # one device pipeline, many callers: the async runtime, the gRPC
        # sidecar, and in-process controllers can all reach this Solver
        # concurrently, and solve/probe mutate shared caches (_b_hint, the
        # price-version re-upload). Serialize every public entry point.
        # Instrumented (introspect/contention.py): solve-lock wait is
        # exactly "how long a caller queued behind another solve".
        from ..introspect import contention
        self._solve_lock = contention.rlock("solver_solve")
        # per group-bucket: (fresh-estimate bucket, bucket actually needed)
        # of the last solve. A same-or-larger fresh estimate starts at the
        # size that worked (each overflow retry costs a full device round
        # trip); a smaller estimate ignores the hint, so one big wave never
        # pins later small solves to a huge padded bin table.
        self._b_hint: Dict[int, Tuple[int, int]] = {}
        # content-keyed memo of _estimate_bins' per-group fit caps (count-
        # independent, see _estimate_caps_uncached): steady-state clusters
        # re-solve near-identical pending sets every pass, and the [G,T,R]
        # fit scan costs ~10 ms host time per 80-group problem
        self._est_cache: Dict[bytes, np.ndarray] = {}
        # degradation ladder state: an optional FaultInjector (tests/soaks
        # force each failure mode deterministically) and plain counters of
        # every off-primary-path event — the provisioning controller mirrors
        # these into the karpenter_solver_degraded_total metric family
        self.faults: Optional[FaultInjector] = None
        self.degraded_counts: Dict[str, int] = {}
        # the overlapped solve path (docs/concepts/performance.md
        # "Pipelining & the tunnel link"): async device dispatch with the
        # result fetch deferred to the last decode moment, double-buffered
        # wave uploads, and the resident-input delta cache. Off = the
        # strictly sequential path the byte-parity tests compare against.
        self.pipeline = pipeline
        self._resident = ResidentInputCache()
        # observability for soaks/benches: proof the overlap engaged
        self.pipeline_stats: Dict[str, int] = {
            "async_solves": 0,       # device solves that dispatched async
            "prefetched_waves": 0,   # wave inputs uploaded during compute
            # the steady-state delta path (solver/incremental.py +
            # solve_delta): how often it carried a pass, how many group
            # rows it re-tensorized, and whether the whole-problem
            # device-resident entry was warm
            "delta_solves": 0,
            "delta_dirty_groups": 0,
            "resident_problem_hits": 0,
            "resident_problem_misses": 0,
            # sharded solves carried by the mesh (full, wave, and delta
            # passes all count — the "is the mesh engaged?" evidence)
            "mesh_solves": 0,
            # the device-resident reconcile microloop (solve_delta →
            # _solve_micro; docs/reference/microloop.md): passes it
            # carried, plan fetches its fingerprint suppressed, plan
            # fetches it paid, merge refinements it ran/skipped, passes
            # that fell back to the standard ladder, O(1) fingerprint
            # syncs, and admission-bookkeeping closures it overlapped
            # with the in-flight dispatch
            "micro_solves": 0,
            "micro_skipped_syncs": 0,
            "micro_fetches": 0,
            "micro_merge_solves": 0,
            "micro_merge_skips": 0,
            # merge bin-table overflow regrows: each retry pays one more
            # upload+fetch pair, so the smoke/bench leg bounds allow
            # +2 legs per regrow on the pass that paid it
            "micro_merge_regrows": 0,
            "micro_aborts": 0,
            "micro_tiny_syncs": 0,
            "overlapped_admission": 0,
            # link legs of the LAST delta pass (upload+fetch transfers;
            # the smoke gate's per-pass ≤-bound evidence)
            "micro_last_legs": 0,
        }
        # cumulative host↔device link accounting (the
        # karpenter_solver_link_legs_total/_link_bytes_total source): a
        # LEG is a transfer whose size scales with the problem or plan
        # (fused input uploads, dirty-block scatters, result fetches);
        # O(1) control scalars — the microloop's changed-plan
        # fingerprint, n_existing — are counted as micro_tiny_syncs,
        # not legs, because they cannot regress to full re-staging
        self.link_stats: Dict[str, int] = {
            "upload_legs": 0, "upload_bytes": 0,
            "fetch_legs": 0, "fetch_bytes": 0,
        }
        self._resident.account = self._account_link
        # retained microloop state (None = cold); reset by every
        # device-state invalidation (fault recovery, mesh swap)
        self._micro: Optional[_MicroState] = None
        # max/mean per-shard pod load of the last sharded solve's split
        # (parallel/sharded.py shard_groups) — the shard-imbalance gauge
        self._mesh_imbalance = 0.0
        # mesh-replicated lattice tensors (_mesh_inputs): avail/price
        # memoized per (mesh, lattice view, price version); alloc is
        # invariant for this Solver's lifetime so it keys on the mesh
        # alone — a weather reprice must not re-replicate it
        self._mesh_consts = None
        self._mesh_alloc = None

    def set_pipeline(self, enabled: bool) -> None:
        """Toggle the overlapped solve path (thread-safe)."""
        with self._solve_lock:
            self.pipeline = bool(enabled)

    @property
    def mesh_devices(self) -> int:
        """Devices in the production mesh (1 = single-device path)."""
        m = self.mesh
        return int(m.devices.size) if m is not None else 1

    def set_mesh(self, mesh) -> None:
        """Swap the production mesh (thread-safe). Invalidates the
        resident input cache AND the replicated lattice memo: entries
        are keyed by device count, but buffers placed under the old
        mesh's sharding must never serve a delta against the new shape
        — a mesh-sized change re-uploads, it never delta-hits stale
        shards (pinned by tests/test_mesh.py)."""
        with self._solve_lock:
            self.mesh = mesh
            self._invalidate_device_state()

    def _account_link(self, direction: str, nbytes: int) -> None:
        """One host↔device transfer crossed the link (see link_stats)."""
        self.link_stats[direction + "_legs"] += 1
        self.link_stats[direction + "_bytes"] += int(nbytes)

    def _invalidate_device_state(self) -> None:
        """Drop EVERY retained device buffer: resident input entries,
        the replicated-lattice memo, and the microloop's retained result
        (its fingerprint base and donated problem state). One helper so
        the fault-recovery ladder and set_mesh can never forget a layer
        — a donated buffer surviving an invalidation would be
        re-dispatched after the backend consumed it (the donation-safety
        pin, tests/test_microloop.py)."""
        self._resident.invalidate()
        self._mesh_consts = None
        self._mesh_alloc = None
        self._micro = None

    def stats(self) -> Dict[str, object]:
        """Introspection snapshot (counter reads only — NEVER takes the
        solve lock: a snapshot must not queue behind an in-flight device
        solve, and every field is an independently-consistent counter)."""
        out: Dict[str, object] = {
            "pipeline": bool(self.pipeline),
            "est_cache_entries": len(self._est_cache),
            "b_hint_entries": len(self._b_hint),
            "faults_injected": self.faults is not None,
            # the mesh surface (docs/reference/sharding.md): device
            # count of the production mesh (1 = single-device), sharded
            # solves carried, and the last split's load imbalance —
            # what `kpctl top`'s SOLVER row and the
            # karpenter_solver_mesh_devices / _shard_imbalance_ratio
            # gauges render
            "mesh_devices": self.mesh_devices,
            "mesh_shard_imbalance": round(self._mesh_imbalance, 4),
            # the microloop surface: engaged + retained-state presence
            # read without the solve lock or any device sync (the
            # stats-never-blocks pin extends to every counter below)
            "micro_engaged": self._micro is not None,
        }
        for k, v in self.pipeline_stats.items():
            out[k] = v
        for k, v in self.link_stats.items():
            out["link_" + k] = v
        for k, v in self.degraded_counts.items():
            out["degraded_" + k.replace("-", "_")] = v
        for k, v in self._resident.stats().items():
            out["resident_" + k] = v
        return out

    _EST_CACHE_MAX = 128
    _DEVICE_RETRIES = 1          # transient device failures retried this often
    _RETRY_BACKOFF_SECONDS = 0.05
    _WAVE_G_TARGET = 1024        # per-wave group budget (a warm-ish bucket:
                                 # smaller compiles than the 4096 top bucket,
                                 # still few waves for realistic overflows)

    # ---- degradation ladder plumbing ----

    def inject_faults(self, faults: Optional[FaultInjector]) -> None:
        """Attach (or clear) a FaultInjector; see solver/faults.py."""
        with self._solve_lock:
            self.faults = faults

    def _count_degraded(self, key: str) -> None:
        self.degraded_counts[key] = self.degraded_counts.get(key, 0) + 1

    def _g_ceiling(self) -> int:
        """Effective group-axis ceiling: the largest compiled bucket, or an
        injected fake ceiling so tests exercise wave-split at small G."""
        top = _G_BUCKETS[-1]
        f = self.faults
        if f is not None and f.g_limit:
            return max(1, min(int(f.g_limit), top))
        return top

    def _b_ceiling(self) -> int:
        """Effective bin-table ceiling (snapped down to a bucket value)."""
        top = _B_BUCKETS[-1]
        f = self.faults
        if f is not None and f.b_limit:
            snapped = [b for b in _B_BUCKETS if b <= int(f.b_limit)]
            return snapped[-1] if snapped else _B_BUCKETS[0]
        return top

    def _maybe_inject_device_fault(self) -> None:
        f = self.faults
        if f is not None and f.take_device_error():
            raise SolverDeviceError("injected device fault")

    def _estimate_bins(self, problem: Problem) -> int:
        """Lower-bound estimate of bins the pack will open: each group
        needs at least count / (best-case per-node fit) bins, and never
        packs more than max_per_bin per node (hostname spread /
        anti-affinity). The expensive [G,T,R] fit scan is COUNT-
        INDEPENDENT and content-cached; the final count division re-runs
        per call, so steady-state passes whose pod counts drifted (the
        incremental build path) still hit the cache (~10 ms per 80-group
        problem otherwise)."""
        if problem.G == 0:
            return 0
        h = hashlib.blake2b(digest_size=16)
        for a in (problem.req, problem.g_type):
            h.update(a.tobytes())
        key = h.digest()
        caps = self._est_cache.get(key)
        if caps is None:
            caps = self._estimate_caps_uncached(problem)
            if len(self._est_cache) >= self._EST_CACHE_MAX:
                self._est_cache.clear()
            self._est_cache[key] = caps
        capped = np.minimum(np.maximum(caps, 1.0),
                            problem.max_per_bin.astype(np.float64))
        return int(np.ceil(problem.count / np.maximum(capped, 1.0)).sum())

    def _estimate_caps_uncached(self, problem: Problem) -> np.ndarray:
        """Per-group best-case per-node pod fit [G] (pre max_per_bin
        clamp). Fit is the joint vector fit of the best type the group's
        type mask actually allows (not per-resource maxima across
        different types, which systematically underestimates B for
        constrained workloads and forces a guaranteed overflow retry —
        one extra device round trip). The retry stays as the backstop."""
        alloc = self.lattice.alloc.astype(np.float64)               # [T,R]
        req = problem.req.astype(np.float64)                        # [G,R]
        caps = np.zeros((problem.G,), np.float64)
        CH = 256  # bound the [g,T,R] temp
        for s in range(0, problem.G, CH):
            r = req[s: s + CH]                                      # [g,R]
            m = problem.g_type[s: s + CH]                           # [g,T]
            pos = r[:, None, :] > 0
            ratio = np.where(pos, alloc[None, :, :]
                             / np.where(pos, r[:, None, :], 1.0), np.inf)
            fit_t = np.floor(np.nan_to_num(ratio.min(axis=2), posinf=1e9))
            caps[s: s + CH] = np.where(m, fit_t, 0.0).max(axis=1, initial=0.0)
        return caps

    def _device_avail_price(self, problem: Problem):
        """A problem built over a masked lattice view (ICE cache applied,
        state/unavailable.py) brings its own availability; shapes match, so
        the jitted kernel is reused without recompilation."""
        if problem.lattice is self.lattice:
            if self.lattice.price_version != self._price_version:
                # pricing refresh rewrote the tensor in place: re-upload
                self._price = jnp.asarray(self.lattice.price)
                self._price_version = self.lattice.price_version
            return self._avail, self._price
        return jnp.asarray(problem.lattice.available), jnp.asarray(problem.lattice.price)

    def _mesh_inputs(self, problem: Problem, mesh):
        """(alloc, avail, price) replicated across ``mesh`` — the
        759-type lattice is the 'weights' of this model: device_put
        once, resident on every shard across passes, instead of
        re-replicating ~MBs of lattice per solve. avail/price memoize
        per (mesh, lattice view, price version) — a pricing refresh
        (price_version bump) or an ICE-set change (a new masked view
        object) re-keys exactly those two; alloc is invariant for this
        Solver's lifetime and keys on the mesh alone, so a weather
        reprice tick never re-ships it. The memo holds a strong ref to
        the view so an id() can never be reused stale."""
        from ..parallel.sharded import replicated_sharding
        sh = None
        ma = self._mesh_alloc
        if ma is None or ma[0] != mesh:
            sh = replicated_sharding(mesh)
            ma = (mesh, jax.device_put(np.asarray(self.lattice.alloc), sh))
            self._mesh_alloc = ma
        lat = problem.lattice
        key = (mesh, id(lat), lat.price_version)
        mc = self._mesh_consts
        if mc is None or mc[0] != key:
            sh = sh if sh is not None else replicated_sharding(mesh)
            mc = (key, lat,
                  (jax.device_put(np.asarray(lat.available), sh),
                   jax.device_put(np.asarray(lat.price), sh)))
            self._mesh_consts = mc
        return (ma[1],) + mc[2]

    # ---- padding ----

    def _layout(self, problem: Problem, G: int, A: Optional[int] = None,
                NP: Optional[int] = None):
        lat = self.lattice
        A = max(problem.A, 1) if A is None else A
        NP = max(problem.NP, 1) if NP is None else NP
        return binpack.group_layout(G, lat.T, lat.Z, lat.C, NP, A, R)

    @staticmethod
    def _pad_field(problem: Problem, f: binpack.FieldSpec,
                   out: Optional[np.ndarray] = None,
                   override: Optional[np.ndarray] = None) -> np.ndarray:
        """Pad one staged field per its spec — the ONE writer both the
        per-array and fused staging paths go through. ``out`` writes into
        a caller-provided view (the fused buffer); ``override`` replaces
        the problem's source array (the merge solve's count swap)."""
        if out is None:
            dt = bool if f.dtype is np.uint8 else f.dtype
            out = np.full(f.shape, f.fill, dt)
        elif f.fill != 0:
            out.fill(f.fill)
        a = getattr(problem, f.src) if override is None else override
        if a.size:
            out[tuple(slice(0, s) for s in a.shape)] = a
        return out

    def _padded_groups(self, problem: Problem, G: int,
                       A: Optional[int] = None,
                       NP: Optional[int] = None) -> binpack.GroupBatch:
        layout, _ = self._layout(problem, G, A, NP)
        return binpack.GroupBatch(**{
            f.name: jnp.asarray(self._pad_field(problem, f))
            for f in layout if f.name in binpack.GroupBatch._fields})

    def _pool_params(self, problem: Problem,
                     NP: Optional[int] = None) -> binpack.PoolParams:
        layout, _ = self._layout(problem, 1, None, NP)
        return binpack.PoolParams(**{
            f.name: jnp.asarray(self._pad_field(problem, f))
            for f in layout if f.name in binpack.PoolParams._fields})

    def _fused_inputs_np(self, problem: Problem, G: int,
                         A: Optional[int] = None, NP: Optional[int] = None,
                         count_override: Optional[np.ndarray] = None) -> np.ndarray:
        """All group + pool tensors padded into ONE uint8 host buffer →
        one host→device transfer (every production path: solve, merge,
        probe, sharded). Staging 18 arrays separately pays the tunneled
        link's per-transfer cost 18×; field order/fill semantics are the
        shared spec in ops/binpack.group_layout, which the per-array
        helpers (_padded_groups/_pool_params — kernel tests and the
        __graft_entry__ compile check) also derive from."""
        layout, total = self._layout(problem, G, A, NP)
        buf = np.zeros((total,), np.uint8)
        for f in layout:
            n = int(np.prod(f.shape)) * np.dtype(f.dtype).itemsize
            view = buf[f.offset: f.offset + n].view(f.dtype).reshape(f.shape)
            self._pad_field(problem, f, out=view,
                            override=count_override if f.name == "count" else None)
        return buf

    def _fused_inputs(self, problem: Problem, G: int,
                      count_override: Optional[np.ndarray] = None) -> jnp.ndarray:
        return jnp.asarray(self._fused_inputs_np(
            problem, G, count_override=count_override))

    def _fused_init_np(self, problem: Problem, B: int,
                       A: Optional[int] = None) -> np.ndarray:
        """Existing bins as ONE small uint8 buffer (per-bin indices +
        resource rows; ops/binpack.init_layout) — the kernel rebuilds the
        one-hot masks on device. E == 0 yields the all-fill buffer
        (equivalent to an empty table; callers skip the upload entirely
        when no problem in the batch has existing capacity)."""
        A = max(problem.A, 1) if A is None else A
        layout, total = binpack.init_layout(B, R, A)
        buf = np.zeros((total,), np.uint8)
        for f in layout:
            n = int(np.prod(f.shape)) * np.dtype(f.dtype).itemsize
            view = buf[f.offset: f.offset + n].view(f.dtype).reshape(f.shape)
            self._pad_field(problem, f, out=view)
        return buf

    def _init_state(self, problem: Problem, B: int,
                    A: Optional[int] = None) -> binpack.BinState:
        lat = self.lattice
        E = problem.E
        A = max(problem.A, 1) if A is None else A
        state = binpack.empty_state(B, lat.T, lat.Z, lat.C, R, A)
        if E == 0:
            return state
        cum = np.zeros((B, R), np.float32)
        tmask = np.zeros((B, lat.T), bool)
        zmask = np.zeros((B, lat.Z), bool)
        cmask = np.zeros((B, lat.C), bool)
        np_id = np.full((B,), -1, np.int32)
        open_ = np.zeros((B,), bool)
        fixed = np.zeros((B,), bool)
        alloc_cap = np.full((B, R), np.inf, np.float32)
        pm = np.zeros((B, A), np.int32)
        po = np.zeros((B, A), bool)
        cum[:E] = problem.e_used
        tmask[np.arange(E), problem.e_type] = True
        zmask[np.arange(E), problem.e_zone] = True
        cmask[np.arange(E), problem.e_cap] = True
        np_id[:E] = problem.e_np
        open_[:E] = True
        fixed[:E] = True
        alloc_cap[:E] = problem.e_alloc  # real node allocatable wins over lattice
        if problem.A:
            pm[:E, : problem.A] = problem.e_pm
            po[:E, : problem.A] = problem.e_po
        return binpack.BinState(
            cum=jnp.asarray(cum), tmask=jnp.asarray(tmask), zmask=jnp.asarray(zmask),
            cmask=jnp.asarray(cmask), np_id=jnp.asarray(np_id),
            npods=jnp.zeros((B,), jnp.int32), open=jnp.asarray(open_),
            fixed=jnp.asarray(fixed), alloc_cap=jnp.asarray(alloc_cap),
            pm=jnp.asarray(pm), po=jnp.asarray(po),
            next_open=jnp.array(E, jnp.int32),
        )

    # ---- warmup (precompile the warm bucket set) ----

    # the boot warmup ladder: the shapes a production operator's FIRST
    # real passes actually hit. G=16..128 covers batches up to ~128
    # scheduling signatures (a 50k-pod wave of 30 deployment shapes is
    # G≈31 → bucket 32); B up to 2048 covers plans up to ~2k nodes.
    WARM_G_BUCKETS: Sequence[int] = (16, 32, 64)
    WARM_B_BUCKETS: Sequence[int] = (32, 128, 512)
    BOOT_G_BUCKETS: Sequence[int] = (16, 32, 64, 96, 128)
    BOOT_B_BUCKETS: Sequence[int] = (32, 128, 512, 1024, 2048)

    def warmup(self, node_pools_count: int = 1, affinity_classes: int = 1,
               g_buckets: Sequence[int] = WARM_G_BUCKETS,
               b_buckets: Sequence[int] = WARM_B_BUCKETS,
               probes: bool = False,
               background: bool = False,
               aot: bool = False,
               on_done=None):
        """Precompile the solve kernels for the warm (G, B) bucket set.

        The reference's Go scheduler has zero compile latency; XLA charges
        20-40 s per bucket shape on first trace. A fresh operator would
        otherwise pay that on its FIRST pending-pod batch — the worst
        possible moment. Compilation is keyed on the STATIC dims
        (G/B buckets, NP pool count, A affinity classes, lattice T/Z/C),
        so warmup must know the pool count; extra affinity classes,
        custom-label VIRTUAL pool variants (problem.NP can exceed the
        configured pool count), or pool additions later still compile on
        demand — the warm set covers the affinity-free common case, not
        every workload shape.

        ``aot=True`` AOT-LOWERS each shape and compiles it without
        executing the kernel. CAVEAT: ``.lower().compile()`` does NOT
        populate jit's dispatch cache — the first real call re-traces
        and re-compiles unless ``enable_persistent_compile_cache`` is
        wired, in which case it loads the executable from disk instead
        of re-paying XLA. So: pass ``aot=True`` only alongside a
        persistent cache dir (the CLI does exactly this); the default
        EXECUTING path warms the real dispatch cache directly and is the
        right call everywhere else.

        ``background=True`` runs on a daemon thread and returns it —
        operator startup proceeds while shapes compile; a real solve
        arriving mid-warmup just serializes on the solver lock.
        ``on_done`` (no-arg callable) fires when the ladder finishes,
        successfully or not — the operator uses it to close the SLO
        warmup window (introspect/slo.py).
        """
        if background:
            t = threading.Thread(
                target=self.warmup, name="solver-warmup", daemon=True,
                kwargs=dict(node_pools_count=node_pools_count,
                            affinity_classes=affinity_classes,
                            g_buckets=g_buckets, b_buckets=b_buckets,
                            probes=probes, aot=aot, on_done=on_done))
            t.start()
            return t
        try:
            lat = self.lattice
            NP = max(node_pools_count, 1)
            A = max(affinity_classes, 1)

            def compile_only(fn, *args, key=None, **static):
                """Compile without running: .lower().compile() populates
                the SAME jit cache (and the persistent on-disk cache) the
                real solve hits, minus the kernel execution. ``key``
                names the shape in the device cost model — the compiled
                handle already carries XLA's FLOPs/bytes/peak-HBM
                analysis, so warmup is where the model fills for free."""
                if aot:
                    try:
                        compiled = fn.lower(*args, **static).compile()
                        if key is not None:
                            costmodel.model().record_compiled(key, compiled)
                        return
                    except Exception:
                        pass   # fall through to the executing path
                np.asarray(fn(*args, **static))

            for G in g_buckets:
                _, g_total = binpack.group_layout(G, lat.T, lat.Z, lat.C,
                                                  NP, A, R)
                gbuf = jnp.asarray(np.zeros((g_total,), np.uint8))
                for B in b_buckets:
                    _, i_total = binpack.init_layout(B, R, A)
                    ibuf = jnp.asarray(np.zeros((i_total,), np.uint8))
                    for init in (None, ibuf):
                        with self._solve_lock:
                            compile_only(
                                binpack.pack_packed_efused,
                                self._alloc, self._avail, self._price,
                                gbuf, init, 0, B, G, lat.T, lat.Z, lat.C,
                                NP, A, key=costmodel.shape_key(G, B),
                                lean=True)
                    if probes:
                        for K in self._K_BUCKETS[:2]:
                            with self._solve_lock:
                                compile_only(
                                    binpack.pack_probe_fused,
                                    self._alloc, self._avail, self._price,
                                    jnp.tile(gbuf, (K, 1)),
                                    jnp.tile(ibuf, (K, 1)),
                                    jnp.zeros((K,), jnp.int32),
                                    B, G, lat.T, lat.Z, lat.C, NP, A)
        finally:
            if on_done is not None:
                try:
                    on_done()
                except Exception:
                    pass   # a callback bug must not kill the warmup thread
        return None

    def capture_cost_model(self, node_pools_count: int = 1,
                           affinity_classes: int = 1,
                           g_buckets: Sequence[int] = WARM_G_BUCKETS,
                           b_buckets: Sequence[int] = WARM_B_BUCKETS) -> int:
        """Fill the device cost model (solver/costmodel.py) for the
        given bucket ladder by LOWERING each shape — tracing only, no
        XLA compile, no kernel execution — and recording XLA's
        FLOPs/bytes analysis. Cheap enough to run at boot even without
        ``--warm-start``; the AOT warmup path records the same analyses
        from its compiled handles. Returns shapes captured."""
        lat = self.lattice
        NP = max(node_pools_count, 1)
        A = max(affinity_classes, 1)
        captured = 0
        for G in g_buckets:
            _, g_total = binpack.group_layout(G, lat.T, lat.Z, lat.C,
                                              NP, A, R)
            gbuf = jnp.asarray(np.zeros((g_total,), np.uint8))
            for B in b_buckets:
                try:
                    with self._solve_lock:
                        lowered = binpack.pack_packed_efused.lower(
                            self._alloc, self._avail, self._price,
                            gbuf, None, 0, B, G, lat.T, lat.Z, lat.C,
                            NP, A, lean=True)
                    if costmodel.model().record_compiled(
                            costmodel.shape_key(G, B), lowered):
                        captured += 1
                except Exception:
                    continue   # a shape that cannot lower has no model
        return captured

    # ---- profiling (xprof hook) ----

    def start_profiling(self, log_dir: str) -> None:
        """Open a JAX profiler trace session; every device pack call is then
        wrapped in a StepTraceAnnotation so Solve() hotspots (kernel time vs
        transfer vs host decode) show up in xprof/tensorboard under named
        steps. The reference side-channel is Go pprof on the controller
        (SURVEY §5 tracing); the TPU-native analog is the XLA profiler."""
        import jax.profiler
        jax.profiler.start_trace(log_dir)
        self._tracing = True

    def stop_profiling(self) -> None:
        import jax.profiler
        self._tracing = False
        jax.profiler.stop_trace()

    def _trace_span(self, name: str):
        if not self._tracing:
            import contextlib
            return contextlib.nullcontext()
        import jax.profiler
        self._trace_step += 1
        return jax.profiler.StepTraceAnnotation(name, step_num=self._trace_step)

    # ---- batched what-if probes ----

    _K_BUCKETS = (4, 8, 16, 32)

    @_locked
    def probe_batch(self, problems: Sequence[Problem]) -> List[ProbeResult]:
        """K consolidation what-ifs in ONE device call.

        Every problem is padded to a shared (K, G, B) bucket, stacked along
        a leading probe axis, and handed to the vmapped kernel
        (ops/binpack.pack_probe_fused); only tiny per-probe aggregates return.
        The disruption controller's prefix ladder + single-node scan ride
        this instead of O(log n + budget) serial Solve() round trips
        (SURVEY.md §2.2 "embarrassingly batchable"); the chosen probe is
        then re-solved exactly once for its real NodePlan."""
        assert problems
        lat = self.lattice
        assert all(p.lattice is problems[0].lattice for p in problems), \
            "probe batch must share one lattice view"
        K = len(problems)
        assert K <= self._K_BUCKETS[-1], f"probe batch {K} exceeds max"
        G = _bucket(max(p.G for p in problems), _G_BUCKETS)
        A = max(max((p.A for p in problems), default=0), 1)
        NP = max(max((p.NP for p in problems), default=0), 1)
        b_needed = max(p.E + min(int(p.count.sum()),
                                 self._estimate_bins(p) + 64)
                       for p in problems)
        B = _bucket(max(b_needed, max(p.E for p in problems) + 1),
                    _B_BUCKETS, clamp=True)
        avail, price = self._device_avail_price(problems[0])
        lat = self.lattice
        # pad K with repeats of problem 0 so jit shapes stay bucketed
        Kp = _bucket(K, self._K_BUCKETS, clamp=True)
        idx = list(range(K)) + [0] * (Kp - K)
        # ONE [K,·] upload for all probes' groups+pools (vs K×18 staged
        # arrays), one more for their existing bins — the tunneled link
        # charges per transfer, and a consolidation batch is ~dozens of
        # what-ifs over hundreds of existing bins
        gbufs = jnp.asarray(np.stack(
            [self._fused_inputs_np(problems[i], G, A, NP) for i in idx]))
        n_existing = jnp.asarray(np.array([problems[i].E for i in idx],
                                          np.int32))
        while True:
            if any(p.E for p in problems):
                ibufs = jnp.asarray(np.stack(
                    [self._fused_init_np(problems[i], B, A) for i in idx]))
            else:
                ibufs = None
            td = time.perf_counter()
            # ONE [K, len(ProbeSummary._fields)] f32 result buffer = one
            # device→host transfer for the whole batch; ProbeSummary's
            # field order IS the column contract on both sides
            with self._trace_span("solver.pack_probe"):
                summ = binpack.ProbeSummary(*np.asarray(
                    binpack.pack_probe_fused(
                        self._alloc, avail, price, gbufs, ibufs, n_existing,
                        B, G, lat.T, lat.Z, lat.C, NP, A)).T)
            device_s = time.perf_counter() - td
            if bool((summ.overflow[:K] > 0).any()):
                B, grew = _grow_bucket(B)
                if grew:
                    continue
            break
        out: List[ProbeResult] = []
        for k in range(K):
            nn = int(summ.n_new[k])
            cc = int(summ.cap_c[k])
            out.append(ProbeResult(
                feasible=(int(summ.leftover[k]) == 0
                          and not bool(summ.overflow[k])
                          and not problems[k].unschedulable),
                n_new=nn,
                new_cost=float(summ.new_cost[k]),
                new_cap_type=(lat.capacity_types[cc]
                              if nn > 0 and 0 <= cc < lat.C else None),
                flex=int(summ.flex[k]),
                device_seconds=device_s))
        return out

    # ---- solve ----

    @_locked
    def solve_relaxed(self, pods, node_pools, lattice=None, existing=(),
                      daemonset_pods=(), bound_pods=(), pvcs=None,
                      storage_classes=None, mesh=None,
                      pool_headroom=None, problem0=None) -> NodePlan:
        """Tracing shim over :meth:`_solve_relaxed`: the whole relaxation
        loop (every round's solve, wave, and stage spans nest underneath)
        is one span carrying the plan's degradation provenance — which is
        what the flight recorder's tail sampler keys retention on.

        ``problem0`` is an already-built round-0 problem for exactly
        these inputs (the provisioner's incremental builder produces one
        whether or not its delta path engaged) — round 0 reuses it
        instead of re-tensorizing; relaxation rounds always rebuild."""
        with trace.span("solver.solve_relaxed", pods=len(pods)) as sp:
            plan = self._solve_relaxed(
                pods, node_pools, lattice=lattice, existing=existing,
                daemonset_pods=daemonset_pods, bound_pods=bound_pods,
                pvcs=pvcs, storage_classes=storage_classes, mesh=mesh,
                pool_headroom=pool_headroom, problem0=problem0)
            sp.set(path=plan.solver_path, degraded=plan.degraded,
                   reason=plan.degraded_reason, waves=plan.waves,
                   pipelined=plan.pipelined,
                   new_nodes=len(plan.new_nodes),
                   unschedulable=len(plan.unschedulable))
            return plan

    def _solve_relaxed(self, pods, node_pools, lattice=None, existing=(),
                       daemonset_pods=(), bound_pods=(), pvcs=None,
                       storage_classes=None, mesh=None,
                       pool_headroom=None, problem0=None) -> NodePlan:
        """Solve with preferred-rule relaxation (reference
        scheduling.md:203-206, 322-334).

        Round 0 treats every soft constraint — preferred node affinity,
        ScheduleAnyway topology spread — as hard. Pods that come back
        unschedulable and still have soft constraints get them relaxed one
        tier at a time (lowest-weight preference first, then advisory
        spreads) and only those pods' groups re-enter the next solve round.
        A pod whose only obstacle is a preference or an advisory skew can
        therefore never end unschedulable; hard-constrained pods fail
        exactly as before. Bounded by the deepest pod's soft-constraint
        count; workloads without soft constraints pay zero extra rounds."""
        from ..apis.objects import relax_pod, relaxation_depth
        from .problem import build_problem

        lattice = lattice if lattice is not None else self.lattice
        depth = {p.name: relaxation_depth(p) for p in pods}
        relax: Dict[str, int] = {}
        # every round increments at least one pod's level, so sum-of-depths
        # bounds termination; relaxing one pod can cascade a sibling into an
        # infeasible spread domain, which is why max-depth alone is not
        # enough. Capped to keep a pathological wave's solve count sane.
        max_rounds = min(1 + sum(depth.values()), 64)
        best = None
        total_solve = total_device = 0.0
        # degradation provenance aggregates across rounds: the returned
        # plan reports the WORST rung any round landed on, so one degraded
        # relaxation round is never laundered into a clean-looking plan
        path_order = {"device": 0, "wave-split": 1, "host-ffd": 2}
        worst_path, any_degraded, reasons = "device", False, []
        total_retries, max_waves = 0, 1
        stage_total: Dict[str, float] = {}
        any_pipelined = False
        for _ in range(max_rounds):
            if problem0 is not None and not relax:
                # round 0 over unrelaxed pods: the caller already built
                # exactly this problem (provisioner incremental builder)
                problem = problem0
            else:
                eff = [p if relax.get(p.name, 0) == 0
                       else relax_pod(p, relax[p.name]) for p in pods]
                problem = build_problem(eff, node_pools, lattice,
                                        existing=existing,
                                        daemonset_pods=daemonset_pods,
                                        bound_pods=bound_pods, pvcs=pvcs,
                                        storage_classes=storage_classes,
                                        pool_headroom=pool_headroom)
            plan = self.solve(problem, mesh=mesh)
            total_solve += plan.solve_seconds
            total_device += plan.device_seconds
            total_retries += plan.device_retries
            max_waves = max(max_waves, plan.waves)
            any_pipelined = any_pipelined or plan.pipelined
            for k, v in plan.stage_ms.items():
                stage_total[k] = stage_total.get(k, 0.0) + v
            if plan.degraded:
                any_degraded = True
                if plan.degraded_reason and plan.degraded_reason not in reasons:
                    reasons.append(plan.degraded_reason)
            if path_order.get(plan.solver_path, 0) > path_order[worst_path]:
                worst_path = plan.solver_path
            # a relaxation round re-packs globally and may regress a pod
            # relaxation cannot help — keep the best plan seen, not the last
            if best is None or ((len(plan.unschedulable), plan.new_node_cost)
                                < (len(best.unschedulable), best.new_node_cost)):
                best = plan
            improvable = [n for n, reason in plan.unschedulable.items()
                          if relax.get(n, 0) < depth.get(n, 0)
                          # pre-solve failures (unknown resource names) are
                          # not fixable by dropping preferences — no rounds.
                          # The legacy free-text prefix stays recognized:
                          # a pre-taxonomy reason string must not burn
                          # relaxation rounds either
                          and taxonomy.code_of(reason)
                          != taxonomy.UNKNOWN_RESOURCE
                          and not reason.startswith("unknown resource")]
            if not improvable:
                break
            for n in improvable:
                relax[n] = relax.get(n, 0) + 1
        best.solve_seconds = total_solve
        best.device_seconds = total_device
        best.degraded = any_degraded
        best.degraded_reason = reasons[0] if reasons else best.degraded_reason
        best.solver_path = worst_path
        best.device_retries = total_retries
        best.waves = max_waves
        best.stage_ms = stage_total
        best.pipelined = any_pipelined
        return best

    @_locked
    def solve(self, problem: Problem, mesh=None) -> NodePlan:
        """Tracing shim over :meth:`_solve_problem` — one span per solve
        round with the ladder's outcome attached."""
        with trace.span("solver.solve", groups=problem.G) as sp:
            plan = self._solve_problem(problem, mesh=mesh)
            sp.set(path=plan.solver_path, degraded=plan.degraded,
                   reason=plan.degraded_reason, retries=plan.device_retries)
            return plan

    @_locked
    def solve_delta(self, problem: Problem, dirty_groups: Sequence[int] = (),
                    mesh=None, overlap=None) -> NodePlan:
        """The steady-state delta-solve entry point (ROADMAP item 2,
        docs/concepts/performance.md "Steady-state reconciles"). The
        problem arrived via solver/incremental.py, so the fused input
        buffers differ from the previous pass only in the dirty-group
        blocks: the device-resident reconcile MICROLOOP
        (docs/reference/microloop.md) ships exactly those blocks as one
        donated-scatter upload, dispatches against the resident problem
        state, and fetches the plan back only when the on-device
        changed-plan fingerprint says it moved. Any pass outside the
        microloop's envelope falls back to the standard solve ladder —
        the fallback is visible in the micro_aborts counter and the
        link-leg gauges, never silent. Forces the pipelined path for
        the duration of the call (delta semantics REQUIRE the resident
        cache) and records the delta evidence counters
        soaks/benches/`kpctl top` assert on. Plans are identical to
        :meth:`solve` of the same problem — the delta is in bytes
        moved, never in the answer.

        ``overlap`` (zero-arg callable) is the admission-bookkeeping
        seam: it runs INSIDE the device compute window (between
        dispatch and the fingerprint sync), so the provisioner's host
        work rides the in-flight dispatch instead of serializing
        behind it. It runs at most once per call — on the fallback
        rungs only AFTER the fallback solve lands. A post-dispatch
        failure can fire the seam and still drop the wave, so callers
        recording metrics from it must STAGE in the seam and commit
        after this returns (controllers/provisioning.py does)."""
        with trace.span("solver.solve_delta", groups=problem.G,
                        dirty=len(dirty_groups)) as sp:
            pre_hits = self._resident.hits
            pre_legs = (self.link_stats["upload_legs"]
                        + self.link_stats["fetch_legs"])
            was_pipelined = self.pipeline
            self.pipeline = True
            overlap_once = [overlap] if overlap is not None else []

            def run_overlap():
                if overlap_once:
                    fn = overlap_once.pop()
                    fn()
                    self.pipeline_stats["overlapped_admission"] += 1

            try:
                try:
                    plan = self._solve_micro(problem, mesh=mesh,
                                             overlap=run_overlap)
                    self.pipeline_stats["micro_solves"] += 1
                except _MicroIneligible:
                    self.pipeline_stats["micro_aborts"] += 1
                    plan = self._solve_problem(problem, mesh=mesh)
                    # only after the fallback lands: a failing pass must
                    # not record admission bookkeeping for a dropped wave
                    run_overlap()
                except Exception:
                    # the microloop's device state may be gone (and its
                    # donated buffers consumed): rebuild from scratch
                    # rather than re-dispatch against dead arrays, then
                    # let the standard ladder own retry/fallback —
                    # degradation in latency, never availability
                    self.pipeline_stats["micro_aborts"] += 1
                    self._invalidate_device_state()
                    plan = self._solve_problem(problem, mesh=mesh)
                    run_overlap()
            finally:
                self.pipeline = was_pipelined
            self.pipeline_stats["delta_solves"] += 1
            self.pipeline_stats["delta_dirty_groups"] += len(dirty_groups)
            self.pipeline_stats["micro_last_legs"] = (
                self.link_stats["upload_legs"]
                + self.link_stats["fetch_legs"] - pre_legs)
            if self._resident.hits > pre_hits:
                self.pipeline_stats["resident_problem_hits"] += 1
            else:
                self.pipeline_stats["resident_problem_misses"] += 1
            sp.set(path=plan.solver_path, degraded=plan.degraded,
                   resident_hit=self._resident.hits > pre_hits,
                   legs=self.pipeline_stats["micro_last_legs"])
            return plan

    # ---- the device-resident reconcile microloop (ROADMAP item 2) --------

    def _solve_micro(self, problem: Problem, mesh=None,
                     overlap=None) -> NodePlan:
        """One steady-state reconcile pass against device-RESIDENT
        problem state (docs/reference/microloop.md).

        The whole fused problem (groups+pools and, when present, the
        existing-bin table) lives as ONE resident device buffer; the
        pass block-diffs against it and ships exactly the dirty blocks
        in a single donated-scatter upload (leg 1). The solve dispatches
        against the updated resident state — on a mesh, against
        replicated device SLICES of it, with the per-shard count split
        derived on device (parallel/sharded.py device_split_counts) so
        no split bytes cross the link. Admission bookkeeping and decode
        prep run inside the compute window; the only mandatory sync is
        the O(1) changed-plan fingerprint (solver/pipeline.py
        plan_changed), and the full plan buffer is fetched (leg 2) only
        when it says the packing moved — an unchanged plan re-decodes
        the retained host bytes against the current pod names. Steady
        state therefore pays ≤2 data legs per pass: one dirty upload,
        one CONDITIONAL plan fetch (a mesh pass whose plan moved pays
        two more for the fused tail-bin merge refinement).

        Raises :class:`_MicroIneligible` for anything outside the
        envelope (wave-scale G, co-location/pinned groups on a mesh,
        bin-table overflow) — solve_delta falls back to the standard
        ladder, counted in micro_aborts. Plans are byte-identical to
        :meth:`solve` of the same problem, pinned by
        tests/test_microloop.py and the smoke/bench referees."""
        t0 = time.perf_counter()
        if mesh is None:
            mesh = self.mesh
        if problem.G == 0 or not self.pipeline:
            raise _MicroIneligible("empty or unpipelined")
        if problem.G > self._g_ceiling():
            raise _MicroIneligible("wave-scale G")
        lat = self.lattice
        D = int(mesh.devices.size) if mesh is not None else 1
        sharded = D > 1
        NP = max(problem.NP, 1)
        A = max(problem.A, 1)
        if sharded and (bool(problem.single_bin.any())
                        or (problem.A and bool(problem.g_need.any()))):
            # co-location / shard-0 pinning need the host split planner
            raise _MicroIneligible("pinned groups on mesh")
        stages = StageTimer()
        G = _bucket(problem.G, _G_BUCKETS)
        fresh = None
        if sharded:
            B = self._b_budget_sharded(problem, D)
        else:
            fresh, B = self._b_budget_single(problem, G)

        with stages.span("build"):
            fused_np = self._fused_inputs_np(problem, G)
            g_size = int(fused_np.size)
            combined_np = (np.concatenate(
                [fused_np, self._fused_init_np(problem, B)])
                if problem.E else fused_np)
        repl = None
        if sharded:
            from ..parallel.sharded import (device_split_counts,
                                            replicated_sharding,
                                            sharded_pack)
            repl = replicated_sharding(mesh)
        # the resident problem identity: mesh size, group/bin buckets,
        # and exact byte length — any drift is a cold re-upload, and
        # the retained fingerprint state below keys on the same tuple
        key = ("m", D, G, B, int(combined_np.size))
        with stages.span("upload"):
            comb_dev = self._resident.upload(key, combined_np,
                                             sharding=repl, donate=True)
        ms = self._micro
        if ms is not None and (
                ms.key != key
                or ms.lattice is not problem.lattice
                or ms.price_version != problem.lattice.price_version):
            # layout drift, a new (ICE-masked) lattice view, or a
            # reprice: retained results solved against other tensors —
            # cold restart, never a stale fingerprint match
            ms = None

        self._maybe_inject_device_fault()
        compute_ms0 = stages.ms.get("compute", 0.0)
        td = time.perf_counter()
        sp_res = None
        try:
            if sharded:
                alloc_r, avail, price = self._mesh_inputs(problem, mesh)
                gslice = comb_dev[:g_size]
                islice = comb_dev[g_size:] if problem.E else None
                count_off = next(
                    f.offset for f in binpack.group_layout(
                        G, lat.T, lat.Z, lat.C, NP, A, R)[0]
                    if f.name == "count")
                csplit = device_split_counts(gslice, D, count_off, G)
                with self._trace_span("solver.pack_micro"):
                    with stages.span("compute"):
                        sp_res = sharded_pack(
                            mesh, alloc_r, avail, price, gslice, islice,
                            problem.E, csplit, B, G, lat.T, lat.Z, lat.C,
                            NP, A)
                new_dev = sp_res.packed
            else:
                avail, price = self._device_avail_price(problem)
                with self._trace_span("solver.pack_micro"):
                    with stages.span("compute"):
                        if problem.E:
                            new_dev = binpack.pack_packed_combined(
                                self._alloc, avail, price, comb_dev,
                                g_size, problem.E, B, G, lat.T, lat.Z,
                                lat.C, NP, A, lean=True)
                        else:
                            new_dev = binpack.pack_packed_efused(
                                self._alloc, avail, price, comb_dev,
                                None, 0, B, G, lat.T, lat.Z, lat.C,
                                NP, A, lean=True)
        except SolverError:
            raise
        except Exception as e:
            raise SolverDeviceError(f"{type(e).__name__}: {e}",
                                    cause=e) from e
        # host work rides the in-flight dispatch: the provisioner's
        # admission bookkeeping (the fetch_async seam's successor here —
        # the fingerprint below replaces the eager result stream) and
        # the plan-independent decode prep
        if overlap is not None:
            overlap()
        # prep feeds only the single-device _decode below; the sharded
        # tail rebuilds its own inside _decode_sharded
        prep = None if sharded else self._decode_prep(problem)
        try:
            with stages.span("download"):
                # the one mandatory sync: O(1) changed-plan fingerprint
                changed = plan_changed(new_dev,
                                       ms.prev_dev if ms else None)
                self.pipeline_stats["micro_tiny_syncs"] += 1
                if changed:
                    buf = np.asarray(new_dev)
                    self._account_link("fetch", buf.nbytes)
                    self.pipeline_stats["micro_fetches"] += 1
                else:
                    buf = ms.prev_host
                    self.pipeline_stats["micro_skipped_syncs"] += 1
        except SolverError:
            raise
        except Exception as e:
            raise SolverDeviceError(f"{type(e).__name__}: {e}",
                                    cause=e) from e
        device_s = time.perf_counter() - td
        if ms is None:
            ms = _MicroState(key=key)
        self._micro = ms
        ms.lattice = problem.lattice
        ms.price_version = problem.lattice.price_version
        ms.prev_dev = new_dev
        if changed:
            ms.prev_host = buf
            ms.prev_merge = None
            if sharded:
                # the merge comparison's psum'd raw cost: fetched once
                # here (O(1)), reused by every skipped-sync pass
                ms.prev_cost = float(sp_res.total_cost)
                self.pipeline_stats["micro_tiny_syncs"] += 1

        if sharded:
            plan = self._micro_decode_sharded(problem, ms, buf, changed,
                                              G, B, D, stages, device_s)
        else:
            with stages.span("decode"):
                dec = _unpack_decode_set(buf, G, lat.T, lat.Z, lat.C, A,
                                         lean=True)
            if (dec.leftover.sum() > 0) and dec.next_open >= B:
                # bin-table overflow: the standard ladder owns growth
                self._micro = None
                raise _MicroIneligible("bin-table overflow")
            needed = _bucket(max(dec.next_open, problem.E + 1, 1),
                             _B_BUCKETS, clamp=True)
            self._b_hint[G] = (fresh, needed)
            with stages.span("decode"):
                plan = self._decode(problem, dec, device_s, prep=prep)
        plan.solve_seconds = time.perf_counter() - t0
        plan.warnings = list(problem.warnings)
        plan.stage_ms = stages.ms
        plan.pipelined = True
        plan.mesh_devices = D
        if sharded:
            plan.shard_imbalance = self._mesh_imbalance
            self.pipeline_stats["mesh_solves"] += 1
        costmodel.model().observe_solve(
            costmodel.shape_key(G, B, mesh_devices=D),
            stages.ms.get("compute", 0.0) - compute_ms0)
        self.pipeline_stats["async_solves"] += 1
        return plan

    def _micro_decode_sharded(self, problem: Problem, ms: _MicroState,
                              buf: np.ndarray, changed: bool, G: int,
                              B: int, D: int, stages: StageTimer,
                              device_s: float) -> NodePlan:
        """Mesh tail of the microloop: per-shard decode + the (possibly
        reused) merge refinement, byte-identical to _solve_sharded's."""
        from ..parallel.sharded import shard_groups, split_counts
        lat = self.lattice
        A = max(problem.A, 1)
        with stages.span("decode"):
            decs = [_unpack_decode_set(buf[d], G, lat.T, lat.Z, lat.C, A)
                    for d in range(buf.shape[0])]
        leftover = np.stack([dec.leftover for dec in decs])
        next_open = np.array([dec.next_open for dec in decs])
        if bool(((leftover.sum(axis=1) > 0) & (next_open >= B)).any()):
            self._micro = None
            raise _MicroIneligible("sharded bin-table overflow")
        # host mirror of the device-derived balanced split (identical
        # formula — the microloop aborted if pinning was in play), for
        # pod-name slicing and the imbalance gauge
        count_pad = np.zeros((G,), np.int32)
        count_pad[: problem.G] = problem.count
        count_split = split_counts(count_pad, D)
        load = shard_groups(count_split).astype(np.float64)
        self._mesh_imbalance = (float(load.max() / load.mean())
                                if load.mean() > 0 else 1.0)
        merge_ctx = {"reuse": None if changed else ms.prev_merge}
        with stages.span("decode"):
            plan = self._decode_sharded(problem, _CostShim(ms.prev_cost),
                                        decs, count_split, device_s,
                                        merge_ctx=merge_ctx)
        if merge_ctx.get("ran"):
            ms.prev_merge = merge_ctx["result"]
            self.pipeline_stats["micro_merge_solves"] += 1
        elif merge_ctx.get("reused"):
            self.pipeline_stats["micro_merge_skips"] += 1
        return plan

    def _solve_problem(self, problem: Problem, mesh=None) -> NodePlan:
        """Solve a problem into a NodePlan, degrading gracefully.

        ``mesh`` (a 1-D ``jax.sharding.Mesh`` over a 'pods' axis) shards the
        pod dimension across devices — the scale-out path for 50k+ pod waves
        (the reference handles this axis with batching windows on one Go
        core; here it is data-parallel over ICI, SURVEY.md §2.3).
        ``mesh=None`` defaults to the Solver's own production mesh
        (``self.mesh``, resolved at boot by parallel/mesh.py plan_mesh)
        — since PR 12 the sharded solve IS the production path when a
        mesh is planned, and every rung of the ladder (full solve,
        wave-split, the steady-state delta) rides it.

        The degradation ladder (docs/concepts/degradation.md): the primary
        device solve; a group axis past the largest compiled bucket goes
        through the wave-split planner (still on device); any device-path
        failure — capacity ceiling, XLA compile error, device OOM — earns a
        bounded retry for transient errors and then lands on the pure-host
        sequential FFD fallback. The ladder never raises for input shape or
        device health: adversarial batches degrade in latency, not
        availability.
        """
        t0 = time.perf_counter()
        if mesh is None:
            mesh = self.mesh
        if problem.G == 0:
            return NodePlan([], {}, dict(problem.unschedulable), 0.0,
                            time.perf_counter() - t0, 0.0)
        retries = 0
        while True:
            try:
                if problem.G > self._g_ceiling():
                    # provenance counts ONCE per solve, not per retry
                    # attempt — these are the counters soaks assert on
                    if retries == 0:
                        self._count_degraded("wave_split")
                        if self.faults is not None and self.faults.g_limit:
                            self.faults.note("g_overflow")
                    plan = self._solve_waves(problem, mesh, t0)
                else:
                    plan = self._solve_device(problem, mesh, t0)
                plan.device_retries = retries
                return plan
            except SolverCapacityError as e:
                # structural ceiling: retrying the same path cannot help
                reason = "b-exhausted" if e.axis == "B" else "g-overflow"
                detail = str(e)
                break
            except Exception as e:
                # only errors the taxonomy marks retryable (device weather:
                # XLA compile error, device OOM — _solve_device wraps these
                # as SolverDeviceError) earn a backoff + re-solve; a
                # deterministic host-side failure goes straight to the
                # fallback so a programming error is never misreported as
                # transient hardware trouble
                if is_retryable_solver_error(e):
                    # the failure may have taken the device-resident input
                    # buffers with it (backend restart, OOM eviction); drop
                    # the cache so the retry — and every later solve whose
                    # unchanged inputs would otherwise delta-hit a dead
                    # buffer — re-uploads instead. The replicated-lattice
                    # memo and the microloop's retained (donated) state
                    # hold device buffers too: left in place, a mesh
                    # retry would re-dispatch against the same dead
                    # arrays and turn one transient fault into a
                    # persistent mesh outage
                    self._invalidate_device_state()
                if is_retryable_solver_error(e) and retries < self._DEVICE_RETRIES:
                    retries += 1
                    self._count_degraded("device_retry")
                    self._clock.sleep(self._RETRY_BACKOFF_SECONDS * retries)
                    continue
                reason = ("device-error" if isinstance(e, SolverDeviceError)
                          else "internal-error")
                detail = f"{type(e).__name__}: {e}"
                break
        self._count_degraded("host_ffd")
        with trace.span("solver.host_ffd", reason=reason, degraded=True):
            plan = self.solve_host_ffd(problem)
        plan.solve_seconds = time.perf_counter() - t0
        plan.degraded = True
        plan.degraded_reason = reason
        plan.solver_path = "host-ffd"
        plan.device_retries = retries
        plan.warnings = list(problem.warnings) + [
            f"solver degraded to host FFD ({reason}: {detail})"]
        return plan

    def _b_budget_single(self, problem: Problem,
                         G: int) -> Tuple[int, int]:
        """The single-device bin budget, including the ``_b_hint``
        fast-restart dance — THE formula, shared by :meth:`_solve_device`
        and the microloop (:meth:`_solve_micro`) so the two paths can
        never drift apart (a divergent micro B silently changes the
        resident key every pass). Returns ``(fresh, B)``; callers feed
        ``fresh`` back into ``_b_hint`` after decode."""
        total_pods = int(problem.count.sum())
        b_needed = problem.E + min(total_pods,
                                   self._estimate_bins(problem) + 64)
        fresh = _bucket(max(b_needed, problem.E + 1), _B_BUCKETS,
                        clamp=True)
        prev = self._b_hint.get(G)
        if prev is not None and fresh >= prev[0]:
            # a same-or-larger problem shape than the one that last
            # forced a retry: start directly at the size that worked
            B = max(fresh, prev[1])
        else:
            B = fresh
        return fresh, min(B, self._b_ceiling())

    def _b_budget_sharded(self, problem: Problem, D: int) -> int:
        """The per-shard bin budget of the mesh pack — THE formula,
        shared by :meth:`_solve_sharded` and the microloop: existing
        bins (shard 0) + this shard's slice of the splittable groups +
        one tail bin per group + whole (pinned/co-located) groups +
        slack. The whole-group term is 0 inside the micro envelope
        (pinned groups abort to the host planner first), so sharing the
        full formula keeps the two paths' resident keys identical."""
        total_pods = int(problem.count.sum())
        caps = np.minimum(problem.max_per_bin.astype(np.int64),
                          np.maximum(problem.count.astype(np.int64), 1))
        capped_bins = int(np.ceil(problem.count
                                  / np.maximum(caps, 1)).sum())
        n_whole = int(problem.single_bin.sum()) + (
            int(problem.g_need.any(axis=1).sum()) if problem.A else 0)
        b_needed = problem.E + min(
            total_pods, -(-capped_bins // D) + problem.G + n_whole + 64)
        return min(_bucket(max(b_needed, problem.E + 1), _B_BUCKETS,
                           clamp=True), self._b_ceiling())

    def _solve_device(self, problem: Problem, mesh=None,
                      t0: Optional[float] = None, gbuf=None,
                      overlap=None) -> NodePlan:
        """The primary path: one bucketed device pack (or the pod-axis
        sharded variant when a multi-device mesh is supplied). Raises
        SolverCapacityError when the bin table cannot grow past its
        ceiling; the ladder in solve() owns what happens next.

        The pipelined variant (``self.pipeline``) overlaps host work with
        the in-flight device call: the result fetch is non-blocking (the
        device→host copy starts at dispatch, ``solver/pipeline.py
        fetch_async``), the ``overlap`` callable — the wave planner's
        "upload wave k+1 while wave k computes" hook — runs between
        dispatch and fetch, and host decode prep (pool name/label
        skeletons, existing-bin name table) fills the residual wait.
        ``gbuf`` is an already-uploaded fused group+pool buffer; when
        provided the build/upload stages were paid by the caller
        (possibly inside a previous wave's compute window).
        """
        t0 = time.perf_counter() if t0 is None else t0
        if mesh is not None and mesh.devices.size > 1:
            return self._solve_sharded(problem, mesh, t0, gbuf=gbuf,
                                       overlap=overlap)
        pipelined = self.pipeline
        stages = StageTimer()
        G = _bucket(problem.G, _G_BUCKETS)
        fresh, B = self._b_budget_single(problem, G)

        fused_np = None
        if gbuf is None:
            with stages.span("build"):
                fused_np = self._fused_inputs_np(problem, G)
        # the combined one-upload form only serves the sequential E>0
        # path; pipelined solves split group and init uploads so the big
        # group buffer can ride the resident delta cache (or a wave's
        # prefetch) while the small init buffer tracks carry state
        use_efused = pipelined or gbuf is not None or problem.E == 0
        if use_efused and gbuf is None:
            with stages.span("upload"):
                # ("g", G, size) is the whole-problem resident entry's
                # identity: a steady-state reconcile landing on the same
                # layout bucket delta-refreshes it (solve_delta counts
                # hit/miss via the cache's own counters)
                if pipelined:
                    gbuf = self._resident.upload(("g", G, fused_np.size),
                                                 fused_np)
                else:
                    gbuf = jnp.asarray(fused_np)
                    self._account_link("upload", fused_np.nbytes)
        avail, price = self._device_avail_price(problem)

        lat = self.lattice
        overlap_pending = overlap
        prep = None
        while True:
            self._maybe_inject_device_fault()
            # per-DISPATCH compute baseline: StageTimer accumulates
            # across overflow-regrow retries, but the cost model must
            # attribute only the FINAL dispatch's compute to the final
            # (G,B) shape — a retried solve is not "the device ran 2x
            # slower than its demonstrated best"
            compute_ms0 = stages.ms.get("compute", 0.0)
            td = time.perf_counter()
            # at most ONE group+pool upload and one small init upload
            # (fused into a single combined transfer on the sequential
            # E>0 path) + one fused result transfer; lean layout: the
            # plan decode never reads cum/alloc_cap/pm/po
            try:
                with self._trace_span("solver.pack"):
                    if use_efused:
                        init_dev = None
                        if problem.E:
                            with stages.span("build"):
                                init_np = self._fused_init_np(problem, B)
                            with stages.span("upload"):
                                if pipelined:
                                    init_dev = self._resident.upload(
                                        ("i", B, init_np.size), init_np)
                                else:
                                    init_dev = jnp.asarray(init_np)
                                    self._account_link("upload",
                                                       init_np.nbytes)
                        with stages.span("compute"):
                            dev_buf = binpack.pack_packed_efused(
                                self._alloc, avail, price, gbuf, init_dev,
                                problem.E, B,
                                G, lat.T, lat.Z, lat.C, max(problem.NP, 1),
                                max(problem.A, 1), lean=True)
                    else:
                        with stages.span("build"):
                            init_np = self._fused_init_np(problem, B)
                        with stages.span("upload"):
                            combined_host = np.concatenate(
                                [fused_np, init_np])
                            combined = jnp.asarray(combined_host)
                            self._account_link("upload",
                                               combined_host.nbytes)
                        with stages.span("compute"):
                            dev_buf = binpack.pack_packed_combined(
                                self._alloc, avail, price, combined,
                                len(fused_np), problem.E, B,
                                G, lat.T, lat.Z, lat.C, max(problem.NP, 1),
                                max(problem.A, 1), lean=True)
                if pipelined:
                    # start streaming the result the moment the kernel
                    # finishes; the host fills the wait below instead of
                    # paying a separate ready-wait + transfer leg
                    fetch_async(dev_buf)
            except SolverError:
                raise
            except Exception as e:
                # XLA compile error / device OOM / transfer failure: the
                # retryable rung of the ladder, as opposed to host-side
                # bugs which must NOT earn a blind re-solve
                raise SolverDeviceError(
                    f"{type(e).__name__}: {e}", cause=e) from e
            # host-side overlap work OUTSIDE the device-error wrap: a
            # deterministic bug in next-wave input building or decode
            # prep must surface as internal-error (no blind re-solve),
            # not masquerade as device weather. The device keeps
            # computing the already-dispatched kernel meanwhile.
            if overlap_pending is not None:
                # the wave pipeline's prefetch: wave k+1's inputs
                # build+upload while wave k computes
                overlap_pending()
                overlap_pending = None
            if prep is None:
                prep = self._decode_prep(problem)
            try:
                with stages.span("download"):
                    buf = np.asarray(dev_buf)
                    self._account_link("fetch", buf.nbytes)
            except SolverError:
                raise
            except Exception as e:
                raise SolverDeviceError(
                    f"{type(e).__name__}: {e}", cause=e) from e
            device_s = time.perf_counter() - td
            with stages.span("decode"):
                dec = _unpack_decode_set(buf, G, lat.T, lat.Z, lat.C,
                                         max(problem.A, 1), lean=True)
            overflowed = (dec.leftover.sum() > 0) and dec.next_open >= B
            if overflowed:
                nb, grew = _grow_bucket(B)
                if grew and nb <= self._b_ceiling():
                    B = nb
                    continue
                # growth exhausted: don't decode a plan that silently drops
                # the leftover — the ladder degrades to host FFD, whose bin
                # table is unbounded (availability over latency)
                if self.faults is not None and self.faults.b_limit:
                    self.faults.note("b_exhausted")
                raise SolverCapacityError(
                    f"bin table exhausted at B={B} with "
                    f"{int(dec.leftover.sum())} pod(s) left over", axis="B")
            break

        # record what this estimate bucket actually consumed (dec.next_open
        # rows), so the hint decays as soon as a smaller wave passes through
        needed = _bucket(max(dec.next_open, problem.E + 1, 1), _B_BUCKETS,
                         clamp=True)
        self._b_hint[G] = (fresh, needed)
        with stages.span("decode"):
            plan = self._decode(problem, dec, device_s, prep=prep)
        plan.solve_seconds = time.perf_counter() - t0
        plan.warnings = list(problem.warnings)
        plan.stage_ms = stages.ms
        plan.pipelined = pipelined
        # attribute the FINAL dispatch's measured compute to this (G,B)
        # shape's cost model: last-vs-best per shape is the "was the
        # DEVICE slow, or was it everything around it" signal kpctl top
        # and burn captures render (solver/costmodel.py)
        costmodel.model().observe_solve(
            costmodel.shape_key(G, B),
            stages.ms.get("compute", 0.0) - compute_ms0)
        if pipelined:
            # once per completed solve (not per overflow-regrow dispatch):
            # this is the "overlap engaged" evidence soak/bench assert on
            self.pipeline_stats["async_solves"] += 1
        return plan

    def _decode_prep(self, problem: Problem) -> Dict[str, object]:
        """Host decode work that does not depend on the device result —
        pool name/label skeletons and the existing-bin name table — run
        while the device computes so it is off the critical path. The
        values feed _decode identically in both modes (the sequential
        path just computes them after the fetch)."""
        return {
            "pool_out": [_pool_out(p) for p in problem.node_pools],
            "existing_names": [b.name for b in problem.existing],
        }

    # ---- wave-split planner (group-axis graceful degradation) ----

    def _solve_waves(self, problem: Problem, mesh, t0: float) -> NodePlan:
        """Solve a problem whose group axis exceeds the largest compiled
        bucket by partitioning it into bucket-sized WAVES and solving them
        in sequence on the device.

        Groups are already FFD-ordered (build_problem sorts descending), so
        waves run cost-ordered exactly like the sequential reference: the
        first wave packs the biggest groups, later waves fill in around
        them. Open-bin state carries BETWEEN waves — every node an earlier
        wave planned re-enters the next wave's problem as a pre-initialized
        existing bin (with its real chosen-type allocatable and its
        affinity-class presence counts), and placements onto REAL existing
        capacity update that capacity's remaining headroom — so packing
        quality stays within the host-FFD envelope instead of each wave
        opening its own fresh fleet.

        Pipelined mode double-buffers the wave INPUTS: wave k+1's fused
        group+pool buffer depends only on its group slice (never on carry
        state), so it builds and uploads while wave k computes on device
        — N waves stop paying one full upload leg each. The carry state
        itself (the small init buffer) is inherently sequential: it is
        derived at the stage boundary from wave k's decode, exactly as in
        the sequential planner, which is why the two modes produce
        byte-identical plans (tests/test_pipeline.py)."""
        ceiling = self._g_ceiling()
        wave = max(1, min(self._WAVE_G_TARGET, ceiling))
        bounds = [(lo, min(lo + wave, problem.G))
                  for lo in range(0, problem.G, wave)]
        n_waves = len(bounds)
        # a multi-device mesh COMPOSES with the wave planner rather than
        # bypassing it: each wave's fused group buffer is exactly what
        # the sharded program replicates, so the double-buffered
        # prefetch (and the resident delta cache, keyed by device count)
        # rides the mesh unchanged — wave k+1's upload lands inside wave
        # k's sharded compute window just like the single-device case
        sharded = mesh is not None and int(mesh.devices.size) > 1
        D = int(mesh.devices.size) if sharded else 1
        wave_sharding = None
        if sharded and self.pipeline:
            from ..parallel.sharded import replicated_sharding
            wave_sharding = replicated_sharding(mesh)
        pipelined = self.pipeline
        stages = StageTimer()

        def wave_gbuf(i: int):
            """Wave i's fused group+pool upload — carry-independent, so
            the pipelined loop runs this inside wave i-1's compute window
            (the _solve_device ``overlap`` hook)."""
            lo_i, hi_i = bounds[i]
            gp = self._wave_slice(problem, lo_i, hi_i)
            Gw = _bucket(gp.G, _G_BUCKETS)
            with stages.span("build"):
                fnp = self._fused_inputs_np(gp, Gw)
            with stages.span("upload"):
                if pipelined:
                    # D in the key: a wave buffer resident under one
                    # mesh shape must never serve another's delta
                    return self._resident.upload(("w", D, i, Gw, fnp.size),
                                                 fnp, sharding=wave_sharding)
                return jnp.asarray(fnp)

        A = problem.A
        # pod name -> group index (req/match/owner lookups while carrying
        # bin state across waves)
        gi_of: Dict[str, int] = {}
        for gi, g in enumerate(problem.groups):
            for name in g.pod_names:
                gi_of[name] = gi
        # pool identity -> index; virtual pools share a base name but
        # differ by custom labels, so the key carries both
        pool_idx: Dict[Tuple[str, frozenset], int] = {}
        for i, p in enumerate(problem.node_pools):
            pool_idx.setdefault(
                (p.base_name or p.name, frozenset(p.custom_labels.items())), i)
        e_idx = {b.name: i for i, b in enumerate(problem.existing)}

        # mutable copies of the real existing-bin running state
        e_used = problem.e_used.copy()
        e_pm = problem.e_pm.copy()
        e_po = problem.e_po.copy()

        # carried open bins: one pseudo existing bin per node planned by an
        # earlier wave (parallel lists; index = pseudo bin id)
        pseudo_nodes: List[PlannedNode] = []
        pseudo_used: List[np.ndarray] = []
        pseudo_np: List[int] = []
        pseudo_pm: List[np.ndarray] = []
        pseudo_po: List[np.ndarray] = []
        pseudo_by_name: Dict[str, int] = {}

        merged_assign: Dict[str, List[str]] = {}
        merged_unsched: Dict[str, str] = dict(problem.unschedulable)
        device_s = 0.0

        def register_pod(pn: str, used: np.ndarray, pm: np.ndarray,
                         po: np.ndarray) -> None:
            gi = gi_of[pn]
            used += problem.req[gi]
            if A:
                pm += problem.g_match[gi]
                po |= problem.g_owner[gi]

        # only the pipelined planner pre-builds wave inputs: the
        # sequential path keeps the pre-pipeline single combined
        # group+init upload inside _solve_device, so it stays the honest
        # baseline the cfg8 overlap margin is measured against
        next_gbuf = wave_gbuf(0) if pipelined else None
        for i, (lo, hi) in enumerate(bounds):
            sub = self._wave_problem(problem, lo, hi, e_used, e_pm, e_po,
                                     pseudo_nodes, pseudo_used, pseudo_np,
                                     pseudo_pm, pseudo_po)
            gbuf_i, next_gbuf = next_gbuf, None
            holder: Dict[str, object] = {}
            overlap = None
            if pipelined and i + 1 < n_waves:
                def overlap(j=i + 1):
                    # runs between wave i's dispatch and its result
                    # fetch: wave j's upload rides wave i's compute
                    holder["gbuf"] = wave_gbuf(j)
                    self.pipeline_stats["prefetched_waves"] += 1
            with trace.span("solver.wave", wave=i, groups=hi - lo,
                            prefetched=gbuf_i is not None and i > 0):
                plan_w = self._solve_device(sub, mesh, gbuf=gbuf_i,
                                            overlap=overlap)
            next_gbuf = holder.get("gbuf")
            if pipelined and next_gbuf is None and i + 1 < n_waves:
                # the prefetch hook did not run (e.g. the wave retried
                # past it): upload synchronously rather than skip a wave
                next_gbuf = wave_gbuf(i + 1)
            device_s += plan_w.device_seconds
            stages.merge(plan_w.stage_ms)
            merged_unsched.update(plan_w.unschedulable)
            for node_name, pod_names in plan_w.existing_assignments.items():
                pi = pseudo_by_name.get(node_name)
                if pi is not None:
                    # pods joining an earlier wave's planned node
                    pseudo_nodes[pi].pods.extend(pod_names)
                    for pn in pod_names:
                        register_pod(pn, pseudo_used[pi], pseudo_pm[pi],
                                     pseudo_po[pi])
                else:
                    merged_assign.setdefault(node_name, []).extend(pod_names)
                    ei = e_idx[node_name]
                    for pn in pod_names:
                        register_pod(pn, e_used[ei], e_pm[ei], e_po[ei])
            for node in plan_w.new_nodes:
                np_i = pool_idx.get(
                    (node.node_pool, frozenset(node.extra_labels.items())), 0)
                used = problem.ds_overhead[np_i].copy()
                pm = np.zeros((A,), np.int32)
                po = np.zeros((A,), bool)
                for pn in node.pods:
                    register_pod(pn, used, pm, po)
                # the name is positional — _wave_problem re-derives it from
                # the pseudo index, so later waves' assignments route back
                pseudo_by_name[f"__wave:{len(pseudo_nodes)}__"] = \
                    len(pseudo_nodes)
                pseudo_nodes.append(node)
                pseudo_used.append(used)
                pseudo_np.append(np_i)
                pseudo_pm.append(pm)
                pseudo_po.append(po)

        new_nodes = [n for n in pseudo_nodes if n.pods]
        cost = float(sum(n.price_per_hour for n in new_nodes))
        return NodePlan(
            new_nodes=new_nodes, existing_assignments=merged_assign,
            unschedulable=merged_unsched, new_node_cost=cost,
            solve_seconds=time.perf_counter() - t0, device_seconds=device_s,
            warnings=list(problem.warnings) + [
                f"wave-split: G={problem.G} over ceiling {ceiling}, "
                f"{n_waves} wave(s) of ≤{wave} groups"],
            degraded=True, degraded_reason="g-overflow",
            solver_path="wave-split", waves=n_waves,
            stage_ms=stages.ms, pipelined=pipelined, mesh_devices=D)

    def _wave_slice(self, problem: Problem, lo: int, hi: int) -> Problem:
        """Groups [lo, hi) with carry-INDEPENDENT fields only — exactly
        what the wave's fused group+pool buffer reads
        (ops/binpack.group_layout names no existing-bin field), so the
        pipelined planner can build wave k+1's upload before wave k's
        results exist. _wave_problem layers the carried bin state on
        top of this at the stage boundary."""
        sl = slice(lo, hi)
        return replace(
            problem,
            groups=problem.groups[sl], unschedulable={}, warnings=[],
            req=problem.req[sl], count=problem.count[sl],
            g_type=problem.g_type[sl], g_zone=problem.g_zone[sl],
            g_cap=problem.g_cap[sl], g_np=problem.g_np[sl],
            max_per_bin=problem.max_per_bin[sl],
            g_spread=problem.g_spread[sl], single_bin=problem.single_bin[sl],
            g_match=problem.g_match[sl], g_owner=problem.g_owner[sl],
            g_need=problem.g_need[sl], strict_custom=problem.strict_custom[sl])

    def _wave_problem(self, problem: Problem, lo: int, hi: int,
                      e_used: np.ndarray, e_pm: np.ndarray, e_po: np.ndarray,
                      pseudo_nodes: List[PlannedNode],
                      pseudo_used: List[np.ndarray], pseudo_np: List[int],
                      pseudo_pm: List[np.ndarray],
                      pseudo_po: List[np.ndarray]) -> Problem:
        """One wave's sub-problem: groups [lo, hi) plus the carried bin
        state — real existing bins at their RUNNING usage and every earlier
        wave's planned node as a fixed pre-initialized bin."""
        lat = self.lattice
        from .problem import ExistingBin
        existing = list(problem.existing)
        if pseudo_nodes:
            k = len(pseudo_nodes)
            p_type = np.array([lat.name_to_idx[n.instance_type]
                               for n in pseudo_nodes], np.int32)
            p_zone = np.array([lat.zones.index(n.zone)
                               for n in pseudo_nodes], np.int32)
            p_cap = np.array([lat.capacity_types.index(n.capacity_type)
                              for n in pseudo_nodes], np.int32)
            p_np = np.asarray(pseudo_np, np.int32)
            p_used = np.stack(pseudo_used).astype(np.float32)
            # a planned node's allocatable is its chosen type's, clamped by
            # its pool's kubelet ceiling — what the launch will deliver
            p_alloc = np.minimum(
                lat.alloc[p_type],
                problem.np_alloc_cap[p_np]).astype(np.float32)
            p_pm = (np.stack(pseudo_pm).astype(np.int32) if problem.A
                    else np.zeros((k, 0), np.int32))
            p_po = (np.stack(pseudo_po).astype(bool) if problem.A
                    else np.zeros((k, 0), bool))
            for i, n in enumerate(pseudo_nodes):
                existing.append(ExistingBin(
                    name=f"__wave:{i}__", node_pool=n.node_pool,
                    instance_type=n.instance_type, zone=n.zone,
                    capacity_type=n.capacity_type, used=p_used[i],
                    alloc_override=p_alloc[i]))
            e_used2 = np.concatenate([e_used, p_used])
            e_alloc2 = np.concatenate([problem.e_alloc, p_alloc])
            e_type2 = np.concatenate([problem.e_type, p_type])
            e_zone2 = np.concatenate([problem.e_zone, p_zone])
            e_cap2 = np.concatenate([problem.e_cap, p_cap])
            e_np2 = np.concatenate([problem.e_np, p_np])
            e_pm2 = np.concatenate([e_pm, p_pm])
            e_po2 = np.concatenate([e_po, p_po])
        else:
            e_used2, e_alloc2 = e_used, problem.e_alloc
            e_type2, e_zone2 = problem.e_type, problem.e_zone
            e_cap2, e_np2 = problem.e_cap, problem.e_np
            e_pm2, e_po2 = e_pm, e_po
        return replace(
            self._wave_slice(problem, lo, hi),
            existing=existing, e_used=e_used2, e_alloc=e_alloc2,
            e_type=e_type2, e_zone=e_zone2, e_cap=e_cap2, e_np=e_np2,
            e_pm=e_pm2, e_po=e_po2)

    # ---- host-FFD fallback (bottom rung of the ladder) ----

    def solve_host_ffd(self, problem: Problem) -> NodePlan:
        """Pure-host sequential FFD (solver/oracle.py — reference parity by
        construction) decoded into a NodePlan. No device dependency, no
        shape ceilings: the bottom rung of the degradation ladder, and the
        path of last resort when the device is unreachable entirely."""
        from .oracle import ffd_oracle
        t0 = time.perf_counter()
        plat = problem.lattice
        oracle = ffd_oracle(problem)
        existing_assignments: Dict[str, List[str]] = {}
        new_bins = []
        for b in oracle.bins:
            if not b.pods:
                continue
            if b.is_existing:
                existing_assignments.setdefault(
                    problem.existing[b.existing_idx].name, []).extend(b.pods)
            else:
                new_bins.append(b)
        nodes: List[PlannedNode] = []
        if new_bins:
            feasible = self._feasible_sets_batch(
                problem,
                np.stack([b.tmask for b in new_bins]),
                np.stack([b.zmask for b in new_bins]),
                np.stack([b.cmask for b in new_bins]))
            for b, (t, z, c), (ftypes, fzones, fcaps) in zip(
                    new_bins, oracle.chosen, feasible):
                pname, extra = _pool_out(problem.node_pools[b.np_idx])
                nodes.append(PlannedNode(
                    node_pool=pname, extra_labels=extra,
                    instance_type=plat.names[t], zone=plat.zones[z],
                    capacity_type=plat.capacity_types[c],
                    price_per_hour=float(plat.price[t, z, c]),
                    pods=list(b.pods),
                    feasible_types=ftypes, feasible_zones=fzones,
                    feasible_capacity_types=fcaps))
        return NodePlan(
            new_nodes=nodes, existing_assignments=existing_assignments,
            unschedulable=dict(oracle.unschedulable),
            new_node_cost=oracle.new_node_cost,
            solve_seconds=time.perf_counter() - t0, device_seconds=0.0,
            warnings=list(problem.warnings), solver_path="host-ffd")

    def _decode(self, problem: Problem, dec: _DecodeSet, device_s: float,
                prep: Optional[Dict[str, object]] = None) -> NodePlan:
        if prep is None:
            prep = self._decode_prep(problem)
        pool_out = prep["pool_out"]
        existing_names = prep["existing_names"]
        lat = self.lattice
        assign = dec.assign
        leftover = dec.leftover
        fixed = dec.fixed
        np_id = dec.np_id

        unschedulable = dict(problem.unschedulable)
        existing_assignments: Dict[str, List[str]] = {}
        new_bins: Dict[int, PlannedNode] = {}
        # batch the feasible-set computation over every new bin that
        # received pods (one vectorized pass instead of per-bin numpy)
        used = assign[: problem.G].sum(axis=0) > 0
        live_rows = np.nonzero(used & ~fixed)[0]
        feasible = self._feasible_sets_batch(
            problem,
            np.unpackbits(dec.tmask_p[live_rows], axis=1)[:, : lat.T].astype(bool),
            np.unpackbits(dec.zmask_p[live_rows], axis=1)[:, : lat.Z].astype(bool),
            np.unpackbits(dec.cmask_p[live_rows], axis=1)[:, : lat.C].astype(bool),
        )
        feasible_for = dict(zip(live_rows.tolist(), feasible))

        # hoist device-result arrays into Python lists once: per-bin
        # numpy scalar extraction (int(arr[b]) ×4 per new bin) is real
        # money at wave-narrowed plan sizes (6k+ bins)
        fixed_l = fixed.tolist()
        np_id_l = np_id.tolist()
        chosen_t = dec.chosen_t.tolist()
        chosen_z = dec.chosen_z.tolist()
        chosen_c = dec.chosen_c.tolist()
        chosen_price = dec.chosen_price.tolist()
        leftover_l = leftover.tolist()

        for gi, group in enumerate(problem.groups):
            names = group.pod_names
            cursor = 0
            row = assign[gi]
            bs = np.nonzero(row)[0]
            for b, n in zip(bs.tolist(), row[bs].tolist()):
                n = int(n)
                pod_slice = names[cursor: cursor + n]
                cursor += n
                if fixed_l[b]:
                    existing_assignments.setdefault(
                        existing_names[b], []).extend(pod_slice)
                else:
                    node = new_bins.get(b)
                    if node is None:
                        ftypes, fzones, fcaps = feasible_for[b]
                        pname, extra = pool_out[np_id_l[b]]
                        node = PlannedNode(
                            node_pool=pname, extra_labels=dict(extra),
                            instance_type=lat.names[chosen_t[b]],
                            zone=lat.zones[chosen_z[b]],
                            capacity_type=lat.capacity_types[chosen_c[b]],
                            price_per_hour=float(chosen_price[b]),
                            feasible_types=ftypes, feasible_zones=fzones,
                            feasible_capacity_types=fcaps,
                        )
                        new_bins[b] = node
                    node.pods.extend(pod_slice)
            if leftover_l[gi]:
                msg = unplaced_reason(group)
                for name in names[cursor: cursor + int(leftover_l[gi])]:
                    unschedulable[name] = msg

        new_nodes = [new_bins[b] for b in sorted(new_bins)]
        cost = float(sum(n.price_per_hour for n in new_nodes))
        return NodePlan(new_nodes=new_nodes, existing_assignments=existing_assignments,
                        unschedulable=unschedulable, new_node_cost=cost,
                        solve_seconds=0.0, device_seconds=device_s)

    def _feasible_sets(self, problem: Problem, tmask_row: np.ndarray,
                       zmask_row: np.ndarray, cmask_row: np.ndarray):
        """A bin's full feasible offering sets, cheapest-type-first (the
        CreateFleet-override flexibility list; reference instance.go:50)."""
        return self._feasible_sets_batch(
            problem, tmask_row[None], zmask_row[None], cmask_row[None])[0]

    def _feasible_sets_batch(self, problem: Problem, tm: np.ndarray,
                             zm: np.ndarray, cm: np.ndarray):
        """Vectorized feasible sets for L bins at once: [L,T],[L,Z],[L,C]
        masks → per-bin (types cheapest-first, zones, captypes) lists.

        Bins are bucketed by their FULL (type, zone, captype) mask
        pattern — a 50k-pod wave's ~1500 bins collapse to a handful of
        patterns (bins seeded by the same group share all three masks),
        so the T-wide price argsort runs once per pattern instead of once
        per bin (measured: 13 ms → <1 ms at 1486 bins). Same-pattern
        bins SHARE one result as immutable tuples: consumers reassign
        the fields (provisioning.py:382) but can never mutate a
        neighbor's copy, and the per-bin list materialization (~90k
        elements at 1500 bins) disappears from the decode budget."""
        lat = self.lattice
        L = tm.shape[0]
        if L == 0:
            return []
        avail_np = problem.lattice.available                  # [T,Z,C]
        p_all = np.where(avail_np, problem.lattice.price, np.inf)
        # two-level bucketing: the [T,nz,nc] price/availability reductions
        # run once per OUTER (zone,captype) pattern; the cheap T-wide
        # argsort + list build run once per inner type-mask variant
        outer: Dict[bytes, Dict[bytes, List[int]]] = {}
        for l in range(L):
            outer.setdefault(zm[l].tobytes() + cm[l].tobytes(), {})                  .setdefault(tm[l].tobytes(), []).append(l)
        out: List[tuple] = [None] * L                          # type: ignore[list-item]
        names, zone_names, cap_names = lat.names, lat.zones, lat.capacity_types
        for zc_groups in outer.values():
            first = next(iter(zc_groups.values()))[0]
            z, c = zm[first], cm[first]
            best = np.full(lat.T, np.inf)                      # [T]
            av_tz = np.zeros((lat.T, lat.Z), bool)
            av_tc = np.zeros((lat.T, lat.C), bool)
            if z.any() and c.any():
                sub = p_all[:, z][:, :, c]                     # [T,nz,nc]
                best = sub.min(axis=(1, 2))
                sub_av = avail_np[:, z][:, :, c]
                av_tz[:, z] = sub_av.any(axis=2)
                av_tc[:, c] = sub_av.any(axis=1)
            for idxs in zc_groups.values():
                t_mask = tm[idxs[0]]
                bpt = np.where(t_mask, best, np.inf)           # [T]
                # argsort puts inf (infeasible) types last, so the first
                # n_fin entries of order are exactly the feasible types
                order = np.argsort(bpt, kind="stable")
                nf = min(int(np.isfinite(bpt).sum()), MAX_FLEXIBLE_TYPES)
                shared = (
                    tuple(names[t] for t in order[:nf].tolist()),
                    tuple(zone_names[zi]
                          for zi, v in enumerate(t_mask @ av_tz) if v),
                    tuple(cap_names[ci]
                          for ci, v in enumerate(t_mask @ av_tc) if v),
                )
                for l in idxs:
                    out[l] = shared
        return out

    # ---- pod-axis sharded solve (multi-chip path) ----
    #
    # The reference scales its one-core Go FFD loop with batch windows; here
    # the 50k-pod axis shards over a device mesh: each shard packs its slice
    # of every group locally (parallel/sharded.py), psum/all-stack collectives
    # reduce the results, and a host-side refinement dissolves under-filled
    # tail bins (at most one per group per shard) back into one small
    # single-device merge solve. Net: D-way scan parallelism with a merge
    # whose size is O(groups x shards), independent of pod count.

    MERGE_FILL_THRESHOLD = 0.85  # dissolve new bins filled below this fraction

    def _solve_sharded(self, problem: Problem, mesh, t0: float,
                       gbuf=None, overlap=None) -> NodePlan:
        """The mesh production path: pod-axis sharded pack + tail-bin
        merge, with the SAME pipelining contract as the single-device
        solve — fused inputs ride the resident delta cache (keyed by
        device count, so a mesh-shape change can never delta-hit stale
        shards), the result fetch streams out during host work, and the
        wave planner's ``overlap`` hook runs inside the sharded compute
        window. ``gbuf`` is an already-uploaded (replicated) fused
        group+pool buffer from the wave prefetch."""
        from ..parallel.sharded import (replicated_sharding, shard_groups,
                                        sharded_pack, split_counts)

        D = int(mesh.devices.size)
        pipelined = self.pipeline
        stages = StageTimer()
        G = _bucket(problem.G, _G_BUCKETS)
        B = self._b_budget_sharded(problem, D)

        repl = replicated_sharding(mesh) if pipelined else None
        if gbuf is None:
            with stages.span("build"):
                fused_np = self._fused_inputs_np(problem, G)
            with stages.span("upload"):
                # ("g", D, G, size) is the mesh-resident whole-problem
                # entry: a steady-state delta pass block-diffs against it
                # and ships only dirty group rows over the host link; the
                # replicated sharding keeps unchanged bytes resident on
                # every shard (solve_delta counts hit/miss)
                if pipelined:
                    gbuf = self._resident.upload(("g", D, G, fused_np.size),
                                                 fused_np, sharding=repl)
                else:
                    gbuf = jnp.asarray(fused_np)
                    self._account_link("upload", fused_np.nbytes)
        alloc_r, avail, price = self._mesh_inputs(problem, mesh)

        count_pad = np.zeros((G,), np.int32)
        count_pad[: problem.G] = problem.count
        pin = np.zeros((G,), bool)
        keep = np.zeros((G,), bool)
        if problem.A:
            pin[: problem.G] = problem.g_need.any(axis=1)
        keep[: problem.G] = problem.single_bin
        keep |= pin
        count_split = split_counts(count_pad, D, keep_whole=keep, pin_shard0=pin)
        # per-shard load balance of this split (max/mean; the
        # karpenter_solver_shard_imbalance_ratio gauge reads it)
        load = shard_groups(count_split).astype(np.float64)
        self._mesh_imbalance = (float(load.max() / load.mean())
                                if load.mean() > 0 else 1.0)

        lat = self.lattice
        A = max(problem.A, 1)
        NP = max(problem.NP, 1)
        overlap_pending = overlap
        while True:
            init_buf = None
            if problem.E:
                with stages.span("build"):
                    init_np = self._fused_init_np(problem, B)
                with stages.span("upload"):
                    if pipelined:
                        init_buf = self._resident.upload(
                            ("i", D, B, init_np.size), init_np,
                            sharding=repl)
                    else:
                        init_buf = jnp.asarray(init_np)
                        self._account_link("upload", init_np.nbytes)
            self._maybe_inject_device_fault()
            compute_ms0 = stages.ms.get("compute", 0.0)
            td = time.perf_counter()
            try:
                with self._trace_span("solver.pack_sharded"):
                    with stages.span("compute"):
                        # the [D,G] split ships from host here (the
                        # microloop derives it on device instead —
                        # parallel/sharded.py device_split_counts)
                        self._account_link("upload", count_split.nbytes)
                        sp = sharded_pack(mesh, alloc_r, avail, price, gbuf,
                                          init_buf, problem.E, count_split,
                                          B, G, lat.T, lat.Z, lat.C, NP, A)
                if pipelined:
                    # stream the stacked per-shard result out the moment
                    # the collective finishes; host overlap work below
                    # fills the wait
                    fetch_async(sp.packed)
            except SolverError:
                raise
            except Exception as e:
                raise SolverDeviceError(
                    f"{type(e).__name__}: {e}", cause=e) from e
            # host-side overlap OUTSIDE the device-error wrap, exactly
            # like the single-device path: wave k+1's input build must
            # classify as internal-error, never as device weather
            if overlap_pending is not None:
                overlap_pending()
                overlap_pending = None
            try:
                with stages.span("download"):
                    # one fused [D,B+n,W] buffer = one device→host
                    # transfer for all shards (sync included); host-side
                    # unpack stays off the device clock
                    packed = np.asarray(sp.packed)
                    self._account_link("fetch", packed.nbytes)
            except SolverError:
                raise
            except Exception as e:
                raise SolverDeviceError(
                    f"{type(e).__name__}: {e}", cause=e) from e
            device_s = time.perf_counter() - td
            with stages.span("decode"):
                decs = [_unpack_decode_set(packed[d], G, lat.T, lat.Z,
                                           lat.C, A)
                        for d in range(packed.shape[0])]
            leftover = np.stack([dec.leftover for dec in decs])           # [D,G]
            next_open = np.array([dec.next_open for dec in decs])          # [D]
            overflowed = bool(((leftover.sum(axis=1) > 0) & (next_open >= B)).any())
            if overflowed:
                nb, grew = _grow_bucket(B)
                if grew and nb <= self._b_ceiling():
                    B = nb
                    continue
                # same exhaustion contract as the single-device path: the
                # ladder degrades to host FFD rather than decoding a plan
                # that drops the spilled pods
                if self.faults is not None and self.faults.b_limit:
                    self.faults.note("b_exhausted")
                raise SolverCapacityError(
                    f"sharded bin table exhausted at B={B} with "
                    f"{int(leftover.sum())} pod(s) left over", axis="B")
            break

        with stages.span("decode"):
            plan = self._decode_sharded(problem, sp, decs, count_split,
                                        device_s)
        plan.solve_seconds = time.perf_counter() - t0
        plan.warnings = list(problem.warnings)
        plan.stage_ms = stages.ms
        plan.pipelined = pipelined
        plan.mesh_devices = D
        plan.shard_imbalance = self._mesh_imbalance
        # the mesh-compiled executable gets its OWN cost-model entry:
        # shape_key carries the device count, so a sharded solve can
        # never pollute the single-device (G,B) bucket's
        # best-demonstrated baseline (or vice versa)
        costmodel.model().observe_solve(
            costmodel.shape_key(G, B, mesh_devices=D),
            stages.ms.get("compute", 0.0) - compute_ms0)
        self.pipeline_stats["mesh_solves"] += 1
        if pipelined:
            self.pipeline_stats["async_solves"] += 1
        return plan

    def _stacked_masks(self, decs: List[_DecodeSet], items: List[Tuple[int, int]]):
        """Unpack the (shard, bin) rows in ``items`` into stacked [L,T]/[L,Z]/
        [L,C] boolean masks — one unpackbits per shard, not per bin."""
        lat = self.lattice
        by_shard: Dict[int, List[int]] = {}
        for i, (d, _b) in enumerate(items):
            by_shard.setdefault(d, []).append(i)
        tm = np.zeros((len(items), lat.T), bool)
        zm = np.zeros((len(items), lat.Z), bool)
        cm = np.zeros((len(items), lat.C), bool)
        for d, idxs in by_shard.items():
            rows = np.array([items[i][1] for i in idxs])
            tm[idxs] = decs[d].tmask(rows, lat.T)
            zm[idxs] = decs[d].zmask(rows, lat.Z)
            cm[idxs] = decs[d].cmask(rows, lat.C)
        return tm, zm, cm

    def _decode_sharded(self, problem: Problem, sp, decs: List[_DecodeSet],
                        count_split: np.ndarray, device_s: float,
                        merge_ctx: Optional[Dict] = None) -> NodePlan:
        lat = self.lattice
        D = count_split.shape[0]

        # -- walk each group's contiguous per-shard name slices through the
        # per-shard bin tables (same cursor decode as single-device)
        bins_content: Dict[Tuple[int, int], List[Tuple[int, List[str]]]] = {}
        spill_names: Dict[int, List[str]] = {}    # group idx -> no shard placed
        unschedulable = dict(problem.unschedulable)
        existing_assignments: Dict[str, List[str]] = {}
        for gi, group in enumerate(problem.groups):
            names = group.pod_names
            start = 0
            for d in range(D):
                share = int(count_split[d, gi])
                shard_names = names[start: start + share]
                start += share
                cursor = 0
                for b in np.nonzero(decs[d].assign[gi])[0]:
                    n = int(decs[d].assign[gi, b])
                    bins_content.setdefault((d, int(b)), []).append(
                        (gi, shard_names[cursor: cursor + n]))
                    cursor += n
                # a shard's leftover gets a second chance in the merge solve
                # (other shards' bins / existing capacity may still hold it)
                spill = shard_names[cursor: cursor + int(decs[d].leftover[gi])]
                if spill:
                    spill_names.setdefault(gi, []).extend(spill)

        # -- classify bins: existing (fixed, shard 0), kept new, dissolved
        kept: List[Tuple[int, int, List[Tuple[int, List[str]]]]] = []
        tail_names: Dict[int, List[str]] = {gi: list(v) for gi, v in spill_names.items()}
        for (d, b), content in sorted(bins_content.items()):
            if decs[d].fixed[b]:
                name = problem.existing[b].name
                for _, pod_names in content:
                    existing_assignments.setdefault(name, []).extend(pod_names)
                continue
            alloc_t = lat.alloc[int(decs[d].chosen_t[b])]
            with np.errstate(divide="ignore", invalid="ignore"):
                frac = np.where(alloc_t > 0, decs[d].cum[b] / alloc_t, 0.0)
            if float(np.max(frac, initial=0.0)) < self.MERGE_FILL_THRESHOLD:
                for gi, pod_names in content:
                    tail_names.setdefault(gi, []).extend(pod_names)
            else:
                kept.append((d, b, content))

        def raw_plan() -> NodePlan:
            """No-merge fallback: every new bin becomes a node as packed;
            spilled pods (no shard placed them) go unschedulable."""
            nodes: List[PlannedNode] = []
            assigns = {k: list(v) for k, v in existing_assignments.items()}
            unsched = dict(unschedulable)
            new_entries = [(db, content) for db, content in sorted(bins_content.items())
                           if not decs[db[0]].fixed[db[1]]]
            tm, zm, cm = self._stacked_masks(decs, [db for db, _ in new_entries])
            feasible = self._feasible_sets_batch(problem, tm, zm, cm)
            for ((d, b), content), (ftypes, fzones, fcaps) in zip(new_entries, feasible):
                dec = decs[d]
                pname, extra = _pool_out(problem.node_pools[int(dec.np_id[b])])
                node = PlannedNode(
                    node_pool=pname, extra_labels=extra,
                    instance_type=lat.names[int(dec.chosen_t[b])],
                    zone=lat.zones[int(dec.chosen_z[b])],
                    capacity_type=lat.capacity_types[int(dec.chosen_c[b])],
                    price_per_hour=float(dec.chosen_price[b]),
                    feasible_types=ftypes, feasible_zones=fzones,
                    feasible_capacity_types=fcaps,
                )
                for _, pod_names in content:
                    node.pods.extend(pod_names)
                nodes.append(node)
            for gi, pool in spill_names.items():
                msg = unplaced_reason(problem.groups[gi])
                for name in pool:
                    unsched[name] = msg
            cost = float(sum(n.price_per_hour for n in nodes))
            return NodePlan(new_nodes=nodes, existing_assignments=assigns,
                            unschedulable=unsched, new_node_cost=cost,
                            solve_seconds=0.0, device_seconds=device_s)

        if not tail_names:
            return raw_plan()

        merged = self._merge_solve(problem, decs, kept, tail_names,
                                   existing_assignments, unschedulable,
                                   device_s, merge_ctx=merge_ctx)
        # the merge is a refinement: take it when it schedules at least as
        # many pods and does not raise cost; otherwise keep the raw packing.
        # Compare on aggregates (total_cost is the psum'd live-bin price sum,
        # identical to raw_plan's cost) so the raw decode only materializes
        # when it actually wins.
        raw_unsched = len(unschedulable) + sum(len(v) for v in spill_names.values())
        raw_cost = float(sp.total_cost)
        if (len(merged.unschedulable) < raw_unsched
                or (len(merged.unschedulable) == raw_unsched
                    and merged.new_node_cost <= raw_cost + 1e-6)):
            return merged
        return raw_plan()

    def _merge_solve(self, problem: Problem, decs: List[_DecodeSet], kept,
                     tail_names, existing_assignments: Dict[str, List[str]],
                     unschedulable: Dict[str, str], device_s: float,
                     merge_ctx: Optional[Dict] = None):
        """Re-pack dissolved tail bins + spilled pods in one single-device
        refinement solve seeded with existing bins (fixed) and kept bins
        (open, re-priced at finalization for maximum offering flexibility).

        The merge-count group buffer AND the seeded bin table ride ONE
        fused upload (ops/binpack.py pack_packed_seeded) — the per-array
        BinState staging this replaces paid eleven link legs per merge.
        ``merge_ctx`` is the microloop's retention seam: ``reuse``
        (result bytes, B2) skips the device round trip entirely on a
        fingerprint-unchanged pass (identical shard results ⇒ identical
        merge inputs ⇒ identical merge result — only the pod NAMES
        decode differently); ``ran``/``result`` hand the fresh result
        back for the next pass's reuse."""
        lat = self.lattice
        E = problem.E
        K = len(kept)
        G = _bucket(problem.G, _G_BUCKETS)
        A = max(problem.A, 1)

        merge_count = np.zeros((G,), np.int32)
        for gi, pool in tail_names.items():
            merge_count[gi] = len(pool)
        tail_total = int(merge_count.sum())
        # bin budget honors per-bin caps (hostname spread / anti-affinity can
        # force one bin per pod) — same formula as the single-device solve
        caps = np.minimum(problem.max_per_bin.astype(np.int64),
                          np.maximum(merge_count[: problem.G].astype(np.int64), 1))
        capped_bins = int(np.ceil(merge_count[: problem.G] / np.maximum(caps, 1)).sum())
        b_needed = E + K + min(tail_total, capped_bins + 64)
        B2 = _bucket(b_needed, _B_BUCKETS, clamp=True)

        reuse = merge_ctx.get("reuse") if merge_ctx else None
        if reuse is not None:
            buf, B2 = reuse
            mdec = _unpack_decode_set(buf, G, lat.T, lat.Z, lat.C, A,
                                      lean=True)
            leftover2 = mdec.leftover
            merge_ctx["reused"] = True
        else:
            fused_np = self._fused_inputs_np(problem, G,
                                             count_override=merge_count)
            avail, price = self._device_avail_price(problem)
            k_tm, k_zm, k_cm = self._stacked_masks(
                decs, [(d, b) for d, b, _ in kept])

            while True:
                seed_np = self._merge_seed_np(problem, decs, kept, B2,
                                              k_tm, k_zm, k_cm)
                combined = np.concatenate([fused_np, seed_np])
                td = time.perf_counter()
                comb_dev = jnp.asarray(combined)
                self._account_link("upload", combined.nbytes)
                buf = np.asarray(binpack.pack_packed_seeded(
                    self._alloc, avail, price, comb_dev, int(fused_np.size),
                    B2, G, lat.T, lat.Z, lat.C, max(problem.NP, 1), A,
                    lean=True))
                self._account_link("fetch", buf.nbytes)
                device_s += time.perf_counter() - td
                mdec = _unpack_decode_set(buf, G, lat.T, lat.Z, lat.C, A,
                                          lean=True)
                leftover2 = mdec.leftover
                overflowed = (leftover2.sum() > 0) and mdec.next_open >= B2
                if overflowed:
                    B2, grew = _grow_bucket(B2)
                    if grew:
                        # the retry re-stages and re-fetches: 2 more
                        # accounted legs, excused from the per-pass
                        # bound via this counter
                        self.pipeline_stats["micro_merge_regrows"] += 1
                        continue
                break
            if merge_ctx is not None:
                merge_ctx["ran"] = True
                merge_ctx["result"] = (buf, B2)

        # -- decode the merged table
        assign2 = mdec.assign
        return self._merge_decode(problem, mdec, leftover2, assign2, kept,
                                  tail_names, existing_assignments,
                                  unschedulable, device_s)

    def _merge_seed_np(self, problem: Problem, decs: List[_DecodeSet],
                       kept, B2: int, k_tm: np.ndarray, k_zm: np.ndarray,
                       k_cm: np.ndarray) -> np.ndarray:
        """The merge's seeded bin table as ONE host uint8 buffer
        (ops/binpack.seed_layout): rows [0,E) are the existing bins at
        their post-pack shard-0 state (fixed), rows [E,E+K) the kept new
        bins from all shards (open, re-priced at finalization). Values
        are bit-exact with the per-array staging this replaced."""
        lat = self.lattice
        E = problem.E
        K = len(kept)
        layout, total = binpack.seed_layout(B2, lat.T, lat.Z, lat.C, R,
                                            max(problem.A, 1))
        buf = np.zeros((total,), np.uint8)
        v: Dict[str, np.ndarray] = {}
        for f in layout:
            n = int(np.prod(f.shape)) * np.dtype(f.dtype).itemsize
            view = buf[f.offset: f.offset + n].view(f.dtype).reshape(f.shape)
            if f.fill != 0:
                view.fill(f.fill)
            v[f.name] = view
        if E:
            d0 = decs[0]
            e_rows = np.arange(E)
            v["s_cum"][:E] = d0.cum[:E]
            v["s_tmask"][:E] = d0.tmask(e_rows, lat.T)
            v["s_zmask"][:E] = d0.zmask(e_rows, lat.Z)
            v["s_cmask"][:E] = d0.cmask(e_rows, lat.C)
            v["s_np"][:E] = d0.np_id[:E]
            v["s_npods"][:E] = d0.npods[:E]
            v["s_open"][:E] = 1
            v["s_fixed"][:E] = 1
            v["s_alloc"][:E] = d0.alloc_cap[:E]
            v["s_pm"][:E] = d0.pm[:E]
            v["s_po"][:E] = d0.po[:E]
        for i, (d, b, _content) in enumerate(kept):
            r = E + i
            dec = decs[d]
            v["s_cum"][r] = dec.cum[b]
            v["s_tmask"][r] = k_tm[i]
            v["s_zmask"][r] = k_zm[i]
            v["s_cmask"][r] = k_cm[i]
            v["s_np"][r] = dec.np_id[b]
            v["s_npods"][r] = dec.npods[b]
            v["s_open"][r] = 1
            v["s_pm"][r] = dec.pm[b]
            v["s_po"][r] = dec.po[b]
        v["s_next"][0] = E + K
        return buf

    def _merge_decode(self, problem: Problem, mdec: _DecodeSet,
                      leftover2: np.ndarray, assign2: np.ndarray, kept,
                      tail_names, existing_assignments: Dict[str, List[str]],
                      unschedulable: Dict[str, str],
                      device_s: float) -> NodePlan:
        """Decode the merged table into the refinement NodePlan."""
        lat = self.lattice
        E = problem.E
        m_np_id = mdec.np_id
        m_ct = mdec.chosen_t
        m_cz = mdec.chosen_z
        m_cc = mdec.chosen_c
        m_cp = mdec.chosen_price
        m_open = mdec.open
        m_fixed = mdec.fixed

        assigns = {k: list(v) for k, v in existing_assignments.items()}
        unsched = dict(unschedulable)
        node_for_row: Dict[int, PlannedNode] = {}

        def node_at(row: int) -> PlannedNode:
            node = node_for_row.get(row)
            if node is None:
                # masks unpack per materialized node only — B2 can be
                # thousands of rows with a handful of live merge bins
                rows1 = np.array([row])
                ftypes, fzones, fcaps = self._feasible_sets(
                    problem, mdec.tmask(rows1, lat.T)[0],
                    mdec.zmask(rows1, lat.Z)[0], mdec.cmask(rows1, lat.C)[0])
                pname, extra = _pool_out(problem.node_pools[int(m_np_id[row])])
                node = PlannedNode(
                    node_pool=pname, extra_labels=extra,
                    instance_type=lat.names[int(m_ct[row])],
                    zone=lat.zones[int(m_cz[row])],
                    capacity_type=lat.capacity_types[int(m_cc[row])],
                    price_per_hour=float(m_cp[row]),
                    feasible_types=ftypes, feasible_zones=fzones,
                    feasible_capacity_types=fcaps,
                )
                node_for_row[row] = node
            return node

        # kept bins keep their original pods even if the merge adds none
        for i, (_d, _b, content) in enumerate(kept):
            node = node_at(E + i)
            for _gi, pod_names in content:
                node.pods.extend(pod_names)

        for gi in range(problem.G):
            pool = tail_names.get(gi, [])
            if not pool:
                continue
            cursor = 0
            for b in np.nonzero(assign2[gi])[0]:
                n = int(assign2[gi, b])
                pod_slice = pool[cursor: cursor + n]
                cursor += n
                if m_fixed[b]:
                    assigns.setdefault(problem.existing[b].name, []).extend(pod_slice)
                else:
                    node_at(int(b)).pods.extend(pod_slice)
            if int(leftover2[gi]):
                msg = unplaced_reason(problem.groups[gi])
                for name in pool[cursor: cursor + int(leftover2[gi])]:
                    unsched[name] = msg

        # any remaining open new bin that took merge pods (kept bins already
        # materialized above; the lean buffer has no npods, but merge-added
        # pods are exactly the assign2 columns)
        live_rows = np.nonzero(m_open & ~m_fixed
                               & (assign2[: problem.G].sum(axis=0) > 0))[0]
        for row in live_rows:
            node_at(int(row))
        new_nodes = [node_for_row[r] for r in sorted(node_for_row)
                     if node_for_row[r].pods]
        cost = float(sum(n.price_per_hour for n in new_nodes))
        return NodePlan(new_nodes=new_nodes, existing_assignments=assigns,
                        unschedulable=unsched, new_node_cost=cost,
                        solve_seconds=0.0, device_seconds=device_s)
