"""Host-facing Solve() API.

Wraps the device kernel (ops/binpack.py) with the host plumbing the
reference spreads across its provisioner loop:

- shape bucketing + padding (jit compiles once per bucket; wildly varying
  pod counts hit a small, warm set of compiled shapes),
- bin-table overflow retry with the next bucket size,
- NodePlan decoding: bin table + assignment matrix → named NodeClaims-to-be
  (instance type, zone, capacity type, price, pod list per node), existing
  node assignments, and per-pod unschedulable reasons.

The decoded NodePlan is what the provisioning controller turns into
NodeClaims and hands to the CloudProvider (the reference's scheduler →
NodeClaim → Create() flow, SURVEY.md §3.2).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..apis.resources import R
from ..lattice.tensors import Lattice
from ..ops import binpack
from .problem import Problem

_G_BUCKETS = (16, 64, 256, 1024, 4096)
_B_BUCKETS = (32, 128, 512, 2048, 8192)


@dataclass
class PlannedNode:
    node_pool: str
    instance_type: str
    zone: str
    capacity_type: str
    price_per_hour: float
    pods: List[str] = field(default_factory=list)
    # the bin's full feasible sets (every instance type that can hold the
    # bin's contents, cheapest-first, capped at MAX_FLEXIBLE_TYPES): the
    # launch path hands these to the cloud as CreateFleet overrides so an
    # ICE on the chosen offering falls through to the next-cheapest without
    # a re-solve (reference instance.go MaxInstanceTypes=60)
    feasible_types: List[str] = field(default_factory=list)
    feasible_zones: List[str] = field(default_factory=list)
    feasible_capacity_types: List[str] = field(default_factory=list)


MAX_FLEXIBLE_TYPES = 60  # reference pkg/providers/instance/instance.go:50


@dataclass
class NodePlan:
    new_nodes: List[PlannedNode]
    existing_assignments: Dict[str, List[str]]   # existing node name -> pods
    unschedulable: Dict[str, str]                # pod name -> reason
    new_node_cost: float                         # $/hr
    solve_seconds: float
    device_seconds: float
    warnings: List[str] = field(default_factory=list)

    @property
    def num_new_nodes(self) -> int:
        return len(self.new_nodes)


def _bucket(n: int, buckets: Sequence[int], clamp: bool = False) -> int:
    for b in buckets:
        if n <= b:
            return b
    if clamp:
        # degrade gracefully: the kernel's overflow path marks what doesn't
        # fit as leftover-unschedulable rather than crashing the solve
        return buckets[-1]
    raise ValueError(f"problem size {n} exceeds the largest bucket {buckets[-1]}")


class Solver:
    """Holds the lattice resident on device; solves padded problems."""

    def __init__(self, lattice: Lattice):
        self.lattice = lattice
        self._alloc = jnp.asarray(lattice.alloc)
        self._avail = jnp.asarray(lattice.available)
        self._price = jnp.asarray(lattice.price)
        self._price_version = lattice.price_version

    def _device_avail_price(self, problem: Problem):
        """A problem built over a masked lattice view (ICE cache applied,
        state/unavailable.py) brings its own availability; shapes match, so
        the jitted kernel is reused without recompilation."""
        if problem.lattice is self.lattice:
            if self.lattice.price_version != self._price_version:
                # pricing refresh rewrote the tensor in place: re-upload
                self._price = jnp.asarray(self.lattice.price)
                self._price_version = self.lattice.price_version
            return self._avail, self._price
        return jnp.asarray(problem.lattice.available), jnp.asarray(problem.lattice.price)

    # ---- padding ----

    def _padded_groups(self, problem: Problem, G: int) -> binpack.GroupBatch:
        lat = self.lattice
        A = max(problem.A, 1)

        def pad(a: np.ndarray, shape, dtype, fill=0):
            out = np.full(shape, fill, dtype)
            if a.size:
                out[tuple(slice(0, s) for s in a.shape)] = a
            return jnp.asarray(out)

        g = problem
        return binpack.GroupBatch(
            req=pad(g.req, (G, R), np.float32),
            count=pad(g.count, (G,), np.int32),
            g_type=pad(g.g_type, (G, lat.T), bool),
            g_zone=pad(g.g_zone, (G, lat.Z), bool),
            g_cap=pad(g.g_cap, (G, lat.C), bool),
            g_np=pad(g.g_np, (G, max(g.NP, 1)), bool),
            max_per_bin=pad(g.max_per_bin, (G,), np.int32),
            spread_class=pad(g.g_spread, (G,), np.int32, fill=-1),
            single_bin=pad(g.single_bin, (G,), bool),
            match=pad(g.g_match, (G, A), bool),
            owner=pad(g.g_owner, (G, A), bool),
            need=pad(g.g_need, (G, A), bool),
            strict_custom=pad(g.strict_custom, (G,), bool),
        )

    def _pool_params(self, problem: Problem) -> binpack.PoolParams:
        NP = max(problem.NP, 1)
        lat = self.lattice

        def fit(a, shape, dtype):
            out = np.zeros(shape, dtype)
            if a.size:
                out[: a.shape[0]] = a
            return jnp.asarray(out)

        return binpack.PoolParams(
            np_type=fit(problem.np_type, (NP, lat.T), bool),
            np_zone=fit(problem.np_zone, (NP, lat.Z), bool),
            np_cap=fit(problem.np_cap, (NP, lat.C), bool),
            ds=fit(problem.ds_overhead, (NP, R), np.float32),
        )

    def _init_state(self, problem: Problem, B: int) -> binpack.BinState:
        lat = self.lattice
        E = problem.E
        A = max(problem.A, 1)
        state = binpack.empty_state(B, lat.T, lat.Z, lat.C, R, A)
        if E == 0:
            return state
        cum = np.zeros((B, R), np.float32)
        tmask = np.zeros((B, lat.T), bool)
        zmask = np.zeros((B, lat.Z), bool)
        cmask = np.zeros((B, lat.C), bool)
        np_id = np.full((B,), -1, np.int32)
        open_ = np.zeros((B,), bool)
        fixed = np.zeros((B,), bool)
        alloc_cap = np.full((B, R), np.inf, np.float32)
        pm = np.zeros((B, A), np.int32)
        po = np.zeros((B, A), bool)
        cum[:E] = problem.e_used
        tmask[np.arange(E), problem.e_type] = True
        zmask[np.arange(E), problem.e_zone] = True
        cmask[np.arange(E), problem.e_cap] = True
        np_id[:E] = problem.e_np
        open_[:E] = True
        fixed[:E] = True
        alloc_cap[:E] = problem.e_alloc  # real node allocatable wins over lattice
        if problem.A:
            pm[:E, : problem.A] = problem.e_pm
            po[:E, : problem.A] = problem.e_po
        return binpack.BinState(
            cum=jnp.asarray(cum), tmask=jnp.asarray(tmask), zmask=jnp.asarray(zmask),
            cmask=jnp.asarray(cmask), np_id=jnp.asarray(np_id),
            npods=jnp.zeros((B,), jnp.int32), open=jnp.asarray(open_),
            fixed=jnp.asarray(fixed), alloc_cap=jnp.asarray(alloc_cap),
            pm=jnp.asarray(pm), po=jnp.asarray(po),
            next_open=jnp.array(E, jnp.int32),
        )

    # ---- solve ----

    def solve(self, problem: Problem) -> NodePlan:
        t0 = time.perf_counter()
        if problem.G == 0:
            return NodePlan([], {}, dict(problem.unschedulable), 0.0,
                            time.perf_counter() - t0, 0.0)
        G = _bucket(problem.G, _G_BUCKETS)
        total_pods = int(problem.count.sum())
        # bins needed ≈ one per group plus the per-bin-capped tail (hostname
        # spread / anti-affinity forces ~count/max_per_bin bins per group);
        # the overflow retry below corrects underestimates
        caps = np.minimum(problem.max_per_bin.astype(np.int64),
                          np.maximum(problem.count.astype(np.int64), 1))
        capped_bins = int(np.ceil(problem.count / np.maximum(caps, 1)).sum()) if problem.G else 0
        b_needed = problem.E + min(total_pods, capped_bins + 64)
        B = _bucket(max(b_needed, problem.E + 1), _B_BUCKETS, clamp=True)

        groups = self._padded_groups(problem, G)
        pools = self._pool_params(problem)
        avail, price = self._device_avail_price(problem)

        while True:
            init = self._init_state(problem, B)
            td = time.perf_counter()
            result = binpack.pack(self._alloc, avail, price, groups, pools, init)
            result.assign.block_until_ready()
            device_s = time.perf_counter() - td
            leftover = np.asarray(result.leftover)
            overflowed = (leftover.sum() > 0) and int(result.state.next_open) >= B
            if overflowed and B < _B_BUCKETS[-1]:
                B = _B_BUCKETS[min(_B_BUCKETS.index(B) + 1, len(_B_BUCKETS) - 1)]
                continue
            break

        plan = self._decode(problem, result, device_s)
        plan.solve_seconds = time.perf_counter() - t0
        plan.warnings = list(problem.warnings)
        return plan

    def _decode(self, problem: Problem, result: binpack.PackResult, device_s: float) -> NodePlan:
        lat = self.lattice
        assign = np.asarray(result.assign)          # [G,B]
        leftover = np.asarray(result.leftover)      # [G]
        npods = np.asarray(result.state.npods)
        open_ = np.asarray(result.state.open)
        fixed = np.asarray(result.state.fixed)
        np_id = np.asarray(result.state.np_id)
        chosen_t = np.asarray(result.chosen_t)
        chosen_z = np.asarray(result.chosen_z)
        chosen_c = np.asarray(result.chosen_c)
        chosen_price = np.asarray(result.chosen_price)

        unschedulable = dict(problem.unschedulable)
        existing_assignments: Dict[str, List[str]] = {}
        new_bins: Dict[int, PlannedNode] = {}
        tmask_all = np.asarray(result.state.tmask)
        zmask_all = np.asarray(result.state.zmask)
        cmask_all = np.asarray(result.state.cmask)
        avail_np = problem.lattice.available
        price_np = problem.lattice.price

        def feasible_sets(b: int):
            offer = (avail_np & tmask_all[b][:, None, None]
                     & zmask_all[b][None, :, None] & cmask_all[b][None, None, :])
            p = np.where(offer, price_np, np.inf)
            best_per_type = p.min(axis=(1, 2))
            order = np.argsort(best_per_type, kind="stable")
            types = [lat.names[t] for t in order
                     if np.isfinite(best_per_type[t])][:MAX_FLEXIBLE_TYPES]
            zones = [lat.zones[z] for z in np.nonzero(offer.any(axis=(0, 2)))[0]]
            caps = [lat.capacity_types[c] for c in np.nonzero(offer.any(axis=(0, 1)))[0]]
            return types, zones, caps

        for gi, group in enumerate(problem.groups):
            names = group.pod_names
            cursor = 0
            for b in np.nonzero(assign[gi])[0]:
                n = int(assign[gi, b])
                pod_slice = names[cursor: cursor + n]
                cursor += n
                if fixed[b]:
                    existing_assignments.setdefault(problem.existing[b].name, []).extend(pod_slice)
                else:
                    node = new_bins.get(int(b))
                    if node is None:
                        t, z, c = int(chosen_t[b]), int(chosen_z[b]), int(chosen_c[b])
                        ftypes, fzones, fcaps = feasible_sets(int(b))
                        node = PlannedNode(
                            node_pool=problem.node_pools[int(np_id[b])].name,
                            instance_type=lat.names[t], zone=lat.zones[z],
                            capacity_type=lat.capacity_types[c],
                            price_per_hour=float(chosen_price[b]),
                            feasible_types=ftypes, feasible_zones=fzones,
                            feasible_capacity_types=fcaps,
                        )
                        new_bins[int(b)] = node
                    node.pods.extend(pod_slice)
            for name in names[cursor: cursor + int(leftover[gi])]:
                unschedulable[name] = "does not fit any existing node or new-node shape"

        new_nodes = [new_bins[b] for b in sorted(new_bins)]
        cost = float(sum(n.price_per_hour for n in new_nodes))
        return NodePlan(new_nodes=new_nodes, existing_assignments=existing_assignments,
                        unschedulable=unschedulable, new_node_cost=cost,
                        solve_seconds=0.0, device_seconds=device_s)
