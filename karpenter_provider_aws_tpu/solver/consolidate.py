"""The vmapped consolidation engine (docs/reference/consolidation.md).

Consolidation's search — "remove candidate set S: do its pods fit on the
remaining capacity plus at most one new, cheaper node?" — is a batch of
what-if re-solves over one shared cluster problem. This module makes
that batch a first-class solver workload around the existing vmapped
probe kernel (`Solver.probe_batch` / ops/binpack.pack_probe_fused):

- **dirty-block deltas**: every candidate removal set is expressed as a
  delta against the resident cluster problem — the set's bins masked
  out of the existing-bin table, its evictee pods re-entering as pending
  groups — and the whole candidate batch rides ONE vmapped dispatch
  over the candidate axis.
- **zero-leg cache**: probe verdicts are cached per candidate set and
  invalidated through the cluster mirror's journal-tagged bin names
  (state/cluster.py DirtySet.bin_names). A pass whose base problem did
  not move (pending-pod churn only, pure candidate-frontier drift)
  serves fingerprint-unchanged candidates from the cache at ZERO device
  sync legs; an unlocalizable mutation clears the cache — the
  always-correct fallback, never a silently-stale verdict.
- **host fallback, counted**: candidate problems outside the vmapped
  envelope (wave-scale G past the solver's compiled bucket ceiling,
  pinned/co-located groups on a >1-device mesh) are flagged for the
  controller's existing exact `_what_if` ladder instead of the batch,
  and counted — the same honesty rule the microloop's `micro_aborts`
  follows.
- **savings referee**: an accepted removal must beat the host FFD
  oracle's costing of the same what-if within the ≤2% envelope
  (`REFEREE_ENVELOPE`) — the device plan may never ride a decode bug
  into a "saving" the reference packer would not certify.
- **coded skip reasons**: every node NOT consolidated gets a
  solver/taxonomy.py code (not-consolidatable-pdb | -budget |
  consolidation-no-savings | -weather-hold | -spot-guard) recorded in
  the per-node ledger, the decision-audit ring (`kpctl explain node`),
  and the karpenter_disruption_consolidation_skips_total code label.
- **weather gate**: an attached advisory (weather/simulator.py
  ``consolidation_advisory``) HOLDS voluntary consolidation through an
  active storm or spot-crash regime window — consolidating INTO
  distressed capacity trades a standing node for one about to be
  reclaimed. An ice-age never holds: capacity held OUT of the market
  makes packing what remains more valuable, not less.

Probe verdicts stay optimistic (soft constraints fully relaxed) — the
controller re-verifies any winner with one exact solve plus the referee
before a single node is touched, so a stale or optimistic probe can cost
a bounded wasted solve, never an incorrect eviction.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..lattice.tensors import masked_view_versioned
from ..metrics import Registry, wire_core_metrics
from ..utils.clock import Clock
from . import taxonomy
from .solve import ProbeResult, Solver

# the savings referee's envelope: the device plan's replacement cost may
# exceed the host FFD oracle's costing of the same what-if by at most
# this fraction (ISSUE: "within the ≤2% envelope")
REFEREE_ENVELOPE = 0.02

# per-node skip ledger bound (newest wins; consolidation candidate sets
# are already capped well below this per pass)
_LEDGER_MAX = 512

# a probe-batch verdict whose set could not be evaluated (snapshot drift
# removed a member's node mid-pass): reported infeasible, never shrunk
_DEAD = ProbeResult(feasible=False, n_new=0, new_cost=0.0,
                    new_cap_type=None, flex=0)


@dataclass(frozen=True)
class SetVerdict:
    """One candidate removal set's evaluation, aligned with the caller's
    probe_sets order."""

    probe: ProbeResult
    removed_price: float     # $/hr of the set's standing capacity
    cached: bool = False     # served from the zero-leg delta cache
    host: bool = False       # outside the vmapped envelope: exact-
                             # verify on the host _what_if ladder


class ConsolidationEngine:
    """Batched what-if dispatch + referee + skip-reason ledger for the
    disruption controller's consolidation method."""

    def __init__(self, cluster, solver: Solver, node_pools: Dict,
                 unavailable, clock: Optional[Clock] = None,
                 metrics: Optional[Registry] = None, audit=None):
        self.cluster = cluster
        self.solver = solver
        self.node_pools = node_pools
        self.unavailable = unavailable
        self.clock = clock or Clock()
        self.audit = audit
        # {"hold": bool, "reason": str} supplier — soak/smoke wire the
        # weather simulator's consolidation_advisory here; None = fair
        self.weather_advisory: Optional[Callable[[], Dict]] = None
        self._lock = threading.Lock()
        m = wire_core_metrics(metrics or Registry())
        self._m_dispatches = m["disruption_vmapped_whatifs"]
        self._m_candidates = m["disruption_whatif_candidates"]
        self._m_cached = m["disruption_whatif_cached"]
        self._m_fallbacks = m["disruption_whatif_host_fallbacks"]
        self._m_skips = m["disruption_consolidation_skips"]
        self._m_savings = m["disruption_consolidation_savings"]
        self.counters: Dict[str, float] = {
            "vmapped_whatifs": 0,      # batched dispatches (kernel launches)
            "batched_candidates": 0,   # candidate sets across dispatches
            "fp_unchanged": 0,         # sets served from cache (zero legs)
            "host_fallbacks": 0,       # sets outside the vmapped envelope
            "cache_invalidations": 0,  # whole-cache clears
            "accepted": 0,             # removals begun
            "nodes_consolidated": 0,   # claims across accepted removals
            "savings_per_hour": 0.0,   # cumulative accepted $/hr savings
            "referee_checks": 0,
            "referee_rejects": 0,
            "weather_holds": 0,        # passes held by the advisory
        }
        self._skips: Dict[str, int] = {}              # code -> count
        self._ledger: Dict[str, Dict] = {}            # node -> last skip
        self._last_batch = 0                          # sets in last dispatch
        # zero-leg delta cache: (sorted member claim names) ->
        # (ProbeResult, removed $/hr), valid while the base problem's
        # fingerprint (journal anchor + price + unavailability) holds
        self._cache: Dict[Tuple[str, ...], Tuple[ProbeResult, float]] = {}
        self._anchor_rev: Optional[int] = None
        self._anchor_price: Optional[int] = None
        self._anchor_unavail: Optional[int] = None

    # ---- weather gate ----------------------------------------------------

    def weather_hold(self) -> str:
        """The advisory's hold reason ("" = consolidate freely)."""
        adv = self.weather_advisory
        if adv is None:
            return ""
        try:
            verdict = adv()
        except Exception:
            return ""    # a broken advisory must never wedge disruption
        if verdict and verdict.get("hold"):
            return str(verdict.get("reason") or "weather")
        return ""

    def note_weather_hold(self, node_names: Sequence[str],
                          reason: str) -> None:
        """One held pass: count it and ledger every candidate node."""
        with self._lock:
            self.counters["weather_holds"] += 1
        for n in node_names:
            self.note_skip(n, taxonomy.CONSOLIDATION_WEATHER_HOLD, reason)

    # ---- skip ledger -----------------------------------------------------

    def note_skip(self, node_name: str, code: str, detail: str = "") -> None:
        """Record "why was this node NOT consolidated": the coded metric
        label, the per-node ledger, and the decision-audit ring."""
        assert code in taxonomy.CODES, code
        now = self.clock.now()
        with self._lock:
            self._skips[code] = self._skips.get(code, 0) + 1
            self._ledger[node_name] = {
                "code": code, "detail": detail, "t": round(now, 3)}
            while len(self._ledger) > _LEDGER_MAX:
                self._ledger.pop(next(iter(self._ledger)))
        self._m_skips.inc(code=code)
        if self.audit is not None:
            self.audit.record_node(node_name, code, detail, t=now)

    def note_accept(self, removed, savings_per_hour: float) -> None:
        """An accepted removal: savings bookkeeping + ledger clear for
        the consolidated nodes (they are no longer 'not consolidated')."""
        with self._lock:
            self.counters["accepted"] += 1
            self.counters["nodes_consolidated"] += len(removed)
            self.counters["savings_per_hour"] += float(savings_per_hour)
            self._m_savings.set(self.counters["savings_per_hour"])
            for c in removed:
                self._ledger.pop(c.name, None)

    # ---- zero-leg delta cache --------------------------------------------

    def _cache_key(self, removed) -> Tuple[str, ...]:
        return tuple(sorted(c.name for c in removed))

    def _refresh_cache(self) -> None:
        """Validate the cache against the journal since the last
        dispatch. Any bin-table movement, unlocalizable mutation, price
        refresh, or unavailability change invalidates everything — a
        what-if's answer depends on the WHOLE remaining bin table, so
        per-set surgical retention would be wrong for any bin change.
        What survives (the dominant steady-state case): pending-pod
        churn and pure candidate-frontier drift, which don't move the
        base problem at all."""
        rev = self.cluster.state_rev
        price = self.solver.lattice.price_version
        unavail = self.unavailable.seq_num
        if self._anchor_rev is None:
            self._anchor_rev, self._anchor_price = rev, price
            self._anchor_unavail = unavail
            return
        stale = (price != self._anchor_price
                 or unavail != self._anchor_unavail)
        if not stale and rev != self._anchor_rev:
            ds = self.cluster.dirty_since(self._anchor_rev)
            stale = (ds.full or ds.other or ds.volumes or ds.daemonsets
                     or ds.bins)
        if stale and self._cache:
            self._cache.clear()
            with self._lock:
                self.counters["cache_invalidations"] += 1
        self._anchor_rev, self._anchor_price = rev, price
        self._anchor_unavail = unavail

    # ---- the vmapped envelope --------------------------------------------

    def _vmap_ineligible(self, problem) -> str:
        """Mirror of the microloop's envelope checks (Solver._solve_micro
        _MicroIneligible): the reason this candidate problem cannot ride
        the vmapped probe batch, or ""."""
        if problem.G > self.solver._g_ceiling():
            return "wave-scale G"
        mesh = getattr(self.solver, "mesh", None)
        sharded = mesh is not None and int(mesh.devices.size) > 1
        if sharded and (bool(problem.single_bin.any())
                        or (problem.A and bool(problem.g_need.any()))):
            return "pinned groups on mesh"
        return ""

    # ---- what-if problem construction ------------------------------------

    def _removed_price(self, lattice, removed) -> float:
        import numpy as np
        total = 0.0
        for c in removed:
            ti = lattice.name_to_idx.get(c.instance_type)
            if ti is None:
                continue
            zi = lattice.zones.index(c.zone) if c.zone in lattice.zones else 0
            ci = (lattice.capacity_types.index(c.capacity_type)
                  if c.capacity_type in lattice.capacity_types else 0)
            p = self.solver.lattice.price[ti, zi, ci]
            total += float(p) if np.isfinite(p) else 0.0
        return total

    def _whatif_problem(self, removed, lattice, all_bins, bound_all,
                        pvcs, storage_classes, ds, pools, node_of,
                        pods_of) -> object:
        """One candidate set's dirty-block delta as a scratch problem:
        member bins masked out of the table, evictee pods re-entering as
        pending groups. ``pods_of(claim_name)`` supplies the (possibly
        relaxed) evictee pods."""
        from .problem import build_problem
        removed_nodes = {node_of[c.name] for c in removed}
        removed_names = {c.name for c in removed}
        pods = [p for c in removed for p in pods_of(c.name)]
        existing = [b for b in all_bins
                    if b.name not in removed_nodes
                    and b.name not in removed_names]
        bound = [bp for bp in bound_all
                 if bp.node_name not in removed_nodes]
        return build_problem(
            pods, pools, lattice, existing=existing, daemonset_pods=ds,
            bound_pods=bound, pvcs=pvcs, storage_classes=storage_classes)

    # ---- the batched dispatch --------------------------------------------

    def probe(self, removed_sets: Sequence[Sequence],
              node_by_claim=None, by_node=None) -> List[SetVerdict]:
        """Evaluate every candidate removal set: cached verdicts at zero
        legs, the rest as ONE vmapped probe dispatch, envelope misfits
        flagged for the host ladder. Aligned with ``removed_sets``."""
        from ..apis.objects import relax_pod, relaxation_depth

        self._refresh_cache()
        verdicts: List[Optional[SetVerdict]] = [None] * len(removed_sets)
        misses: List[int] = []
        n_cached = n_fallback = 0
        for i, removed in enumerate(removed_sets):
            if not removed:
                verdicts[i] = SetVerdict(_DEAD, 0.0)
                continue
            hit = self._cache.get(self._cache_key(removed))
            if hit is not None:
                # the cache survived _refresh_cache, so no bin/price/
                # unavailability moved since the verdict: the set's nodes
                # still stand and the verdict still holds — zero legs AND
                # zero snapshot rebuilds for a fully-cached pass
                verdicts[i] = SetVerdict(hit[0], hit[1], cached=True)
                n_cached += 1
                continue
            misses.append(i)

        batch_problems, batch_idx, batch_prices = [], [], []
        if misses:
            lattice = masked_view_versioned(self.solver.lattice,
                                            self.unavailable)
            if node_by_claim is None:
                node_by_claim = self.cluster.nodes_by_claim()
            if by_node is None:
                by_node = self.cluster.pods_by_node(
                    include_daemonsets=False)
            all_bins = self.cluster.existing_bins(lattice)
            bound_all = self.cluster.bound_pods()
            pvcs, storage_classes = self.cluster.volume_state()
            ds = self.cluster.daemonset_pods()
            pools = list(self.node_pools.values())

            valid = {i: all(c.name in node_by_claim for c in removed_sets[i])
                     for i in misses}
            claim_names = {c.name for i in misses if valid[i]
                           for c in removed_sets[i]}
            node_of = {n: node_by_claim[n].name for n in claim_names}
            relaxed: Dict[str, object] = {}
            for n in claim_names:
                for p in by_node.get(node_of[n], ()):
                    if p.name not in relaxed:
                        relaxed[p.name] = relax_pod(p, relaxation_depth(p))

            def pods_of(claim_name):
                return [relaxed[p.name]
                        for p in by_node.get(node_of[claim_name], ())]

            for i in misses:
                removed = removed_sets[i]
                if not valid[i]:
                    # snapshot drift removed a member's node: reported
                    # infeasible, never silently shrunk — verdicts must
                    # stay aligned with the caller's sets
                    verdicts[i] = SetVerdict(_DEAD, 0.0)
                    continue
                price = self._removed_price(lattice, removed)
                problem = self._whatif_problem(
                    removed, lattice, all_bins, bound_all, pvcs,
                    storage_classes, ds, pools, node_of, pods_of)
                why = self._vmap_ineligible(problem)
                if why:
                    # outside the envelope: the controller exact-verifies
                    # on the host _what_if ladder under its budget —
                    # flagged, counted, never silently dropped
                    verdicts[i] = SetVerdict(_DEAD, price, host=True)
                    n_fallback += 1
                    continue
                batch_problems.append(problem)
                batch_idx.append(i)
                batch_prices.append(price)

        probed = (self.solver.probe_batch(batch_problems)
                  if batch_problems else [])
        for pr, i, price in zip(probed, batch_idx, batch_prices):
            verdicts[i] = SetVerdict(pr, price)
            self._cache[self._cache_key(removed_sets[i])] = (pr, price)
        # verdicts cached under the CURRENT anchor (refreshed above)
        with self._lock:
            if batch_problems:
                self.counters["vmapped_whatifs"] += 1
                self.counters["batched_candidates"] += len(batch_problems)
                self._last_batch = len(batch_problems)
            self.counters["fp_unchanged"] += n_cached
            self.counters["host_fallbacks"] += n_fallback
        if batch_problems:
            self._m_dispatches.inc()
            self._m_candidates.inc(len(batch_problems))
        if n_cached:
            self._m_cached.inc(n_cached)
        if n_fallback:
            self._m_fallbacks.inc(n_fallback)
        return [v if v is not None else SetVerdict(_DEAD, 0.0)
                for v in verdicts]

    # ---- the savings referee ---------------------------------------------

    def referee(self, removed, plan, node_by_claim=None,
                by_node=None) -> Tuple[bool, float]:
        """Cost the same what-if with the host FFD oracle and accept the
        device plan only within the ≤2% envelope. Returns (accepted,
        device/oracle cost ratio; 0.0 when the oracle has no costing —
        an FFD that cannot place the evictees cannot out-cost a plan
        that does)."""
        if node_by_claim is None:
            node_by_claim = self.cluster.nodes_by_claim()
        if by_node is None:
            by_node = self.cluster.pods_by_node(include_daemonsets=False)
        live = [c for c in removed if c.name in node_by_claim]
        with self._lock:
            self.counters["referee_checks"] += 1
        if not live:
            return True, 0.0
        lattice = masked_view_versioned(self.solver.lattice,
                                        self.unavailable)
        node_of = {c.name: node_by_claim[c.name].name for c in live}

        def pods_of(claim_name):
            return list(by_node.get(node_of[claim_name], ()))

        problem = self._whatif_problem(
            live, lattice, self.cluster.existing_bins(lattice),
            self.cluster.bound_pods(), *self.cluster.volume_state(),
            self.cluster.daemonset_pods(), list(self.node_pools.values()),
            node_of, pods_of)
        oracle = self.solver.solve_host_ffd(problem)
        if oracle.unschedulable:
            return True, 0.0
        bound = oracle.new_node_cost * (1.0 + REFEREE_ENVELOPE) + 1e-9
        ok = plan.new_node_cost <= bound
        ratio = (plan.new_node_cost / oracle.new_node_cost
                 if oracle.new_node_cost > 0.0
                 else (1.0 if plan.new_node_cost <= 0.0 else float("inf")))
        if not ok:
            with self._lock:
                self.counters["referee_rejects"] += 1
        return ok, ratio

    # ---- introspection ---------------------------------------------------

    def stats(self) -> Dict[str, float]:
        """The ``consolidation`` introspection provider (CONSOLIDATION
        row in kpctl top; sampled into soak artifacts): flat numeric."""
        with self._lock:
            out: Dict[str, float] = {
                k: (round(v, 6) if isinstance(v, float) else float(v))
                for k, v in self.counters.items()}
            out["probe_cache_size"] = float(len(self._cache))
            out["last_batch"] = float(self._last_batch)
            out["ledger_size"] = float(len(self._ledger))
            for code, n in sorted(self._skips.items()):
                out["skip_" + code.replace("-", "_")] = float(n)
            return out

    def headroom_probe(self) -> Dict[str, float]:
        """Zero-leg probe-cache occupancy (introspect/headroom.py).
        Unbounded dict in code, but bounded in practice by the candidate
        frontier — a fill rate that never drains means the invalidation
        anchors stopped firing. drops = whole-cache invalidations."""
        with self._lock:
            inval = self.counters["cache_invalidations"]
        return {"depth": float(len(self._cache)), "capacity": 0.0,
                "drops": float(inval)}

    def ledger_doc(self) -> Dict[str, Dict]:
        """Per-node skip ledger snapshot (`kpctl explain node` falls back
        here via the audit ring; /debug/explain?node= serves the ring)."""
        with self._lock:
            return {k: dict(v) for k, v in self._ledger.items()}
