"""Device cost model: what a solve SHOULD cost vs what it measured.

XLA knows, at compile time, exactly what each bucketed pack kernel is:
``compiled.cost_analysis()`` reports FLOPs and bytes accessed,
``memory_analysis()`` the peak device (HBM) footprint. This module
captures those per (G, B) bucket-ladder shape — at warmup/AOT compile
time, where the compiled handle already exists (solver/solve.py
``warmup``) — and then attributes every live solve's measured compute
stage against the model:

- **modeled floor** = the best compute time ever measured for that
  shape (self-calibrating: the first solves establish what the hardware
  actually achieves for this kernel; no hand-waved peak-FLOPs constant),
- **measured vs modeled** ratio per solve: ~1.0 means the device ran
  the kernel at its demonstrated speed; >>1.0 means the slowness is NOT
  the kernel — queueing, link contention, another caller's kernel — and
  the profiler/contention layers say which.

``kpctl top``'s DEVICE row and ``/debug/pprof/device`` render this;
burn-triggered captures (introspect/profiler.py BurnCapture) embed the
summary so a degradation episode records whether the device itself
slowed down. Live device memory rides along via
``jax.local_devices()[0].memory_stats()`` where the backend supports it
(TPU does; CPU returns None and the fields report 0).

Everything is bounded (one entry per compiled shape — the bucket ladder
is finite by construction) and off the hot path: ``observe_solve`` is a
dict update per solve, capture only runs where a compile already
happened.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

_MAX_SHAPES = 256   # bucket ladder is ~dozens; this is a runaway bound


class DeviceCostModel:
    def __init__(self):
        self._lock = threading.Lock()
        # shape key ("G64_B512") -> model/measurement record
        self._shapes: Dict[str, Dict] = {}
        self.last_shape: Optional[str] = None
        self.captures = 0          # compile-time analyses recorded
        self.capture_errors = 0

    # ---- compile-time capture ---------------------------------------------

    def record_compiled(self, key: str, compiled) -> bool:
        """Capture ``cost_analysis()`` / ``memory_analysis()`` from a
        ``jax.stages.Compiled`` (or Lowered) handle. Never raises — an
        analysis a backend does not support must not fail a warmup."""
        flops = bytes_accessed = peak_bytes = 0.0
        try:
            ca = compiled.cost_analysis()
            # jax returns either a dict or a 1-list of dicts by version
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            if isinstance(ca, dict):
                flops = float(ca.get("flops", 0.0) or 0.0)
                bytes_accessed = float(ca.get("bytes accessed", 0.0) or 0.0)
        except Exception:
            self.capture_errors += 1
        try:
            ma = compiled.memory_analysis()
            for attr in ("temp_size_in_bytes", "output_size_in_bytes",
                         "argument_size_in_bytes"):
                peak_bytes += float(getattr(ma, attr, 0) or 0)
        except Exception:
            pass   # memory_analysis is rarer than cost_analysis
        if not (flops or bytes_accessed or peak_bytes):
            return False
        self.record_analysis(key, flops=flops, bytes_accessed=bytes_accessed,
                             peak_bytes=peak_bytes)
        return True

    def record_analysis(self, key: str, flops: float = 0.0,
                        bytes_accessed: float = 0.0,
                        peak_bytes: float = 0.0) -> None:
        """The raw-form entry point (tests; backends with out-of-band
        analyses)."""
        with self._lock:
            if key not in self._shapes and len(self._shapes) >= _MAX_SHAPES:
                return
            rec = self._shapes.setdefault(key, self._fresh())
            rec["flops"] = flops
            rec["bytes_accessed"] = bytes_accessed
            rec["peak_bytes"] = peak_bytes
            self.captures += 1

    @staticmethod
    def _fresh() -> Dict:
        return {"flops": 0.0, "bytes_accessed": 0.0, "peak_bytes": 0.0,
                "solves": 0, "best_ms": 0.0, "last_ms": 0.0}

    # ---- per-solve attribution --------------------------------------------

    def observe_solve(self, key: str, compute_ms: float) -> None:
        """Attribute one solve's measured compute stage to its shape:
        the rolling best is the model floor; last-vs-best is the
        contention signal."""
        if compute_ms <= 0:
            return
        with self._lock:
            if key not in self._shapes and len(self._shapes) >= _MAX_SHAPES:
                return
            rec = self._shapes.setdefault(key, self._fresh())
            rec["solves"] += 1
            rec["last_ms"] = round(compute_ms, 4)
            if rec["best_ms"] == 0.0 or compute_ms < rec["best_ms"]:
                rec["best_ms"] = round(compute_ms, 4)
            self.last_shape = key

    # ---- reporting ---------------------------------------------------------

    @staticmethod
    def device_memory() -> Dict[str, float]:
        """Live device memory where the backend reports it (TPU/GPU
        ``memory_stats``; CPU returns None → zeros)."""
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats()
            if not stats:
                return {"bytes_in_use": 0.0, "bytes_limit": 0.0,
                        "peak_bytes_in_use": 0.0}
            return {
                "bytes_in_use": float(stats.get("bytes_in_use", 0) or 0),
                "bytes_limit": float(stats.get("bytes_limit", 0) or 0),
                "peak_bytes_in_use": float(
                    stats.get("peak_bytes_in_use", 0) or 0),
            }
        except Exception:
            return {"bytes_in_use": 0.0, "bytes_limit": 0.0,
                    "peak_bytes_in_use": 0.0}

    def stats(self) -> Dict[str, float]:
        """The introspection provider (flat numeric keys): the LAST
        solved shape's measured-vs-modeled plus live device memory."""
        with self._lock:
            out: Dict[str, float] = {
                "shapes": len(self._shapes),
                "captures": self.captures,
                "capture_errors": self.capture_errors,
            }
            key = self.last_shape
            rec = self._shapes.get(key) if key else None
            if rec is not None:
                out["last_compute_ms"] = rec["last_ms"]
                out["last_model_ms"] = rec["best_ms"]
                out["last_vs_model"] = (
                    round(rec["last_ms"] / rec["best_ms"], 3)
                    if rec["best_ms"] else 0.0)
                out["last_flops"] = rec["flops"]
        out.update(self.device_memory())
        return out

    def summary(self) -> Dict:
        """The /debug/pprof/device document + burn-capture embed: every
        shape's model and measurements."""
        with self._lock:
            shapes = {k: dict(v) for k, v in sorted(self._shapes.items())}
            for rec in shapes.values():
                if rec["best_ms"]:
                    rec["last_vs_model"] = round(
                        rec["last_ms"] / rec["best_ms"], 3)
        return {"shapes": shapes, "captures": self.captures,
                "captureErrors": self.capture_errors,
                "deviceMemory": self.device_memory(),
                "lastShape": self.last_shape}

    def reset(self) -> None:
        with self._lock:
            self._shapes.clear()
            self.last_shape = None
            self.captures = 0
            self.capture_errors = 0


_MODEL = DeviceCostModel()


def model() -> DeviceCostModel:
    """The process-wide cost model (one device pipeline per process)."""
    return _MODEL


def shape_key(G: int, B: int, mesh_devices: int = 1) -> str:
    """Cost-model key for one compiled shape. The device count is PART
    of the key: a mesh-compiled executable is a different program (D-way
    shard_map + collectives) with a different demonstrated-best compute
    floor — letting it share the single-device (G,B) entry would pollute
    the best-demonstrated baseline in both directions and make
    ``last_vs_model`` read as phantom contention after every mesh↔single
    transition (PR 12 bugfix; bench rows key the same way)."""
    if mesh_devices > 1:
        return f"G{G}_B{B}_D{mesh_devices}"
    return f"G{G}_B{B}"
