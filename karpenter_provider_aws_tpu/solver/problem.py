"""Pending pods + NodePools + lattice → the batched constraint problem.

This is the tensorization step the reference performs implicitly, one pod at
a time, inside its Go scheduler loop (core provisioner; see SURVEY.md §2.2).
Here:

1. Pods are **deduplicated into groups** by scheduling signature (requests +
   constraints + tolerations + self-anti-affinity). 50k pods from a handful
   of deployments collapse to a handful of groups — the key observation that
   makes the packing scan short on device.
2. Each group's requirements compile to boolean masks over the lattice axes
   (ops/masks.py) and to a per-NodePool compatibility row (host-side exact
   algebra, incl. taints/tolerations, custom template labels, minValues).
3. NodePools compile to their own masks, daemonset overhead vectors, and a
   weight-descending order (the order the reference tries pools,
   nodepools.md:161-163).
4. Existing capacity (in-flight NodeClaims / registered nodes) becomes
   pre-initialized bins so the solver fills real headroom before opening new
   nodes — the reference simulates against in-flight nodes the same way.

Everything is plain numpy here; solve.py pads and ships to device.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..apis import wellknown as wk
from ..apis.objects import NodePool, Pod, tolerates_all
from ..apis.requirements import Requirements
from ..apis.resources import R, resources_to_vec_checked
from ..lattice.tensors import Lattice
from ..ops.masks import _AXIS_KEYS, _CAT_KEY_INDEX, _NUM_KEY_INDEX, compile_masks


@dataclass
class ExistingBin:
    """A node (or in-flight NodeClaim) offered to the packer as existing
    headroom. ``fixed`` bins keep their instance type; they are never
    re-priced at finalization."""

    name: str
    node_pool: str
    instance_type: str
    zone: str
    capacity_type: str
    used: np.ndarray                      # [R] resources already committed
    alloc_override: Optional[np.ndarray] = None  # [R] if real node alloc differs from lattice


@dataclass
class PodGroup:
    signature: str
    pod_names: List[str]
    req: np.ndarray                # [R]
    type_mask: np.ndarray          # [T]
    zone_mask: np.ndarray          # [Z]
    cap_mask: np.ndarray           # [C]
    np_ok: np.ndarray              # [NP] bool
    hostname_anti_affinity: bool
    requirements: Requirements     # merged pod-level requirements (for claims)
    strict_custom: bool = False    # has existence-requiring custom-key constraints
                                   # (resolvable only via a known pool's labels)


@dataclass
class Problem:
    lattice: Lattice
    node_pools: List[NodePool]     # weight-descending order
    groups: List[PodGroup]         # FFD order (sorted descending)
    existing: List[ExistingBin]
    unschedulable: Dict[str, str]  # pod name -> reason
    # dense group arrays, FFD-sorted (host numpy; solve.py pads to buckets)
    req: np.ndarray                # [G,R] f32
    count: np.ndarray              # [G] i32
    g_type: np.ndarray             # [G,T] bool
    g_zone: np.ndarray             # [G,Z] bool
    g_cap: np.ndarray              # [G,C] bool
    g_np: np.ndarray               # [G,NP] bool
    antiaff: np.ndarray            # [G] bool
    strict_custom: np.ndarray      # [G] bool
    # nodepool arrays
    np_type: np.ndarray            # [NP,T] bool
    np_zone: np.ndarray            # [NP,Z] bool
    np_cap: np.ndarray             # [NP,C] bool
    ds_overhead: np.ndarray        # [NP,R] f32 daemonset overhead per new node
    # existing-bin arrays
    e_used: np.ndarray             # [E,R] f32
    e_alloc: np.ndarray            # [E,R] f32 (fixed node allocatable)
    e_type: np.ndarray             # [E] i32 type index
    e_zone: np.ndarray             # [E] i32
    e_cap: np.ndarray              # [E] i32
    e_np: np.ndarray               # [E] i32 nodepool index (-1 unknown)
    warnings: List[str] = field(default_factory=list)  # unsupported-constraint notices

    @property
    def G(self) -> int:
        return len(self.groups)

    @property
    def NP(self) -> int:
        return len(self.node_pools)

    @property
    def E(self) -> int:
        return len(self.existing)


def _custom_keys_ok(reqs: Requirements, pool_labels: Mapping[str, str]) -> bool:
    """Exact host-side check of constraints on keys the lattice does not
    model: they must be satisfied by the pool's template labels (or tolerate
    absence)."""
    for key in reqs.keys():
        if key in _AXIS_KEYS or key in _CAT_KEY_INDEX or key in _NUM_KEY_INDEX or key == wk.LABEL_REGION:
            continue
        c = reqs.get(key)
        if key in pool_labels:
            if not c.matches(pool_labels[key]):
                return False
        elif not c.allows_absent:
            return False
    return True


def _is_self_hostname_anti_affinity(pod: Pod) -> bool:
    """Does the pod anti-affine against its own replicas per hostname
    (the 1-pod-per-node pattern, scale suite provisioning_test.go:82-118)?"""
    for term in pod.pod_affinity:
        if term.anti and term.topology_key == wk.LABEL_HOSTNAME:
            sel = dict(term.label_selector)
            if all(pod.labels.get(k) == v for k, v in sel.items()):
                return True
    return False


def _group_signature(pod: Pod) -> str:
    reqs = pod.scheduling_requirements()
    parts = [repr(sorted(pod.requests.items()))]
    parts.append(repr(reqs))
    parts.append(repr(sorted((t.key, t.operator, t.value, t.effect) for t in pod.tolerations)))
    parts.append(repr(_is_self_hostname_anti_affinity(pod)))
    parts.append(repr(sorted(
        (c.topology_key, c.max_skew, c.when_unsatisfiable, tuple(sorted(c.label_selector)))
        for c in pod.topology_spread
    )))
    return "|".join(parts)


def build_problem(pods: Sequence[Pod], node_pools: Sequence[NodePool], lattice: Lattice,
                  existing: Sequence[ExistingBin] = (),
                  daemonset_pods: Sequence[Pod] = ()) -> Problem:
    pools = sorted(node_pools, key=lambda p: (-p.weight, p.name))
    NP = len(pools)
    T, Z, C = lattice.T, lattice.Z, lattice.C
    key_values = lattice.key_values_present()

    # --- NodePool masks + daemonset overhead
    np_type = np.ones((NP, T), dtype=bool)
    np_zone = np.ones((NP, Z), dtype=bool)
    np_cap = np.ones((NP, C), dtype=bool)
    ds_overhead = np.zeros((NP, R), dtype=np.float32)
    pool_reqs: List[Requirements] = []
    for pi, pool in enumerate(pools):
        reqs = pool.scheduling_requirements()
        pool_reqs.append(reqs)
        m = compile_masks(reqs, lattice, extra_labels=pool.labels)
        np_type[pi], np_zone[pi], np_cap[pi] = m.type_mask, m.zone_mask, m.cap_mask
        for ds in daemonset_pods:
            # a daemonset lands on the pool's nodes iff it tolerates the pool
            # taints and its node selectors are compatible (reference
            # resolves daemonset overhead per simulated node the same way)
            if not tolerates_all(ds.tolerations, pool.taints + pool.startup_taints):
                continue
            ds_reqs = ds.scheduling_requirements()
            if not ds_reqs.intersects(reqs):
                continue
            if not _custom_keys_ok(ds_reqs, pool.labels):
                continue
            vec, unknown = resources_to_vec_checked(ds.requests, implicit_pod=True)
            if unknown:
                continue
            ds_overhead[pi] += vec

    # --- group pods
    unschedulable: Dict[str, str] = {}
    groups_by_sig: Dict[str, PodGroup] = {}
    order: List[str] = []
    for pod in pods:
        vec, unknown = resources_to_vec_checked(pod.requests, implicit_pod=True)
        if unknown:
            unschedulable[pod.name] = f"unknown resource(s): {', '.join(unknown)}"
            continue
        sig = _group_signature(pod)
        g = groups_by_sig.get(sig)
        if g is not None:
            g.pod_names.append(pod.name)
            continue
        reqs = pod.scheduling_requirements()
        # custom-key constraints resolve exactly per-pool in np_ok below
        masks = compile_masks(reqs, lattice, skip_unresolved_custom=True)
        np_ok = np.zeros((NP,), dtype=bool)
        for pi, pool in enumerate(pools):
            if not reqs.intersects(pool_reqs[pi]):
                continue
            if not tolerates_all(pod.tolerations, pool.taints + pool.startup_taints):
                continue
            if not _custom_keys_ok(reqs, pool.labels):
                continue
            merged = reqs.merge(pool_reqs[pi])
            if not merged.min_values_satisfied(key_values):
                continue
            np_ok[pi] = True
        strict = any(
            key not in _AXIS_KEYS and key not in _CAT_KEY_INDEX
            and key not in _NUM_KEY_INDEX and key != wk.LABEL_REGION
            and not reqs.get(key).allows_absent
            for key in reqs.keys()
        )
        g = PodGroup(
            signature=sig, pod_names=[pod.name], req=vec,
            type_mask=masks.type_mask, zone_mask=masks.zone_mask, cap_mask=masks.cap_mask,
            np_ok=np_ok, hostname_anti_affinity=_is_self_hostname_anti_affinity(pod),
            requirements=reqs, strict_custom=strict,
        )
        groups_by_sig[sig] = g
        order.append(sig)

    groups = [groups_by_sig[s] for s in order]

    # mark groups with no feasible (pool, type, offering) at all
    schedulable_groups: List[PodGroup] = []
    for g in groups:
        feasible = False
        for pi in np.nonzero(g.np_ok)[0]:
            tm = g.type_mask & np_type[pi]
            zm = g.zone_mask & np_zone[pi]
            cm = g.cap_mask & np_cap[pi]
            if (tm[:, None, None] & zm[None, :, None] & cm[None, None, :] & lattice.available).any():
                feasible = True
                break
        if feasible or len(existing) > 0:
            # groups infeasible for new nodes may still fit existing capacity
            schedulable_groups.append(g)
        else:
            for name in g.pod_names:
                unschedulable[name] = "no compatible nodepool/instance-type offering"
    groups = schedulable_groups

    # --- FFD order: dominant normalized request, descending (the grouped
    # equivalent of the reference's pods-sorted-by-size FFD loop)
    if groups:
        mean_alloc = np.maximum(lattice.alloc.mean(axis=0), 1e-6)  # [R]
        def ffd_key(g: PodGroup):
            norm = g.req / mean_alloc
            return (-float(norm.max()), -float(g.req[0]), -float(g.req[1]), g.signature)
        groups.sort(key=ffd_key)

    G = len(groups)
    req = np.stack([g.req for g in groups]) if G else np.zeros((0, R), np.float32)
    count = np.array([len(g.pod_names) for g in groups], dtype=np.int32)
    g_type = np.stack([g.type_mask for g in groups]) if G else np.zeros((0, T), bool)
    g_zone = np.stack([g.zone_mask for g in groups]) if G else np.zeros((0, Z), bool)
    g_cap = np.stack([g.cap_mask for g in groups]) if G else np.zeros((0, C), bool)
    g_np = np.stack([g.np_ok for g in groups]) if G else np.zeros((0, NP), bool)
    antiaff = np.array([g.hostname_anti_affinity for g in groups], dtype=bool)
    strict_custom = np.array([g.strict_custom for g in groups], dtype=bool)

    # surface constraints the solver does not yet enforce instead of silently
    # violating them (topology spread + non-self pod affinity land with the
    # topology milestone)
    warnings = []
    seen_warn = set()
    for pod in pods:
        if pod.topology_spread and "spread" not in seen_warn:
            seen_warn.add("spread")
            warnings.append("topologySpreadConstraints not yet enforced by the solver")
        for term in pod.pod_affinity:
            supported = (term.anti and term.topology_key == wk.LABEL_HOSTNAME
                         and all(pod.labels.get(k) == v for k, v in dict(term.label_selector).items()))
            if not supported and "affinity" not in seen_warn:
                seen_warn.add("affinity")
                warnings.append("pod (anti-)affinity beyond hostname self-anti-affinity not yet enforced")

    # --- existing bins
    E = len(existing)
    e_used = np.zeros((E, R), np.float32)
    e_alloc = np.zeros((E, R), np.float32)
    e_type = np.zeros((E,), np.int32)
    e_zone = np.zeros((E,), np.int32)
    e_cap = np.zeros((E,), np.int32)
    e_np = np.full((E,), -1, np.int32)
    pool_index = {p.name: i for i, p in enumerate(pools)}
    zone_index = {z: i for i, z in enumerate(lattice.zones)}
    cap_index = {c: i for i, c in enumerate(lattice.capacity_types)}
    for ei, b in enumerate(existing):
        ti = lattice.name_to_idx[b.instance_type]
        e_used[ei] = b.used
        e_alloc[ei] = b.alloc_override if b.alloc_override is not None else lattice.alloc[ti]
        e_type[ei] = ti
        e_zone[ei] = zone_index[b.zone]
        e_cap[ei] = cap_index[b.capacity_type]
        e_np[ei] = pool_index.get(b.node_pool, -1)

    return Problem(
        lattice=lattice, node_pools=pools, groups=groups, existing=list(existing),
        unschedulable=unschedulable,
        req=req.astype(np.float32), count=count, g_type=g_type, g_zone=g_zone,
        g_cap=g_cap, g_np=g_np, antiaff=antiaff, strict_custom=strict_custom,
        warnings=warnings,
        np_type=np_type, np_zone=np_zone, np_cap=np_cap, ds_overhead=ds_overhead,
        e_used=e_used, e_alloc=e_alloc, e_type=e_type, e_zone=e_zone, e_cap=e_cap, e_np=e_np,
    )
