"""Pending pods + NodePools + lattice → the batched constraint problem.

This is the tensorization step the reference performs implicitly, one pod at
a time, inside its Go scheduler loop (core provisioner; see SURVEY.md §2.2).
Here:

1. Pods are **deduplicated into groups** by scheduling signature (requests +
   labels + constraints + tolerations + affinity + spread). 50k pods from a
   handful of deployments collapse to a handful of groups — the key
   observation that makes the packing scan short on device.
2. Each group's requirements compile to boolean masks over the lattice axes
   (ops/masks.py) and to a per-NodePool compatibility row (host-side exact
   algebra, incl. taints/tolerations, custom template labels, minValues).
3. Topology constraints resolve per solver/topology.py: zone/capacity-type
   scoped ones split groups into per-domain subgroups host-side; hostname
   scoped ones compile to per-row caps + affinity-class matrices the kernel
   enforces with per-bin presence masks.
4. NodePools compile to their own masks, daemonset overhead vectors, and a
   weight-descending order (the order the reference tries pools,
   nodepools.md:161-163).
5. Existing capacity (in-flight NodeClaims / registered nodes) becomes
   pre-initialized bins so the solver fills real headroom before opening new
   nodes — the reference simulates against in-flight nodes the same way.

Everything is plain numpy here; solve.py pads and ships to device.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..apis import wellknown as wk
from ..apis.objects import (IN_TREE_PROVISIONERS, WINDOWS_BUILD, NodePool,
                            Pod, pool_os, tolerates_all)
from ..apis.requirements import Operator, Requirement, Requirements
from ..apis.resources import R, axis as res_axis, resources_to_vec_checked
from ..lattice.tensors import Lattice
from ..ops.masks import _AXIS_KEYS, _CAT_KEY_INDEX, _NUM_KEY_INDEX, compile_masks
from . import taxonomy
from .topology import _BIG, BoundPod, ClassRegistry, resolve_group_topology


@dataclass
class ExistingBin:
    """A node (or in-flight NodeClaim) offered to the packer as existing
    headroom. ``fixed`` bins keep their instance type; they are never
    re-priced at finalization."""

    name: str
    node_pool: str
    instance_type: str
    zone: str
    capacity_type: str
    used: np.ndarray                      # [R] resources already committed
    alloc_override: Optional[np.ndarray] = None  # [R] if real node alloc differs from lattice
    labels: Dict[str, str] = field(default_factory=dict)  # node labels (custom-key matching)


@dataclass
class PodGroup:
    signature: str
    pod_names: List[str]
    req: np.ndarray                # [R]
    type_mask: np.ndarray          # [T]
    zone_mask: np.ndarray          # [Z]
    cap_mask: np.ndarray           # [C]
    np_ok: np.ndarray              # [NP] bool
    requirements: Requirements     # merged pod-level requirements (for claims)
    max_per_bin: int = _BIG        # hostname spread / self-anti-affinity cap
    spread_class: int = -1         # class whose per-bin count the cap tracks
    single_bin: bool = False       # hostname self-affinity: all replicas co-locate
    match: np.ndarray = None       # [A] selector classes matching this group's labels
    owner: np.ndarray = None       # [A] hostname anti-affinity terms owned
    need: np.ndarray = None        # [A] hostname affinity presence requirements
    strict_custom: bool = False    # has existence-requiring custom-key constraints
                                   # (resolvable only via a known pool's labels)
    unnarrowed_type_mask: Optional[np.ndarray] = None  # pre-accel-narrowing
                                   # mask; the feasibility gate falls back to
                                   # it if narrowing made the group infeasible
    ledger: Optional[object] = None  # solver/explain.py GroupLedger — the
                                   # group's constraint-elimination record
                                   # (None when the build ran explain=False)


@dataclass
class Problem:
    lattice: Lattice
    node_pools: List[NodePool]     # weight-descending order
    groups: List[PodGroup]         # FFD order (sorted descending)
    existing: List[ExistingBin]
    unschedulable: Dict[str, str]  # pod name -> reason
    # dense group arrays, FFD-sorted (host numpy; solve.py pads to buckets)
    req: np.ndarray                # [G,R] f32
    count: np.ndarray              # [G] i32
    g_type: np.ndarray             # [G,T] bool
    g_zone: np.ndarray             # [G,Z] bool
    g_cap: np.ndarray              # [G,C] bool
    g_np: np.ndarray               # [G,NP] bool
    max_per_bin: np.ndarray        # [G] i32
    g_spread: np.ndarray           # [G] i32 spread class (-1 = none)
    single_bin: np.ndarray         # [G] bool
    g_match: np.ndarray            # [G,A] bool
    g_owner: np.ndarray            # [G,A] bool
    g_need: np.ndarray             # [G,A] bool
    strict_custom: np.ndarray      # [G] bool
    # nodepool arrays
    np_type: np.ndarray            # [NP,T] bool
    np_zone: np.ndarray            # [NP,Z] bool
    np_cap: np.ndarray             # [NP,C] bool
    ds_overhead: np.ndarray        # [NP,R] f32 daemonset overhead per new node
    np_alloc_cap: np.ndarray       # [NP,R] f32 allocatable ceiling (+inf;
                                   # kubelet maxPods caps the pods axis)
    # existing-bin arrays
    e_used: np.ndarray             # [E,R] f32
    e_alloc: np.ndarray            # [E,R] f32 (fixed node allocatable)
    e_type: np.ndarray             # [E] i32 type index
    e_zone: np.ndarray             # [E] i32
    e_cap: np.ndarray              # [E] i32
    e_np: np.ndarray               # [E] i32 nodepool index (-1 unknown)
    e_pm: np.ndarray               # [E,A] i32 count of bound pods matching class a
    e_po: np.ndarray               # [E,A] bool bin holds a bound pod owning anti-term a
    warnings: List[str] = field(default_factory=list)  # unsupported-constraint notices
    # groups eliminated entirely at build (no feasible offering, no
    # existing capacity): kept so the explain surface can render their
    # elimination waterfall for the pods now in ``unschedulable``
    dropped_groups: List[PodGroup] = field(default_factory=list)

    @property
    def G(self) -> int:
        return len(self.groups)

    @property
    def NP(self) -> int:
        return len(self.node_pools)

    @property
    def E(self) -> int:
        return len(self.existing)

    @property
    def A(self) -> int:
        return self.g_match.shape[1] if self.g_match.ndim == 2 else 0


_ACCEL_AXES = tuple(
    res_axis(a) for a in ("nvidia.com/gpu", "amd.com/gpu",
                          "habana.ai/gaudi", "aws.amazon.com/neuron"))


# accel types within this per-unit-price factor of the best stay in the
# narrowed set — a little launch flexibility is worth a few % of cost
_ACCEL_UNIT_PRICE_SLACK = 1.05


def _accel_bin_cap(vec: np.ndarray, type_mask: np.ndarray,
                   zone_mask: np.ndarray, cap_mask: np.ndarray,
                   pool_tmask: np.ndarray, existing_tmask: np.ndarray,
                   lattice: Lattice) -> Optional[np.ndarray]:
    """Accelerator bin-splitting: a narrowed type mask that lands
    finalization on the cheapest PER-ACCELERATOR-UNIT types.

    Sequential FFD (the reference's scheduler, and our scan) packs a
    whole accelerator wave into the first bin with room, so one big
    accelerator node hosts it even when small accelerator types cost
    less per unit (measured: 4 one-GPU pods → one g5.12xlarge at
    $1.92/hr where four g5.xlarge cost $1.54); generic pods riding that
    bin then UPSIZE it further at finalization. Two counter-moves, both
    computed from the live (ICE-masked) lattice:

    - narrow the group's type mask to types within
      ``_ACCEL_UNIT_PRICE_SLACK`` of the best per-unit price (keeping
      only types that fit at least one pod). NEW bins then hold only as
      many accelerator pods as the small types' own capacity — the wave
      splits via ordinary capacity math, with no per-bin cap that would
      also throttle joins onto EXISTING accelerator nodes — and a
      joining generic pod can consume a bin's true leftover but never
      upsize it.

    Splitting is never worse on accelerator cost — k small nodes at the
    best unit price cost ≤ one big node holding k units, by definition
    of the per-unit argmin — and displaced generic pods land on far
    cheaper general capacity. The FFD referee (which packs the SAME
    capped problem) keeps parity honest; tests/test_solver.py pins the
    absolute win against the UNCAPPED pack.

    Correctness fences (review r4): candidates intersect the group's
    POOL-feasible types (``pool_tmask`` — a p3-only pool ranks within p3,
    never narrowing itself unschedulable), prices reduce over the group's
    OWN zone/capacity-type masks (an on-demand-only pool ranks by
    on-demand prices, not spot), and accelerator-capable EXISTING node
    types stay in the mask (free GPUs on a running multi-GPU node always
    beat a launch).

    Returns the narrowed mask, or None when no accelerator demand or
    nothing to gain."""
    for ax in _ACCEL_AXES:
        per_pod = float(vec[ax])
        if per_pod <= 0:
            continue
        if not zone_mask.any() or not cap_mask.any():
            return None
        counts = lattice.capacity[:, ax]
        # a candidate must hold at least one WHOLE pod (all axes) AND be
        # launchable by some compatible pool
        fits_one = (lattice.alloc >= vec[None, :]).all(axis=1)
        feasible = type_mask & (counts >= per_pod) & fits_one
        cand = feasible & pool_tmask
        if not cand.any():
            return None
        idx = np.nonzero(cand)[0]
        # cheapest offering per candidate, WITHIN the group's own zone and
        # capacity-type masks (only candidate rows: the reduction stays
        # O(|cand|·Z·C), not O(T·Z·C) per group)
        offers = lattice.available[np.ix_(idx, np.nonzero(zone_mask)[0],
                                          np.nonzero(cap_mask)[0])]
        prices = np.where(
            offers,
            lattice.price[np.ix_(idx, np.nonzero(zone_mask)[0],
                                 np.nonzero(cap_mask)[0])],
            np.inf)
        pmin = prices.reshape(len(idx), -1).min(axis=1)
        per_unit = pmin / np.maximum(counts[idx], 1e-9)
        b = int(np.argmin(per_unit))
        if not np.isfinite(per_unit[b]):
            return None
        keep = np.zeros(type_mask.shape, dtype=bool)
        keep[idx[per_unit <= per_unit[b] * _ACCEL_UNIT_PRICE_SLACK]] = True
        # existing accelerator-capable node types stay joinable — their
        # free capacity is already paid for
        keep |= feasible & existing_tmask
        return keep
    return None


# a group only counts as a "wave" (per-pod-cost narrowing candidate)
# above this many identical pods; below it, bin-sharing with other
# groups usually matters more than homogeneous type choice
_WAVE_MIN_PODS = 64
# trigger only when the predicted per-pod saving is large (best per-pod
# cost ≤ this fraction of the densest type's per-pod cost): flat price
# curves — the common case, where FFD is already near-optimal — must
# not be fragmented for marginal gains
_WAVE_GAIN = 0.7
_WAVE_PRICE_SLACK = 1.05
# density floor: the whole BATCH may narrow into at most this many
# bins' worth of nodes — each wave's candidates must hold at least
# total_pending/this pods per bin. Nodes are not free beyond their
# price (kubelet, daemonsets, API-object load, and the pack kernel's
# scan length all scale with bin count), so the narrowing picks the
# best per-pod cost among types that keep the plan size bounded rather
# than fragmenting a 50k-pod batch into thousands of burstable
# nanonodes. The floor is GLOBAL (total pending / bins), not
# per-group: a batch of thirty 1.6k-pod waves fragments exactly like
# one 50k wave, and a per-group bound cannot see that.
_WAVE_MAX_BINS = 1024

# narrowing results memoized by CONTENT (every array input's bytes)
# plus lattice identity: the numpy reductions in
# _accel_bin_cap/_wave_candidates are ~0.5 ms per group, and a steady
# controller rebuilds the same groups every batch. The cached value is
# COUNT-INDEPENDENT — the accel mask plus the wave candidate table
# (idx, per-bin fit K, cheapest price pmin); the cheap floor/gain
# decision that DOES depend on the group's count and the batch's total
# pending (_wave_mask_from_table) re-runs on every call. This is what
# lets a steady-state reconcile whose pod counts drift a little reuse
# the expensive reductions for every untouched group (the incremental
# build path, solver/incremental.py) while staying bit-identical to a
# from-scratch rebuild. price/availability moves invalidate via
# price_version in the key and the `is` check on the stored lattice ref
# (pricing mutates price[...] in place but bumps the version; ICE
# produces a NEW masked_view lattice object — holding the ref strongly
# means a dead lattice's id can never alias a live key). Two-level: at
# most _NARROW_LATS lattices are retained (an ICE-churning controller
# mints a masked_view per cycle; an unbounded flat map would pin every
# dead one), each with at most _NARROW_MAX per-group entries. Guarded
# by build_problem's _INTERN_LOCK.
_NARROW_MAX = 4096
_NARROW_LATS = 4
_NARROW_CACHE: Dict[int, tuple] = {}   # id(lat) -> (lattice, {key: entry})
_WAVE_UNSET = object()   # wave candidate table not computed yet (lazy)


def _wave_bin_cap(vec: np.ndarray, count: int, type_mask: np.ndarray,
                  zone_mask: np.ndarray, cap_mask: np.ndarray,
                  pool_tmask: np.ndarray, existing_tmask: np.ndarray,
                  ds_vec: np.ndarray, lattice: Lattice,
                  max_per_bin: int = 0,
                  total_pending: int = 0) -> Optional[np.ndarray]:
    """Per-POD-cost narrowing for pods-axis-bound waves.

    Sequential FFD (the reference's scheduler: first-fit, then price each
    bin at its cheapest fitting type — designs/bin-packing.md:16-43)
    grows a tiny-pod wave's bins to the maximum pod DENSITY any feasible
    type offers, then must price at the huge types that carry that
    density (ENI-limited pods: 737 needs 15×50-ENI machines). When the
    wave is bound by the pods axis rather than cpu/memory, the big
    type's vCPUs go unused and its $/pod is several times worse than a
    small type's (real catalog: m5.24xlarge at 737 pods = $6.3e-3/pod vs
    t3.medium-class nodes under $2.5e-3/pod). This narrows the wave's
    type mask to the types within ``_WAVE_PRICE_SLACK`` of the best
    per-pod cost, so bins seal at the small types' own density and the
    wave splits via ordinary capacity math.

    Per-pod cost of a type = its cheapest offering price (within the
    group's OWN zone/captype masks) divided by how many of THIS group's
    pods fit an empty bin of that type after daemonset overhead — the
    pods axis, cpu, memory, and every other requested axis all cap the
    fit, so the ranking is exact for homogeneous bins.

    Fences mirror _accel_bin_cap: candidates intersect the group's
    pool-feasible types; existing node types stay joinable (their free
    capacity is paid for); the caller holds the unnarrowed mask as a
    schedulability fallback; and the ``_WAVE_GAIN`` gate keeps the
    narrowing OFF whenever FFD's densest-type choice is already within
    30% of optimal — only genuinely pods-axis-bound shapes trigger.
    Never applied to accelerator groups (_accel_bin_cap owns those).
    """
    table = _wave_candidates(vec, type_mask, zone_mask, cap_mask,
                             pool_tmask, ds_vec, lattice)
    if table is None:
        return None
    return _wave_mask_from_table(table, count, type_mask, existing_tmask,
                                 max_per_bin, total_pending)


def _wave_candidates(vec: np.ndarray, type_mask: np.ndarray,
                     zone_mask: np.ndarray, cap_mask: np.ndarray,
                     pool_tmask: np.ndarray, ds_vec: np.ndarray,
                     lattice: Lattice) -> Optional[tuple]:
    """The COUNT-INDEPENDENT half of the wave narrowing: the expensive
    per-candidate reductions — how many of this group's pods fit an empty
    bin of each candidate type (K, pre-spread-clamp) and the cheapest
    offering price within the group's own zone/captype masks (pmin).
    Everything here depends only on the group's content and the lattice,
    so the narrowing cache can reuse it across passes whose pod counts
    drifted; _wave_mask_from_table applies the count/total-dependent
    floor and gain gates per call."""
    if not zone_mask.any() or not cap_mask.any():
        return None
    cand = type_mask & pool_tmask
    if not cand.any():
        return None
    idx = np.nonzero(cand)[0]
    # pods of this group per empty bin of each candidate type
    free = lattice.alloc[idx] - ds_vec[None, :]
    need = vec[None, :]
    with np.errstate(divide="ignore", invalid="ignore"):
        per_axis = np.where(need > 0, free / np.maximum(need, 1e-9), np.inf)
    K = np.floor(per_axis.min(axis=1))
    # the K >= 1 feasibility filter commutes with the spread clamp
    # (min(K, mpb) >= 1 ⇔ K >= 1 whenever mpb >= 1, and mpb == 0 means
    # no clamp), so filtering pre-clamp keeps the table count-free
    fits = K >= 1
    if not fits.any():
        return None
    idx, K = idx[fits], K[fits]
    # price every candidate BEFORE the floor: the floor's relaxation
    # point must be the densest candidate that actually has an offering
    # in the group's zone/captype masks — an ICE'd or out-of-zone big
    # type must not anchor a floor no available type can meet
    offers = lattice.available[np.ix_(idx, np.nonzero(zone_mask)[0],
                                      np.nonzero(cap_mask)[0])]
    prices = np.where(
        offers,
        lattice.price[np.ix_(idx, np.nonzero(zone_mask)[0],
                             np.nonzero(cap_mask)[0])],
        np.inf)
    pmin = prices.reshape(len(idx), -1).min(axis=1)
    if not np.isfinite(pmin).any():
        return None
    return idx, K, pmin


def _wave_mask_from_table(table: tuple, count: int, type_mask: np.ndarray,
                          existing_tmask: np.ndarray, max_per_bin: int,
                          total_pending: int) -> Optional[np.ndarray]:
    """The cheap per-call half of the wave narrowing: the density floor
    and the gain gate over an already-computed candidate table. O(|cand|)
    numpy over a handful of candidates — safe to re-run on every build."""
    if count < _WAVE_MIN_PODS:
        return None
    idx, K, pmin = table
    if max_per_bin:
        # hostname-spread groups seal bins early; rank at the density
        # the bins will actually reach
        K = np.minimum(K, max_per_bin)
    priced = np.isfinite(pmin)
    # density floor (see _WAVE_MAX_BINS): candidates must carry the
    # batch-wide density that keeps the whole plan bounded — relaxed to
    # the densest PRICED candidate when nothing meets it (a t-family-only
    # pool offers only small types; FFD would use them too, and the gain
    # gate still decides). A hostname-spread wave needs no extra clamp:
    # K was already capped to max_per_bin above, so the densest-candidate
    # relaxation can never demand more than the spread's per-bin cap.
    floor = max(total_pending, count) / _WAVE_MAX_BINS
    floor = min(floor, float(K[priced].max()))
    meets_floor = (K >= floor) & priced
    idx, K, pmin = idx[meets_floor], K[meets_floor], pmin[meets_floor]
    per_pod = pmin / K
    b = int(np.argmin(per_pod))
    # what FFD would effectively pay: the per-pod cost of the DENSEST
    # priced type (first-fit grows bins to max density; end-pricing then
    # needs a type carrying that density)
    dense = int(np.argmax(K))
    ffd_per_pod = per_pod[dense]
    if per_pod[b] > ffd_per_pod * _WAVE_GAIN:
        return None
    keep = np.zeros(type_mask.shape, dtype=bool)
    keep[idx[per_pod <= per_pod[b] * _WAVE_PRICE_SLACK]] = True
    # existing node types stay joinable — free capacity is paid for
    keep |= type_mask & existing_tmask
    return keep


def _is_custom_key(key: str) -> bool:
    """A label key the lattice does not model (user-defined)."""
    return (key not in _AXIS_KEYS and key not in _CAT_KEY_INDEX
            and key not in _NUM_KEY_INDEX and key != wk.LABEL_REGION)


def _resolve_custom_sigma(reqs, pool: NodePool, preqs,
                          gen: str) -> Optional[Dict[str, str]]:
    """Custom-key labels a node of ``pool`` must carry to host this group,
    for keys the pool leaves FREE via a template requirement (Exists, or
    In over several values — reference scheduling.md:536-556). Returns
    None when no labeling can satisfy the group on this pool, {} when
    nothing needs pinning (template labels or absence already resolve
    every key), else the value assignment. ``gen`` is the generated value
    used when the group demands existence without naming one."""
    offered = set(preqs.keys())
    sigma: Dict[str, str] = {}
    for key in reqs.keys():
        if not _is_custom_key(key):
            continue
        c = reqs.get(key)
        if key in pool.labels:
            if not c.matches(pool.labels[key]):
                return None
            continue
        if key not in offered:
            if not c.allows_absent:
                return None
            continue
        if c.allows_absent and c.include is None:
            # e.g. NotIn: satisfied without the key; no pin needed
            continue
        both = c.intersect(preqs.get(key))
        if both.include is not None:
            picks = sorted(v for v in both.include if both.matches(v))
            if not picks:
                return None
            sigma[key] = picks[0]
        elif both.gt is not None or both.lt is not None:
            n = int(both.gt) + 1 if both.gt is not None else int(both.lt) - 1
            if not both.matches(str(n)):
                return None
            sigma[key] = str(n)
        else:
            if not both.matches(gen):
                return None
            sigma[key] = gen
    return sigma


def _custom_keys_ok(reqs: Requirements, pool_labels: Mapping[str, str]) -> bool:
    """Exact host-side check of constraints on keys the lattice does not
    model: they must be satisfied by the pool's template labels (or tolerate
    absence)."""
    for key in reqs.keys():
        if not _is_custom_key(key):
            continue
        c = reqs.get(key)
        if key in pool_labels:
            if not c.matches(pool_labels[key]):
                return False
        elif not c.allows_absent:
            return False
    return True


def csi_claims_count(claims, pvcs: Mapping, storage_classes: Mapping,
                     warnings: Optional[List[str]] = None) -> int:
    """CSI volume attach slots the claims in ``claims`` consume. The core
    scheduler counts a node's CSI volumes against the CSINode attach limit
    (reference troubleshooting.md:277-288 'Pods using PVCs can hit volume
    limits'); deprecated in-tree plugins publish no limits, so the
    reference logs an error and cannot enforce them
    (troubleshooting.md:290-294) — mirrored here as a warning + exclusion.
    Unknown PVCs/StorageClasses count one slot each (almost certainly CSI;
    over-counting is the safe direction for attach limits). Pass a SET of
    claim names for per-unique-volume accounting (resident pods sharing a
    claim attach it once, state/cluster.py existing_bins); pending-group
    charging is per pod-claim reference — a conservative approximation,
    since the resource-axis encoding cannot dedup across groups inside
    the kernel."""
    n = 0
    for cname in claims:
        pvc = pvcs.get(cname)
        sc = (storage_classes.get(pvc.storage_class)
              if pvc is not None and pvc.storage_class else None)
        if sc is not None and sc.provisioner in IN_TREE_PROVISIONERS:
            if warnings is not None:
                warnings.append(
                    f"PVC {cname!r} uses deprecated in-tree plugin "
                    f"{sc.provisioner!r}: attach limits unknown and not "
                    "enforced; use the CSI driver")
            continue
        n += 1
    return n


def _volume_zone_mask(pod: Pod, pvcs: Mapping, storage_classes: Mapping,
                      zones: Sequence[str], warnings: List[str],
                      shared_pins: Optional[Mapping] = None) -> np.ndarray:
    """Zone restriction from the pod's PVC references (reference
    scheduling.md:389-398): a bound PV pins its exact zone; an unbound claim
    restricts to its StorageClass's allowedTopologies (if any).

    ``shared_pins`` maps unbound claims with multiple same-batch consumers
    to ONE pre-chosen zone index (the reference 'randomly selects' a zone
    for WaitForFirstConsumer claims) so consumers can never diverge across
    zones and then fight over the bind. The pin is chosen globally in
    build_problem from the intersection of every consumer's allowed zones."""
    mask = np.ones((len(zones),), dtype=bool)
    zone_index = {z: i for i, z in enumerate(zones)}
    for cname in pod.volume_claims:
        pvc = pvcs.get(cname)
        if pvc is None:
            warnings.append(f"pod references unknown PVC {cname!r}")
            continue
        if pvc.bound_zone is not None:
            m = np.zeros((len(zones),), dtype=bool)
            zi = zone_index.get(pvc.bound_zone)
            if zi is not None:
                m[zi] = True
            mask &= m
            continue
        sc = storage_classes.get(pvc.storage_class)
        if sc is None:
            if pvc.storage_class:
                warnings.append(
                    f"PVC {cname!r} references unknown StorageClass "
                    f"{pvc.storage_class!r}")
            continue
        if sc.zones:
            m = np.zeros((len(zones),), dtype=bool)
            for z in sc.zones:
                zi = zone_index.get(z)
                if zi is not None:
                    m[zi] = True
            mask &= m
        if shared_pins is not None and cname in shared_pins:
            pin_zi = shared_pins[cname]
            if pin_zi is not None:
                pin = np.zeros((len(zones),), dtype=bool)
                pin[pin_zi] = True
                mask &= pin
    return mask


def _selector_keys(pods: Sequence[Pod], bound_pods: Sequence[BoundPod]) -> frozenset:
    """Label keys referenced by ANY affinity/spread selector in the batch or
    on bound pods. Only these keys affect scheduling semantics, so the group
    signature projects labels onto them — per-pod-unique labels (StatefulSet
    pod names, pod-index) never break deduplication.

    Each pod caches its contribution on itself (Pod.__setattr__ drops the
    cache when a selector field is reassigned); cluster state hands the
    SAME Pod objects to every scheduling pass, so steady-state batches pay
    one dict get per pod — whether the selector containers are shared
    (controller-stamped fixtures) or per-pod unique (anything parsed from
    the API server is its own object)."""
    keys: set = set()
    upd = keys.update

    def fill(p: Pod) -> frozenset:
        mine: set = set()
        for term in p.pod_affinity:
            mine.update(k for k, _ in term.label_selector)
        for c in p.topology_spread:
            mine.update(k for k, _ in c.label_selector)
        out = frozenset(mine)
        p.__dict__["_kpat_selkeys"] = out
        return out

    # the emptiness check and the cache hit live INLINE in the loop:
    # most pods carry no selectors at all, and 50k no-op FUNCTION CALLS
    # alone cost ~12 ms of the build budget. The instance __dict__ is
    # read directly: a plain attribute load first scans the type (miss —
    # default_factory fields leave no class attribute) before the
    # instance dict, and at 50k pods the two skipped type scans per pod
    # are another measurable slice of the build budget. ``.get`` (not
    # indexing): a Pod built without __init__ (object.__new__ +
    # piecemeal assignment, serde fast paths, test doubles) may lack the
    # keys entirely, and a missing selector field must read as "no
    # selectors", not KeyError.
    for p in pods:
        d = p.__dict__
        if d.get("pod_affinity") or d.get("topology_spread"):
            cached = d.get("_kpat_selkeys")
            upd(cached if cached is not None else fill(p))
    for bp in bound_pods:
        d = bp.pod.__dict__
        if d.get("pod_affinity") or d.get("topology_spread"):
            cached = d.get("_kpat_selkeys")
            upd(cached if cached is not None else fill(bp.pod))
    return frozenset(keys)


def _group_key(pod: Pod, relevant_keys: frozenset, memo: dict) -> tuple:
    """Cheap per-pod scheduling-signature key over RAW hashable fields.

    All the fields that feed group compilation are here verbatim, so equal
    keys imply identical compiled groups (the expensive requirements /
    mask / topology work runs once per distinct key, not once per pod —
    this is what keeps 50k-pod tensorization in the tens of milliseconds).
    Field order is preserved rather than sorted: pods stamped out by the
    same controller share the construction order, and a differing order
    merely splits a group, never merges distinct ones.

    ``memo`` collapses repeated container objects (pods stamped out from a
    deployment template share the same requests/selector dicts) to one
    tuple build each; holding the container ref keeps its id() stable.
    """

    def t(container) -> tuple:
        if not container:
            return ()
        e = memo.get(id(container))
        if e is not None and e[0] is container:
            return e[1]
        out = (tuple(container.items()) if isinstance(container, dict)
               else tuple(container))
        memo[id(container)] = (container, out)
        return out

    labels = pod.labels
    lab = (tuple(sorted((k, v) for k, v in labels.items() if k in relevant_keys))
           if relevant_keys and labels else ())
    return (
        t(pod.requests),
        lab,
        t(pod.node_selector),
        t(pod.required_affinity),
        t(pod.preferred_affinity),
        t(pod.tolerations),
        t(pod.topology_spread),
        t(pod.pod_affinity),
        t(pod.volume_claims),
    )


# Global signature interning. A pod's full scheduling signature (the nested
# tuple _group_key builds) maps to a small int once per process; the per-pod
# cache stores (relevant_keys, sig_id) so repeated scheduling passes over the
# same pods cost one dict hit + one pointer compare per pod — int-keyed group
# lookup instead of re-hashing nested tuples. Both registries are bounded by
# the number of DISTINCT pod shapes seen, not pod count; shapes can still
# churn over a long-lived controller (rollout-hash-style labels), so the
# registries reset at _INTERN_MAX. build_problem serializes on _INTERN_LOCK:
# two concurrent misses must not mint one sig_id for two signatures, and a
# reset must not yank sig_ids out from under a mid-flight grouping pass
# (stale per-pod caches miss via the interned relevant_keys pointer).
_INTERN_LOCK = threading.Lock()
_INTERN_MAX = 1 << 20
_RK_INTERN: Dict[frozenset, frozenset] = {}
_SIG_IDS: Dict[tuple, int] = {}
_SIG_TUPLES: List[tuple] = []        # sig_id -> sig (for the id->key map)
_BAD_SIDS: Dict[int, str] = {}       # sig_id -> unknown-resource reason
                                     # (depends only on the sig's requests)


def signature_of(pod: Pod, relevant_keys: frozenset = frozenset()
                 ) -> Tuple[str, Optional[str]]:
    """(signature repr, unknown-resource reason) of one pod under the
    given relevant label keys — the SAME interned signature machinery
    build_problem groups with, so solver/incremental.py can match a
    churned pod to the previous build's groups without a full regroup.
    Serializes on the intern lock; the per-pod cache makes repeat calls
    one dict hit."""
    with _INTERN_LOCK:
        rk = _RK_INTERN.setdefault(relevant_keys, relevant_keys)
        cache = pod.__dict__.get("_kpat_sig")
        if cache is not None and cache[0] is rk:
            sid = cache[1]
        else:
            sig = _group_key(pod, rk, {})
            sid = _SIG_IDS.get(sig)
            if sid is None:
                sid = len(_SIG_TUPLES)
                _SIG_IDS[sig] = sid
                _SIG_TUPLES.append(sig)
                _, unknown = resources_to_vec_checked(pod.requests,
                                                      implicit_pod=True)
                if unknown:
                    _BAD_SIDS[sid] = taxonomy.reason(
                        taxonomy.UNKNOWN_RESOURCE,
                        f"unknown resource(s): {', '.join(unknown)}")
            pod.__dict__["_kpat_sig"] = (rk, sid)
        return repr(_SIG_TUPLES[sid]), _BAD_SIDS.get(sid)


def recheck_narrow(group: PodGroup, count: int, total_pending: int,
                   lattice: Lattice) -> bool:
    """Would a from-scratch build reach the SAME narrowing decision for
    ``group`` at the new (count, total_pending)? The incremental builder
    (solver/incremental.py) calls this for every retained group — the
    expensive candidate reductions are content-cached, so the replay is
    one dict hit plus the cheap floor/gain step. False means the drifted
    counts flipped a narrowing decision and the caller must rebuild from
    scratch (parity over speed, always)."""
    ctx = getattr(group, "_narrow_ctx", None)
    if ctx is None:
        # narrowing never ran for this group (narrow=False build);
        # nothing count-dependent to flip
        return True
    (nkey, vec, tmask, zm, cm, pool_tmask, ds_max, existing_tmask,
     prev_raw) = ctx
    with _INTERN_LOCK:
        slot = _NARROW_CACHE.get(id(lattice))
        if slot is not None and slot[0] is not lattice:
            slot = None
        entry = slot[1].get(nkey) if slot is not None else None
        if entry is None:
            a_accel = _accel_bin_cap(vec, tmask, zm, cm, pool_tmask,
                                     existing_tmask, lattice)
            entry = [a_accel, _WAVE_UNSET]
            if slot is None:
                if len(_NARROW_CACHE) >= _NARROW_LATS:
                    _NARROW_CACHE.clear()
                slot = (lattice, {})
                _NARROW_CACHE[id(lattice)] = slot
            if len(slot[1]) >= _NARROW_MAX:
                slot[1].clear()
            slot[1][nkey] = entry
        if entry[0] is not None:
            new_raw = entry[0]
        elif (count >= _WAVE_MIN_PODS and ds_max is not None
                and pool_tmask.any()):
            if entry[1] is _WAVE_UNSET:
                entry[1] = _wave_candidates(vec, tmask, zm, cm, pool_tmask,
                                            ds_max, lattice)
            new_raw = (None if entry[1] is None
                       else _wave_mask_from_table(
                           entry[1], count, tmask, existing_tmask,
                           group.max_per_bin, total_pending))
        else:
            new_raw = None
    if prev_raw is None or new_raw is None:
        return prev_raw is None and new_raw is None
    return bool(np.array_equal(prev_raw, new_raw))


def _group_ledger(cap, g: PodGroup, np_type: np.ndarray,
                  np_zone: np.ndarray, np_cap: np.ndarray, NP: int):
    """One group's constraint-elimination ledger (solver/explain.py).
    O(stages) dot products over [T] per group — the per-pattern offering
    counts are memoized inside ``cap``, so same-shaped groups share
    every reduction."""
    vec, req_tmask, zm, cm = g._explain_ctx
    lattice = cap.lattice
    fits_t = (lattice.alloc >= vec[None, :]).all(axis=1)
    if g.np_ok.any():
        ptm = np_type[g.np_ok].any(axis=0)
        pzm = np_zone[g.np_ok].any(axis=0)
        pcm = np_cap[g.np_ok].any(axis=0)
    else:
        ptm = np.zeros(np_type.shape[1], dtype=bool)
        pzm = np.zeros(np_zone.shape[1], dtype=bool)
        pcm = np.zeros(np_cap.shape[1], dtype=bool)
    final = g.type_mask if g.unnarrowed_type_mask is not None else None
    notes: List[str] = []
    if g.single_bin:
        notes.append("hostname self-affinity: all replicas co-locate")
    if g.spread_class >= 0:
        notes.append(f"hostname spread: at most {g.max_per_bin} per node")
    elif g.max_per_bin < _BIG:
        notes.append(f"per-node cap: at most {g.max_per_bin}")
    if g.strict_custom:
        notes.append("strict custom-key constraints")
    if g.need is not None and g.need.any():
        notes.append("requires a co-located affinity class")
    if g.owner is not None and g.owner.any():
        notes.append("owns a hostname anti-affinity term")
    return cap.ledger(vec, fits_t, req_tmask, zm, cm, ptm, pzm, pcm,
                      final, g.signature, len(g.pod_names),
                      int(g.np_ok.sum()), NP, notes)


def build_problem(pods: Sequence[Pod], node_pools: Sequence[NodePool], lattice: Lattice,
                  existing: Sequence[ExistingBin] = (),
                  daemonset_pods: Sequence[Pod] = (),
                  bound_pods: Sequence[BoundPod] = (),
                  pvcs: Optional[Mapping] = None,
                  storage_classes: Optional[Mapping] = None,
                  pool_headroom: Optional[Mapping[str, np.ndarray]] = None,
                  narrow: bool = True, explain: bool = False) -> Problem:
    with _INTERN_LOCK:
        if len(_SIG_TUPLES) >= _INTERN_MAX:
            _RK_INTERN.clear()
            _SIG_IDS.clear()
            _SIG_TUPLES.clear()
            _BAD_SIDS.clear()
        return _build_problem(pods, node_pools, lattice, existing,
                              daemonset_pods, bound_pods, pvcs,
                              storage_classes, pool_headroom, narrow,
                              explain)


def _build_problem(pods: Sequence[Pod], node_pools: Sequence[NodePool], lattice: Lattice,
                   existing: Sequence[ExistingBin] = (),
                   daemonset_pods: Sequence[Pod] = (),
                   bound_pods: Sequence[BoundPod] = (),
                   pvcs: Optional[Mapping] = None,
                   storage_classes: Optional[Mapping] = None,
                   pool_headroom: Optional[Mapping[str, np.ndarray]] = None,
                   narrow: bool = True, explain: bool = False) -> Problem:
    real_pools = sorted(node_pools, key=lambda p: (-p.weight, p.name))
    T, Z, C = lattice.T, lattice.Z, lattice.C
    key_values = lattice.key_values_present()
    warnings: List[str] = []
    # pool masks build AFTER grouping: groups' custom-key demands against
    # pool-requirement-offered keys (Exists / In with free values) expand
    # the pool list with virtual labeled variants first (see below)

    # --- group pods by scheduling signature (one expensive compile per
    # distinct key; the per-pod loop is one dict hit + one pointer compare)
    unschedulable: Dict[str, str] = {}
    raw_groups: Dict[int, Tuple[Pod, List[str]]] = {}   # sig_id -> (rep, names)
    bad_claims: Dict[str, int] = {}   # PVC refs of unknown-resource pods
    order: List[int] = []
    relevant_keys = _selector_keys(pods, bound_pods)
    relevant_keys = _RK_INTERN.setdefault(relevant_keys, relevant_keys)
    memo: dict = {}
    # three-level grouping, fastest first:
    # 1. the per-pod cache (rk, sig_id) stored on the Pod — cluster state
    #    hands the SAME Pod objects to every scheduling pass (and every
    #    relaxation round), so after the first pass each pod costs one dict
    #    get and one pointer compare. Pod.__setattr__ drops the cache when
    #    any scheduling field is reassigned; relevant-keys changes miss on
    #    the interned rk pointer.
    # 2. an identity tuple over the field containers — pods stamped out from
    #    one controller template share the same requests/selector OBJECTS,
    #    so first-pass grouping needs no content hashing (identity is
    #    verified with `is` before use, so a recycled id() can never
    #    mis-group).
    # 3. the full content key (_group_key), interned to a small int.
    coarse: Dict[tuple, tuple] = {}   # identity key -> (rep pod, names or None)
    lab_rel = bool(relevant_keys)
    _SIG = "_kpat_sig"
    # bound `names.append` per live sid: the steady-state per-pod cost is
    # one dict get on the pod + one pointer compare + one dict get here +
    # one call — no tuple index or method-attribute lookup per pod (at
    # 50k pods those two extra ops alone are ~10 ms of the build budget)
    appenders: Dict[int, Any] = {}
    ap_get = appenders.get
    bad_get = _BAD_SIDS.get
    # run fast path: template-mates SHARE one cache tuple (the coarse
    # path below installs the rep's tuple on every mate), and waves
    # arrive in template order — a pointer match on the previous pod's
    # cache skips even the sid/appender lookups, leaving one dict get,
    # one `is`, and one append for most of a steady 50k wave (~5 ms off
    # the cfg5 build budget). Never armed for bad sids.
    prev_cache: Any = None
    prev_ap: Any = None
    for pod in pods:
        cache = pod.__dict__.get(_SIG)
        if cache is not None:
            if cache is prev_cache:
                prev_ap(pod.name)
                continue
            if cache[0] is relevant_keys:
                sid = cache[1]
                ap = ap_get(sid)
                if ap is not None:
                    prev_cache = cache
                    prev_ap = ap
                    ap(pod.name)
                    continue
                reason = bad_get(sid)
                if reason is not None:
                    unschedulable[pod.name] = reason
                    for c in pod.volume_claims:
                        bad_claims[c] = bad_claims.get(c, 0) + 1
                    continue
                names = [pod.name]
                raw_groups[sid] = (pod, names)
                ap = names.append
                appenders[sid] = ap
                prev_cache = cache
                prev_ap = ap
                order.append(sid)
                continue
        ck = (id(pod.requests) if pod.requests else 0,
              id(pod.node_selector) if pod.node_selector else 0,
              id(pod.required_affinity) if pod.required_affinity else 0,
              id(pod.preferred_affinity) if pod.preferred_affinity else 0,
              id(pod.tolerations) if pod.tolerations else 0,
              id(pod.topology_spread) if pod.topology_spread else 0,
              id(pod.pod_affinity) if pod.pod_affinity else 0,
              id(pod.volume_claims) if pod.volume_claims else 0,
              id(pod.labels) if (lab_rel and pod.labels) else 0)
        hit = coarse.get(ck)
        if hit is not None:
            rep, names = hit
            if (names is not None
                    and (not pod.requests or rep.requests is pod.requests)
                    and (not pod.node_selector or rep.node_selector is pod.node_selector)
                    and (not pod.required_affinity or rep.required_affinity is pod.required_affinity)
                    and (not pod.preferred_affinity or rep.preferred_affinity is pod.preferred_affinity)
                    and (not pod.tolerations or rep.tolerations is pod.tolerations)
                    and (not pod.topology_spread or rep.topology_spread is pod.topology_spread)
                    and (not pod.pod_affinity or rep.pod_affinity is pod.pod_affinity)
                    and (not pod.volume_claims or rep.volume_claims is pod.volume_claims)
                    and (not (lab_rel and pod.labels) or rep.labels is pod.labels)):
                names.append(pod.name)
                rc = rep.__dict__.get(_SIG)
                if rc is not None and rc[0] is relevant_keys:
                    pod.__dict__[_SIG] = rc
                continue
        sig = _group_key(pod, relevant_keys, memo)
        sid = _SIG_IDS.get(sig)
        if sid is None:
            sid = len(_SIG_TUPLES)
            _SIG_IDS[sig] = sid
            _SIG_TUPLES.append(sig)
            _, unknown = resources_to_vec_checked(pod.requests, implicit_pod=True)
            if unknown:
                _BAD_SIDS[sid] = taxonomy.reason(
                    taxonomy.UNKNOWN_RESOURCE,
                    f"unknown resource(s): {', '.join(unknown)}")
        pod.__dict__[_SIG] = (relevant_keys, sid)
        entry = raw_groups.get(sid)
        if entry is not None:
            entry[1].append(pod.name)
            if hit is None:
                coarse[ck] = (pod, entry[1])
            continue
        reason = _BAD_SIDS.get(sid)
        if reason is not None:
            unschedulable[pod.name] = reason
            for c in pod.volume_claims:
                bad_claims[c] = bad_claims.get(c, 0) + 1
            continue
        names = [pod.name]
        raw_groups[sid] = (pod, names)
        appenders[sid] = names.append
        order.append(sid)
        if hit is None:
            coarse[ck] = (pod, names)

    # unbound claims with multiple same-batch consumers pin to one zone,
    # chosen from the intersection of EVERY consumer's allowed zones (its
    # node-selector/affinity zone constraints plus its other claims' bound
    # zones) — a per-consumer first-eligible pick would diverge or falsely
    # exclude consumers whose own constraints forbid the picked zone
    # consumer counts come from the groups (all pods of a group share the
    # same claims list — it is part of the signature) plus the rare
    # unknown-resource pods tallied during the scan
    claim_refs: Dict[str, int] = dict(bad_claims)
    for sid in order:
        rep, names = raw_groups[sid]
        for c in rep.volume_claims:
            claim_refs[c] = claim_refs.get(c, 0) + len(names)
    shared_pins: Dict[str, Optional[int]] = {}
    shared = [c for c, n in claim_refs.items() if n > 1
              and pvcs and c in pvcs and pvcs[c].bound_zone is None]
    if shared:
        inter: Dict[str, np.ndarray] = {}
        scratch: List[str] = []
        for sid in order:
            rep, _names = raw_groups[sid]
            touches = [c for c in rep.volume_claims if c in shared]
            if not touches:
                continue
            m = compile_masks(rep.scheduling_requirements(), lattice,
                              skip_unresolved_custom=True).zone_mask
            m = m & _volume_zone_mask(rep, pvcs or {}, storage_classes or {},
                                      lattice.zones, scratch)
            for c in touches:
                inter[c] = m if c not in inter else (inter[c] & m)
        for c, m in inter.items():
            elig = np.nonzero(m)[0]
            if elig.size:
                shared_pins[c] = int(elig[0])
            else:
                shared_pins[c] = None
                warnings.append(
                    f"consumers of shared unbound PVC {c!r} have no common "
                    f"eligible zone; the volume can only bind for some of them")

    # --- virtual-pool expansion for custom-key label assignment
    # (reference scheduling.md:536-556, the Exists-operator workload
    # segregation): a pool whose TEMPLATE REQUIREMENT covers a custom key
    # (Exists, or In with several values) leaves the node's label value
    # free; a group demanding a concrete value gets a virtual variant of
    # that pool whose merged labels pin it. Bins then separate by value
    # through ordinary pool identity — conflicting groups can never share
    # a node — and everything downstream (np masks, weight order, claim
    # labels) treats the variant as just another pool. Limits, budgets,
    # and the drift hash roll up to ``base_name``.
    pool_reqs_real = [p.scheduling_requirements() for p in real_pools]

    # custom-key spread domains: every value a NodePool names for the key
    # (In-requirement values or a template label) — the reference
    # discovers spread domains from its NodePools the same way
    # (scheduling.md:312-446, :558-614 'virtual domains'). Values found
    # only on live nodes do NOT become split domains: no pool can launch
    # into them, so pinning a slice there would strand it (existing
    # matching pods still COUNT into the water-fill via bound_pods).
    custom_domains: Dict[str, List[str]] = {}

    def _add_domain(key: str, val: str) -> None:
        if _is_custom_key(key):
            vals = custom_domains.setdefault(key, [])
            if val not in vals:
                vals.append(val)
    for pool, preqs in zip(real_pools, pool_reqs_real):
        for key in preqs.keys():
            if _is_custom_key(key):
                c = preqs.get(key)
                if c.include:
                    for v in sorted(c.include):
                        _add_domain(key, v)
        for k, v in pool.labels.items():
            _add_domain(k, v)
        # effective template labels are domain sources too: every node of
        # a windows pool carries the build label even when the pool never
        # names it (mirrors the pool_eff_labels stamping below)
        if (pool_os(pool) == "windows"
                and wk.LABEL_WINDOWS_BUILD not in pool.labels):
            _add_domain(wk.LABEL_WINDOWS_BUILD, WINDOWS_BUILD)

    virtual: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], NodePool] = {}

    def _ensure_virtual(pool: NodePool, sigma: Dict[str, str]) -> None:
        vkey = (pool.name, tuple(sorted(sigma.items())))
        if vkey not in virtual:
            import dataclasses
            virtual[vkey] = dataclasses.replace(
                pool,
                name=pool.name + "@" + ",".join(
                    f"{k}={v}" for k, v in sorted(sigma.items())),
                labels={**pool.labels, **sigma},
                base_name=pool.base_name or pool.name,
                custom_labels=dict(sigma))

    for sid in order:
        rep, _names = raw_groups[sid]
        reqs = rep.scheduling_requirements()
        # generated value for existence-only demands: stable across passes
        # (content-derived, NOT the volatile group ordinal — otherwise a
        # later batch pins a different value and can never rejoin the node
        # the first batch labeled); the reference stamps a random label
        import hashlib
        gen = "kpat-" + hashlib.sha1(
            repr(_SIG_TUPLES[sid]).encode()).hexdigest()[:8]
        base_sigmas: Dict[str, Dict[str, str]] = {}
        if any(_is_custom_key(k) for k in reqs.keys()):
            for pool, preqs in zip(real_pools, pool_reqs_real):
                sigma = _resolve_custom_sigma(reqs, pool, preqs, gen)
                if sigma:
                    _ensure_virtual(pool, sigma)
                if sigma is not None:
                    base_sigmas[pool.name] = sigma
        # a DoNotSchedule spread over a custom key pins each slice to one
        # domain value: pre-materialize the per-domain pool variants,
        # COMPOSED with the group's own demand sigma (a group can pin
        # team=a and spread over rack at the same time)
        for c in rep.topology_spread:
            key = c.topology_key
            if not _is_custom_key(key) or c.when_unsatisfiable == "ScheduleAnyway":
                continue
            for d in custom_domains.get(key, ()):
                for pool, preqs in zip(real_pools, pool_reqs_real):
                    if key in pool.labels:
                        continue  # fixed-label pool serves its own domain
                    if key in set(preqs.keys()) and preqs.get(key).matches(d):
                        base = base_sigmas.get(pool.name, {})
                        if key in base:
                            continue  # demand already pins this key
                        _ensure_virtual(pool, {**base, key: d})
    # '@' sorts before alphanumerics, so on equal weight the REAL pool
    # still precedes its variants... actually '@'(0x40) < 'a', but the
    # real name is a strict prefix and strings compare prefix-first, so
    # "default" < "default@k=v": unconstrained groups keep preferring the
    # unlabeled base pool
    pools = sorted(list(real_pools) + list(virtual.values()),
                   key=lambda p: (-p.weight, p.name))
    NP = len(pools)

    # --- NodePool masks + daemonset overhead
    np_type = np.ones((NP, T), dtype=bool)
    np_zone = np.ones((NP, Z), dtype=bool)
    np_cap = np.ones((NP, C), dtype=bool)
    ds_overhead = np.zeros((NP, R), dtype=np.float32)
    np_alloc_cap = np.full((NP, R), np.inf, dtype=np.float32)
    # per-daemonset request vectors, computed ONCE (not per pool — the
    # csi_claims_count warning side effect must fire once per solve):
    # a daemonset mounting CSI PVCs consumes an attach slot on EVERY
    # node it lands on, so its overhead vector charges the axis like
    # pending groups do
    ds_prepared: List[Tuple[Pod, np.ndarray]] = []
    for ds in daemonset_pods:
        vec, unknown = resources_to_vec_checked(ds.requests, implicit_pod=True)
        if unknown:
            continue
        if ds.volume_claims:
            vec[res_axis("attachable-volumes")] = csi_claims_count(
                ds.volume_claims, pvcs or {}, storage_classes or {}, warnings)
        ds_prepared.append((ds, vec))
    pool_reqs: List[Requirements] = []
    pool_eff_labels: List[Mapping[str, str]] = []
    for pi, pool in enumerate(pools):
        if pool.kubelet is not None and pool.kubelet.max_pods is not None:
            # kubelet maxPods caps the pods axis of every node the pool
            # launches, below the ENI-derived density (reference
            # nodepools CRD spec.template.spec.kubelet)
            np_alloc_cap[pi, res_axis("pods")] = float(pool.kubelet.max_pods)
        reqs = pool.scheduling_requirements()
        # nodes of a pool boot ONE concrete OS (the AMI family's;
        # pool_os resolves it, default linux) — pin the pool's os
        # constraint to exactly that value so pod-vs-pool compatibility
        # and the launched node's label can never disagree, whatever
        # shape the user's os requirement took
        p_os = pool_os(pool)
        reqs = reqs.merge(Requirements(
            [Requirement(wk.LABEL_OS, Operator.IN, (p_os,))]))
        pool_reqs.append(reqs)
        # a pool's OWN value-free custom-key requirements (Exists / In on
        # user keys) are label templates its nodes will carry — never
        # lattice constraints; they must not zero the pool's masks
        # effective template labels: every windows node carries the
        # build label (cloudprovider.create stamps it), so pods selecting
        # on it resolve against this pool like any template label —
        # WITHOUT mutating the user's NodePool object
        eff = pool.labels
        if p_os == "windows" and wk.LABEL_WINDOWS_BUILD not in eff:
            eff = {**eff, wk.LABEL_WINDOWS_BUILD: WINDOWS_BUILD}
        pool_eff_labels.append(eff)
        m = compile_masks(reqs, lattice, extra_labels=eff,
                          skip_unresolved_custom=True)
        np_type[pi], np_zone[pi], np_cap[pi] = m.type_mask, m.zone_mask, m.cap_mask
        if pool_headroom is not None:
            # remaining limit budget caps a NEW node's size at solve time
            # (the reference narrows an in-flight node's instance-type
            # options as the pool approaches spec.limits) — limits roll up
            # to the base pool for virtual variants. The charge a node
            # makes against the limit is its CLAMPED capacity (kubelet
            # maxPods lowers the pods axis), so compare the clamped value
            rem = pool_headroom.get(pool.base_name or pool.name)
            if rem is not None:
                eff_capacity = np.minimum(lattice.capacity,
                                          np_alloc_cap[pi][None, :])
                np_type[pi] &= np.all(eff_capacity <= rem[None, :] + 1e-6,
                                      axis=1)
        for ds, vec in ds_prepared:
            # a daemonset lands on the pool's nodes iff it tolerates the pool
            # taints and its node selectors are compatible (reference
            # resolves daemonset overhead per simulated node the same way)
            # startupTaints clear before steady state: a daemonset still
            # runs (and costs overhead) on the pool's nodes even without
            # tolerating them (reference nodepools.md:484)
            if not tolerates_all(ds.tolerations, pool.taints):
                continue
            # hard rules only: a daemonset's zone/node PREFERENCE must not
            # drop its overhead from nodes it would still run on (in real
            # k8s the DS schedules there regardless; sizing must include it)
            ds_reqs = ds.hard_scheduling_requirements()
            if not ds_reqs.compatible_with(reqs):
                continue
            if not _custom_keys_ok(ds_reqs, pool_eff_labels[pi]):
                continue
            ds_overhead[pi] += vec

    # accelerator-capable EXISTING node types (see _accel_bin_cap: their
    # free capacity must stay joinable through any narrowed group mask)
    existing_tmask = np.zeros((T,), dtype=bool)
    for b in existing:
        ti = lattice.name_to_idx.get(b.instance_type)
        if ti is not None:
            existing_tmask[ti] = True

    # --- per raw group: masks, pool compatibility, topology resolution
    registry = ClassRegistry()
    # bound pods' hostname anti-affinity terms must be classes too — the k8s
    # symmetry check keeps pending matches OFF nodes whose resident pods own
    # such terms, even when no pending pod references the selector
    for bp in bound_pods:
        for term in bp.pod.pod_affinity:
            if term.anti and term.topology_key == wk.LABEL_HOSTNAME:
                registry.intern(tuple(term.label_selector))
    groups: List[PodGroup] = []
    pending_topo: List[Tuple[PodGroup, Pod, np.ndarray, np.ndarray]] = []  # group, rep, owner, need
    pending_spread_counts: Dict = {}  # (selector, key) -> planned per-domain adds
    for sid in order:
        rep, names = raw_groups[sid]
        sig = _SIG_TUPLES[sid]
        vec, _ = resources_to_vec_checked(rep.requests, implicit_pod=True)
        if rep.volume_claims:
            vec[res_axis("attachable-volumes")] = csi_claims_count(
                rep.volume_claims, pvcs or {}, storage_classes or {}, warnings)
        reqs = rep.scheduling_requirements()
        # custom-key constraints resolve exactly per-pool in np_ok below
        masks = compile_masks(reqs, lattice, skip_unresolved_custom=True)
        np_ok = np.zeros((NP,), dtype=bool)
        for pi, pool in enumerate(pools):
            # directional: pod requirements vs the pool's node template
            if not reqs.compatible_with(pool_reqs[pi]):
                continue
            # pods are NOT required to tolerate startupTaints — they are
            # temporary and cleared by an init daemon before steady-state
            # scheduling (reference nodepools.md:60-64,484: "pods aren't
            # required to tolerate these taints to be considered")
            if not tolerates_all(rep.tolerations, pool.taints):
                continue
            if not _custom_keys_ok(reqs, pool_eff_labels[pi]):
                continue
            merged = reqs.merge(pool_reqs[pi])
            if not merged.min_values_satisfied(key_values):
                continue
            np_ok[pi] = True
        strict = any(
            _is_custom_key(key) and not reqs.get(key).allows_absent
            for key in reqs.keys()
        )
        # unknown-pool existing bins (their NodePool is gone) are treated
        # as linux, the sim's universal default: a group whose os
        # constraint excludes linux must stay off them exactly like a
        # strict custom key (known-pool bins resolve os through np_ok)
        if wk.LABEL_OS in reqs.keys() \
                and not reqs.get(wk.LABEL_OS).matches("linux"):
            strict = True

        zone_mask_eff = masks.zone_mask
        if rep.volume_claims:
            zone_mask_eff = zone_mask_eff & _volume_zone_mask(
                rep, pvcs or {}, storage_classes or {}, lattice.zones, warnings,
                shared_pins=shared_pins)
        splits, topo, cut = resolve_group_topology(
            rep, len(names), zone_mask_eff, masks.cap_mask,
            lattice.zones, lattice.capacity_types, registry, bound_pods, warnings,
            pending_counts=pending_spread_counts,
            custom_domains=custom_domains)
        if cut > 0:
            for name in names[len(names) - cut:]:
                unschedulable[name] = taxonomy.reason(
                    taxonomy.ZONE_ANTI_AFFINITY,
                    "more replicas than eligible zones")
            names = names[: len(names) - cut]
        cursor = 0
        for s in splits:
            sub_names = names[cursor: cursor + s.count]
            cursor += s.count
            if not sub_names:
                continue
            np_ok_s = np_ok
            if s.custom:
                # custom-spread slice: only pools whose EFFECTIVE labels
                # (template labels + derived well-knowns like windows-build,
                # same map _custom_keys_ok resolves against) carry exactly
                # this slice's domain values may host it
                np_ok_s = np_ok & np.array(
                    [all(eff.get(k) == v for k, v in s.custom.items())
                     for eff in pool_eff_labels], dtype=bool)
            g_tmask = masks.type_mask
            unnarrowed = None
            narrow_ctx = None
            if narrow and not topo.single_bin:
                # accelerator bin-splitting (see _accel_bin_cap) — never
                # applied over hostname self-affinity's one-bin contract.
                # Ranking sees only offerings SOME compatible pool can
                # launch (union of pool type/zone/captype masks); the
                # feasibility gate below still holds the pre-narrowing
                # mask as a fallback for per-pool interactions the union
                # can't capture.
                any_pool = bool(np_ok_s.any())
                if any_pool:
                    pool_tmask = np_type[np_ok_s].any(axis=0)
                    pool_zmask = np_zone[np_ok_s].any(axis=0)
                    pool_cmask = np_cap[np_ok_s].any(axis=0)
                else:
                    pool_tmask = np.zeros(T, dtype=bool)
                    pool_zmask = np.zeros(Z, dtype=bool)
                    pool_cmask = np.zeros(C, dtype=bool)
                zm = s.zone_mask & pool_zmask
                cm = s.cap_mask & pool_cmask
                # heaviest compatible pool's daemonset overhead: ranking
                # with it keeps small types from being over-favored
                ds_max = (ds_overhead[np_ok_s].max(axis=0)
                          if any_pool else None)
                # the cached entry is COUNT-INDEPENDENT (accel mask +
                # wave candidate table); the cheap floor/gain decision
                # below re-runs per call so pod-count drift between
                # steady-state passes neither misses the cache nor
                # diverges from a from-scratch rebuild
                nkey = (lattice.price_version, vec.tobytes(),
                        masks.type_mask.tobytes(), zm.tobytes(),
                        cm.tobytes(), pool_tmask.tobytes(),
                        existing_tmask.tobytes(),
                        ds_max.tobytes() if ds_max is not None else b"")
                slot = _NARROW_CACHE.get(id(lattice))
                if slot is not None and slot[0] is not lattice:
                    slot = None                     # id reuse: stale slot
                entry = slot[1].get(nkey) if slot is not None else None
                if entry is None:
                    a_accel = _accel_bin_cap(
                        vec, masks.type_mask, zm, cm, pool_tmask,
                        existing_tmask, lattice)
                    # the wave table fills LAZILY (below): a batch of
                    # thousands of sub-threshold singleton groups must
                    # not pay the candidate reductions it will never use
                    entry = [a_accel, _WAVE_UNSET]
                    if slot is None:
                        if len(_NARROW_CACHE) >= _NARROW_LATS:
                            _NARROW_CACHE.clear()
                        slot = (lattice, {})
                        _NARROW_CACHE[id(lattice)] = slot
                    if len(slot[1]) >= _NARROW_MAX:
                        slot[1].clear()
                    slot[1][nkey] = entry
                a_accel = entry[0]
                if a_accel is not None:
                    a_mask = a_accel
                elif (len(sub_names) >= _WAVE_MIN_PODS and any_pool
                        and ds_max is not None):
                    # pods-axis-bound wave narrowing (generic groups
                    # only — accel groups are _accel_bin_cap's)
                    if entry[1] is _WAVE_UNSET:
                        entry[1] = _wave_candidates(
                            vec, masks.type_mask, zm, cm, pool_tmask,
                            ds_max, lattice)
                    a_mask = (None if entry[1] is None
                              else _wave_mask_from_table(
                                  entry[1], len(sub_names),
                                  masks.type_mask, existing_tmask,
                                  topo.max_per_bin, len(pods)))
                else:
                    a_mask = None
                # retained for solver/incremental.py recheck_narrow: the
                # raw (pre-feasibility-fallback) decision plus every
                # input needed to replay it at a drifted count
                narrow_ctx = (nkey, vec, masks.type_mask, zm, cm,
                              pool_tmask, ds_max, existing_tmask, a_mask)
                if a_mask is not None and a_mask.any():
                    unnarrowed = masks.type_mask
                    g_tmask = a_mask
            g = PodGroup(
                signature=repr(sig), pod_names=sub_names, req=vec,
                type_mask=g_tmask, zone_mask=s.zone_mask, cap_mask=s.cap_mask,
                np_ok=np_ok_s, requirements=reqs,
                max_per_bin=topo.max_per_bin, spread_class=topo.spread_class,
                single_bin=topo.single_bin,
                strict_custom=strict,
                unnarrowed_type_mask=unnarrowed,
            )
            g._narrow_ctx = narrow_ctx
            if explain:
                # the inputs the ledger build (below, after the
                # feasibility gate settles type masks) needs: the request
                # vector and the PRE-narrowing compiled masks
                g._explain_ctx = (vec, masks.type_mask,
                                  s.zone_mask, s.cap_mask)
            groups.append(g)
            pending_topo.append((g, rep, topo.owner, topo.need))

    # --- finalize affinity-class rows at full registry width
    A = registry.A
    for g, rep, owner, need in pending_topo:
        g.match = registry.match_row(rep.labels) if A else np.zeros((0,), dtype=bool)
        g.owner = np.zeros((A,), dtype=bool)
        g.need = np.zeros((A,), dtype=bool)
        if owner is not None and owner.size:
            g.owner[: owner.size] = owner
        if need is not None and need.size:
            g.need[: need.size] = need

    # mark groups with no feasible (pool, type, offering) at all.
    # fast path: when neither the group nor the pool restricts zones or
    # capacity types (the common case), feasibility collapses to one
    # T-wide AND against "type has ANY available offering" — the full
    # [T,Z,C] broadcast only runs for restricted combinations (measured
    # ~3 ms/build at 31 groups on the 759-type catalog otherwise)
    avail_t = lattice.available.any(axis=(1, 2))           # [T]
    np_zone_full = np_zone.all(axis=1)                     # [NP]
    np_cap_full = np_cap.all(axis=1)                       # [NP]

    def _has_offering(g) -> bool:
        g_free = bool(g.zone_mask.all()) and bool(g.cap_mask.all())
        for pi in np.nonzero(g.np_ok)[0]:
            if g_free and np_zone_full[pi] and np_cap_full[pi]:
                if (g.type_mask & np_type[pi] & avail_t).any():
                    return True
                continue
            tm = g.type_mask & np_type[pi]
            zm = g.zone_mask & np_zone[pi]
            cm = g.cap_mask & np_cap[pi]
            if (tm[:, None, None] & zm[None, :, None] & cm[None, None, :]
                    & lattice.available).any():
                return True
        return False

    ledger_cap = None
    if explain:
        from .explain import LedgerCapture
        ledger_cap = LedgerCapture(lattice)
    schedulable_groups: List[PodGroup] = []
    dropped_groups: List[PodGroup] = []
    for g in groups:
        feasible = _has_offering(g)
        if not feasible and g.unnarrowed_type_mask is not None:
            # accel narrowing must never COST schedulability: per-pool
            # interactions (zone pins, ICE, daemonset overhead at pack
            # time) the union-masked ranking can't see fall back to the
            # full mask (the pre-narrowing behavior)
            g.type_mask = g.unnarrowed_type_mask
            g.unnarrowed_type_mask = None
            feasible = _has_offering(g)
        if ledger_cap is not None:
            g.ledger = _group_ledger(ledger_cap, g, np_type, np_zone,
                                     np_cap, NP)
        if feasible or len(existing) > 0:
            # groups infeasible for new nodes may still fit existing capacity
            schedulable_groups.append(g)
        else:
            # the ledger refines the code: every compatible offering
            # eliminated by the ICE/unavailable mask is weather-caused
            # pending (ice-hold), not genuine infeasibility
            code = (g.ledger.blame_code() if g.ledger is not None
                    else "") or taxonomy.NO_OFFERING
            msg = taxonomy.reason(
                code, "all compatible offerings currently unavailable"
                if code == taxonomy.ICE_HOLD
                else "no compatible nodepool/instance-type offering")
            for name in g.pod_names:
                unschedulable[name] = msg
            dropped_groups.append(g)
    groups = schedulable_groups

    # --- FFD order: dominant normalized request, descending (the grouped
    # equivalent of the reference's pods-sorted-by-size FFD loop).
    # Groups with presence requirements (need) must come after potential
    # seeders, so they sort by a secondary "needs-presence" key.
    if groups:
        mean_alloc = np.maximum(lattice.alloc.mean(axis=0), 1e-6)  # [R]
        def ffd_key(g: PodGroup):
            norm = g.req / mean_alloc
            return (bool(g.need.any()), -float(norm.max()), -float(g.req[0]),
                    -float(g.req[1]), g.signature)
        groups.sort(key=ffd_key)

    G = len(groups)
    req = np.stack([g.req for g in groups]) if G else np.zeros((0, R), np.float32)
    count = np.array([len(g.pod_names) for g in groups], dtype=np.int32)
    g_type = np.stack([g.type_mask for g in groups]) if G else np.zeros((0, T), bool)
    g_zone = np.stack([g.zone_mask for g in groups]) if G else np.zeros((0, Z), bool)
    g_cap = np.stack([g.cap_mask for g in groups]) if G else np.zeros((0, C), bool)
    g_np = np.stack([g.np_ok for g in groups]) if G else np.zeros((0, NP), bool)
    max_per_bin = np.array([min(g.max_per_bin, _BIG) for g in groups], dtype=np.int32)
    g_spread = np.array([g.spread_class for g in groups], dtype=np.int32)
    single_bin = np.array([g.single_bin for g in groups], dtype=bool)
    g_match = np.stack([g.match for g in groups]) if G else np.zeros((0, A), bool)
    g_owner = np.stack([g.owner for g in groups]) if G else np.zeros((0, A), bool)
    g_need = np.stack([g.need for g in groups]) if G else np.zeros((0, A), bool)
    strict_custom = np.array([g.strict_custom for g in groups], dtype=bool)

    # --- existing bins
    E = len(existing)
    e_used = np.zeros((E, R), np.float32)
    e_alloc = np.zeros((E, R), np.float32)
    e_type = np.zeros((E,), np.int32)
    e_zone = np.zeros((E,), np.int32)
    e_cap = np.zeros((E,), np.int32)
    e_np = np.full((E,), -1, np.int32)
    e_pm = np.zeros((E, A), np.int32)
    e_po = np.zeros((E, A), bool)
    pool_index = {p.name: i for i, p in enumerate(pools)}
    by_base: Dict[str, List[int]] = {}
    for pi, p in enumerate(pools):
        by_base.setdefault(p.base_name or p.name, []).append(pi)
    zone_index = {z: i for i, z in enumerate(lattice.zones)}
    cap_index = {c: i for i, c in enumerate(lattice.capacity_types)}
    bin_index = {b.name: i for i, b in enumerate(existing)}

    def bin_pool(b: ExistingBin) -> int:
        """The most specific pool variant a bin's node labels realize —
        a node labeled team=a belongs to the team=a virtual variant, so
        groups demanding that value can join it and conflicting groups
        cannot."""
        best, score = pool_index.get(b.node_pool, -1), -1
        for pi in by_base.get(b.node_pool, ()):
            sigma = pools[pi].custom_labels
            if all(b.labels.get(k) == v for k, v in sigma.items()) \
                    and len(sigma) > score:
                best, score = pi, len(sigma)
        return best

    for ei, b in enumerate(existing):
        ti = lattice.name_to_idx[b.instance_type]
        e_used[ei] = b.used
        if b.alloc_override is not None:
            # NaN marks axes the node did not report (canonical_to_vec
            # missing=nan): fall back to the lattice's prediction — e.g.
            # attachable-volumes before the CSINode registers
            ov = b.alloc_override
            e_alloc[ei] = np.where(np.isnan(ov), lattice.alloc[ti], ov)
        else:
            e_alloc[ei] = lattice.alloc[ti]
        e_type[ei] = ti
        e_zone[ei] = zone_index[b.zone]
        e_cap[ei] = cap_index[b.capacity_type]
        e_np[ei] = bin_pool(b)
    # seed affinity-class presence on existing bins from bound pods
    if A:
        for bp in bound_pods:
            ei = bin_index.get(bp.node_name)
            if ei is None:
                continue
            e_pm[ei] += registry.match_row(bp.pod.labels).astype(np.int32)
            for term in bp.pod.pod_affinity:
                if term.anti and term.topology_key == wk.LABEL_HOSTNAME:
                    key = tuple(sorted(term.label_selector))
                    a = registry.index.get(key)
                    if a is not None:
                        e_po[ei, a] = True

    return Problem(
        lattice=lattice, node_pools=pools, groups=groups, existing=list(existing),
        unschedulable=unschedulable,
        req=req.astype(np.float32), count=count, g_type=g_type, g_zone=g_zone,
        g_cap=g_cap, g_np=g_np, max_per_bin=max_per_bin, g_spread=g_spread,
        single_bin=single_bin,
        g_match=g_match, g_owner=g_owner, g_need=g_need, strict_custom=strict_custom,
        warnings=list(dict.fromkeys(warnings)),  # distinct notices once each
        dropped_groups=dropped_groups,
        np_type=np_type, np_zone=np_zone, np_cap=np_cap, ds_overhead=ds_overhead,
        np_alloc_cap=np_alloc_cap,
        e_used=e_used, e_alloc=e_alloc, e_type=e_type, e_zone=e_zone, e_cap=e_cap,
        e_np=e_np, e_pm=e_pm, e_po=e_po,
    )
