"""Revision-keyed incremental problem build for steady-state reconciles.

BENCH_r05 put the host share of the 50k-pod e2e p50 at ~35 ms, almost
all of it rebuilding and re-tensorizing the ENTIRE problem from scratch
every provisioning pass — even when <5% of the pods changed since the
last one. This module closes that gap: the :class:`IncrementalProblemBuilder`
retains the previous :class:`~.problem.Problem` keyed by the cluster
state revision (state/cluster.py dirty journal) and, when the pass's
churn is local, produces the next problem by patching ONLY what moved:

- journal-touched pods are matched to the previous build's signature
  groups (the same interned signatures build_problem groups with) and
  their groups' membership lists/counts updated in copy-on-write form;
- the existing-bin arrays are re-derived from the current bin list (an
  O(E) numpy pass — bins are hundreds where pods are tens of thousands);
- every retained group's count-dependent narrowing decision is replayed
  against the content-cached candidate tables
  (solver/problem.py recheck_narrow) — a flipped decision aborts to a
  full rebuild, so the incremental problem is always plan-equivalent to
  a from-scratch build.

Everything else — one gate failing, a new scheduling signature, topology
/affinity/volume machinery in play, pool or lattice or daemonset drift —
falls back to :func:`~.problem.build_problem`, the always-correct path.
The builder never guesses: any doubt → rebuild, and the randomized
churn-sequence parity test (tests/test_incremental.py) pins the
equivalence at every step.

The provisioning controller owns one builder per Provisioner and hands
the resulting problem to ``Solver.solve_delta`` (solver/solve.py),
which since PR 14 runs the device-resident reconcile MICROLOOP
(docs/reference/microloop.md): the whole fused problem stays resident
on device, the patched build here becomes one dirty-block donated
scatter over the link, and the plan only syncs back when an on-device
fingerprint says it moved — together the <20 ms steady-state reconcile
path of ROADMAP open item 2. The journal this builder consumes arrives
pre-coalesced (state/cluster.py DirtyJournalCoalescer batches ticks
between passes); ``BuildResult.journal_ticks`` records how many.

Delta-on-mesh (PR 12, docs/reference/sharding.md): the builder is
deliberately mesh-AGNOSTIC — the patched problem it produces is the
same whether one device or eight solve it. The shard-awareness lives
one layer down: ``solve_delta`` rides the boot-planned mesh, the
resident input cache keys its entries by device count and pins them
with the mesh-replicated sharding, and a mesh-shape change invalidates
the resident state rather than delta-hitting stale shards — so a
steady-state reconcile stays incremental (dirty blocks over the link,
never a full re-upload) on a multi-chip deployment exactly as it does
on one device. The delta-vs-full parity this module pins therefore
holds per-plan on the mesh too (tests/test_mesh.py; MULTICHIP_r06's
delta-on-mesh row records it at 20k pods).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..apis.objects import NodePool, Pod
from ..lattice.tensors import Lattice
from .problem import (Problem, _BIG, build_problem, recheck_narrow,
                      signature_of)

# an incremental pass touching more than this fraction of the previous
# build's pods rebuilds instead: the per-pod patch path's constant
# factors beat the vectorized full build only while churn is local
_MAX_CHURN_FRACTION = 0.25


@dataclass
class BuildResult:
    problem: Problem
    incremental: bool = False
    dirty_groups: Tuple[int, ...] = ()
    reason: str = ""            # why a full rebuild ran ("" = incremental)
    rev: int = -1               # cluster-state revision this build is keyed at
    journal_ticks: int = 1      # coalesced journal drains behind this build
                                # (>1 = the controller fell behind and the
                                # coalescer batched ticks into one delta)


def _resolve(x):
    """Inputs may arrive as values or as zero-arg thunks; thunks let the
    provisioner skip O(pods) cluster scans (existing_bins, bound_pods)
    entirely on passes where the journal proves they did not change."""
    return x() if callable(x) else x


def _pool_fingerprint(pools: Sequence[NodePool]) -> tuple:
    """Cheap content fingerprint of everything about a NodePool that
    feeds build_problem (masks, taints/tolerations, weight order,
    kubelet clamp, virtual-pool expansion inputs). Pools are few; this
    is microseconds."""
    out = []
    for p in pools:
        out.append((
            p.name, p.weight, p.node_class_ref,
            tuple(sorted(p.labels.items())),
            tuple(sorted((t.key, t.value or "", t.effect)
                         for t in p.taints)),
            tuple(sorted((r.key, r.operator.value,
                          tuple(sorted(str(v) for v in r.values)))
                         for r in p.requirements)),
            (p.kubelet.max_pods if p.kubelet is not None else None),
        ))
    return tuple(sorted(out))


def _headroom_fingerprint(h: Optional[Mapping[str, np.ndarray]]):
    if not h:
        return None
    return {k: v.tobytes() for k, v in h.items()}


class IncrementalProblemBuilder:
    """Stateful wrapper over build_problem with a delta fast path.

    Thread-compat: ONE owner (the provisioner serializes passes); the
    builder itself keeps no locks.
    """

    def __init__(self, explain: bool = True):
        # capture constraint-elimination ledgers on every full build
        # (solver/explain.py); the delta path patches them copy-on-write
        self._explain = explain
        self._prev: Optional[Problem] = None
        self._rev: int = -1
        self._lattice: Optional[Lattice] = None
        self._price_version: int = -1
        self._pool_fp: Optional[tuple] = None
        self._headroom_fp = None
        self._simple = False        # prev build eligible for deltas at all
        self._sig_to_gi: Dict[str, int] = {}
        self._pod_to_gi: Optional[Dict[str, int]] = None   # lazy
        self._dropped_pods: frozenset = frozenset()
        self._bin_types: frozenset = frozenset()
        # observability (Solver.stats folds the solve-side counters; the
        # provisioner provider folds these)
        self.incremental_builds = 0
        self.full_builds = 0
        self.last_reason = ""

    @property
    def rev(self) -> int:
        """The cluster-state revision of the retained build (-1 = cold);
        the provisioner reads the dirty journal from here."""
        return self._rev

    # ---- stats ----------------------------------------------------------

    def stats(self) -> Dict[str, object]:
        return {
            "incremental_builds": self.incremental_builds,
            "full_builds": self.full_builds,
            "eligible": self._simple,
        }

    # ---- the entry point ------------------------------------------------

    def build(self, pods: Sequence[Pod], node_pools: Sequence[NodePool],
              lattice: Lattice, existing=(), daemonset_pods=(),
              bound_pods=(), pvcs=None, storage_classes=None,
              pool_headroom=None, dirty=None,
              touched: Optional[Mapping[str, Tuple[str, Optional[Pod]]]]
              = None) -> BuildResult:
        """Build the problem for ``pods``, incrementally when the dirty
        set allows. ``existing``/``daemonset_pods``/``bound_pods``/
        ``pvcs``/``storage_classes`` may be values or zero-arg thunks
        (resolved only when actually needed). ``dirty`` is a
        state/cluster.py DirtySet; ``touched`` maps each dirty pod name
        to its (state, pod) classification (ClusterState.touched_pods).
        """
        ticks = dirty.ticks if dirty is not None else 1
        reason = self._delta_blocker(pods, node_pools, lattice,
                                     pool_headroom, dirty, touched)
        if reason is None:
            res = self._build_delta(pods, lattice, existing, dirty, touched)
            if res is not None:
                self.incremental_builds += 1
                self.last_reason = ""
                res.journal_ticks = ticks
                return res
            reason = self.last_reason or "delta-failed"
        res = self._build_full(pods, node_pools, lattice, existing,
                               daemonset_pods, bound_pods, pvcs,
                               storage_classes, pool_headroom, dirty,
                               reason)
        res.journal_ticks = ticks
        return res

    # ---- gates ----------------------------------------------------------

    def _delta_blocker(self, pods, node_pools, lattice, pool_headroom,
                       dirty, touched) -> Optional[str]:
        """The any-doubt-→-rebuild gate ladder. Returns the blocking
        reason, or None when the delta path may run."""
        if dirty is None:
            return "no-dirty-set"
        if self._prev is None:
            return "cold"
        if dirty.full or dirty.other:
            return "journal-overflow" if dirty.full else "untracked-mutation"
        if dirty.since != self._rev:
            return "revision-skew"
        if not self._simple:
            return self.last_reason or "complex-problem"
        if dirty.volumes:
            return "volume-churn"
        if dirty.daemonsets:
            return "daemonset-churn"
        if lattice is not self._lattice:
            return "lattice-changed"
        if lattice.price_version != self._price_version:
            return "price-changed"
        if touched is None and dirty.pods:
            return "no-touched-classification"
        if len(dirty.pods) > max(64, int(
                _MAX_CHURN_FRACTION * max(len(pods), 1))):
            return "bulk-churn"
        if _pool_fingerprint(node_pools) != self._pool_fp:
            return "pools-changed"
        hfp = _headroom_fingerprint(_resolve(pool_headroom))
        if hfp != self._headroom_fp:
            return "headroom-changed"
        return None

    @staticmethod
    def _eligibility(problem: Problem, pods: Sequence[Pod],
                     bound_pods: Sequence) -> str:
        """Why this build can NOT seed deltas ("" = it can). The simple
        shape the delta path supports: one group per signature, no
        affinity classes / topology splits / virtual pools / volume zone
        pins / relaxable soft constraints — the steady-state common case."""
        from .problem import _selector_keys
        if _selector_keys(pods, bound_pods):
            # ANY selector key in play (a bound pod's spread/affinity
            # counts even when no class compiled) changes how labels
            # project into signatures — signature_of's churned-pod
            # matching assumes the empty projection
            return "selector-keys"
        if problem.A:
            return "affinity-classes"
        if any(p.custom_labels for p in problem.node_pools):
            return "virtual-pools"
        if problem.G:
            if problem.single_bin.any():
                return "single-bin-groups"
            if (problem.g_spread != -1).any():
                return "spread-classes"
            if (problem.max_per_bin < _BIG).any():
                return "per-bin-caps"
            if problem.strict_custom.any():
                return "strict-custom-keys"
        # one O(pods) scan, paid ONCE per full build: anything with
        # selector/topology machinery, volumes, or relaxable soft
        # constraints takes the always-correct full path
        for p in pods:
            d = p.__dict__
            if (d.get("pod_affinity") or d.get("topology_spread")
                    or d.get("preferred_affinity")
                    or d.get("volume_claims")):
                return "complex-pods"
        return ""

    # ---- full build ------------------------------------------------------

    def _build_full(self, pods, node_pools, lattice, existing,
                    daemonset_pods, bound_pods, pvcs, storage_classes,
                    pool_headroom, dirty, reason) -> BuildResult:
        existing = _resolve(existing) or ()
        headroom = _resolve(pool_headroom)
        bound = _resolve(bound_pods) or ()
        problem = build_problem(
            pods, node_pools, lattice, existing=existing,
            daemonset_pods=_resolve(daemonset_pods) or (),
            bound_pods=bound,
            pvcs=_resolve(pvcs), storage_classes=_resolve(storage_classes),
            pool_headroom=headroom, explain=self._explain)
        self.full_builds += 1
        self.last_reason = reason
        self._prev = problem
        self._rev = dirty.rev if dirty is not None else -1
        self._lattice = lattice
        self._price_version = lattice.price_version
        self._pool_fp = _pool_fingerprint(node_pools)
        self._headroom_fp = _headroom_fingerprint(headroom)
        self._pod_to_gi = None   # rebuilt lazily on the first delta
        self._dropped_pods = frozenset(
            n for g in problem.dropped_groups for n in g.pod_names)
        self._bin_types = frozenset(b.instance_type for b in existing)
        blocker = self._eligibility(problem, pods, bound)
        # a signature appearing in TWO groups (topology split slipped the
        # gates) would make pod→group matching ambiguous
        self._sig_to_gi = {}
        for gi, g in enumerate(problem.groups):
            if not blocker and g.signature in self._sig_to_gi:
                blocker = "split-signature"
            self._sig_to_gi[g.signature] = gi
        self._simple = not blocker
        self.last_reason = blocker or reason
        return BuildResult(problem=problem, incremental=False,
                           reason=reason, rev=self._rev)

    # ---- the delta path --------------------------------------------------

    def _pod_map(self) -> Dict[str, int]:
        """pod name -> group index of the previous build (lazy: one
        O(pods) dict build per FULL build, amortized across every delta
        that follows it)."""
        if self._pod_to_gi is None:
            m: Dict[str, int] = {}
            for gi, g in enumerate(self._prev.groups):
                for n in g.pod_names:
                    m[n] = gi
            self._pod_to_gi = m
        return self._pod_to_gi

    def _build_delta(self, pods, lattice, existing, dirty,
                     touched) -> Optional[BuildResult]:
        prev = self._prev
        pod_map = self._pod_map()
        unschedulable = None     # copy-on-write
        new_names: Dict[int, List[str]] = {}
        dirty_gis: set = set()

        def names_of(gi: int) -> List[str]:
            lst = new_names.get(gi)
            if lst is None:
                lst = list(prev.groups[gi].pod_names)
                new_names[gi] = lst
                dirty_gis.add(gi)
            return lst

        removed: Dict[int, set] = {}
        adds: List[Tuple[str, Pod]] = []
        for name in (dirty.pods if dirty is not None else ()):
            if name in self._dropped_pods:
                # a build-time-dropped group's membership changed: the
                # retained dropped_groups (and their ledgers) would go
                # stale and explain differently from a full rebuild —
                # parity over speed, always
                self.last_reason = "dropped-group-churn"
                return None
            state, pod = (touched.get(name, ("gone", None))
                          if touched is not None else ("gone", None))
            gi = pod_map.get(name)
            if gi is not None:
                removed.setdefault(gi, set()).add(name)
                del pod_map[name]
            if unschedulable is None and name in prev.unschedulable:
                unschedulable = dict(prev.unschedulable)
            if unschedulable is not None:
                unschedulable.pop(name, None)
            if state == "daemonset":
                self.last_reason = "daemonset-churn"
                return None
            if pod is not None:
                d = pod.__dict__
                if (d.get("pod_affinity") or d.get("topology_spread")
                        or d.get("volume_claims")):
                    # a touched pod with selector/volume machinery in ANY
                    # state changes semantics the retained build never
                    # compiled — a pod first seen BOUND with anti-affinity
                    # must repel matching pending pods (the k8s symmetry
                    # rule), which only a full rebuild's bound-pod class
                    # compilation can express
                    self.last_reason = "complex-pod-churn"
                    return None
            if state == "pending":
                adds.append((name, pod))

        # apply removals group-by-group (one list rebuild per dirty group)
        for gi, gone in removed.items():
            lst = names_of(gi)
            new_names[gi] = [n for n in lst if n not in gone]

        # re-add pending pods by signature; an unknown signature means a
        # shape this build has never compiled → full rebuild
        for name, pod in adds:
            sig, bad = signature_of(pod)
            if bad is not None:
                if unschedulable is None:
                    unschedulable = dict(prev.unschedulable)
                unschedulable[name] = bad
                continue
            gi = self._sig_to_gi.get(sig)
            if gi is None:
                self.last_reason = "new-signature"
                return None
            names_of(gi).append(name)
            pod_map[name] = gi

        count = prev.count
        if dirty_gis:
            count = prev.count.copy()
            for gi in dirty_gis:
                count[gi] = len(new_names[gi])
        total = int(count.sum())
        unsched = (unschedulable if unschedulable is not None
                   else prev.unschedulable)
        if total + len(unsched) != len(pods):
            # the journal and the pending snapshot disagree (a race in
            # the threaded stratum, or an untracked path) — never ship a
            # problem that doesn't cover exactly the pending set
            self.last_reason = "count-mismatch"
            return None

        # replay every retained group's count-dependent narrowing against
        # the cached candidate tables; one flipped decision → rebuild.
        # total_pending replays as len(pods) — exactly what a from-scratch
        # build_problem passes (unschedulable pods included), which the
        # count guard above just proved consistent
        for gi, g in enumerate(prev.groups):
            if not recheck_narrow(g, int(count[gi]), len(pods), lattice):
                self.last_reason = "narrow-flip"
                return None

        # existing bins: re-derive the arrays only when the journal says
        # they moved; the bin TYPE set changing affects narrowing and
        # feasibility of retained groups → rebuild
        if dirty is not None and dirty.bins:
            existing = list(_resolve(existing) or ())
            if (len(existing) > 0) != (prev.E > 0):
                self.last_reason = "bin-presence-flip"
                return None
            if frozenset(b.instance_type for b in existing) != self._bin_types:
                self.last_reason = "bin-types-changed"
                return None
            e_arrays = self._existing_arrays(existing, lattice, prev)
        else:
            existing = prev.existing
            e_arrays = None

        groups = prev.groups
        if dirty_gis:
            groups = list(prev.groups)
            for gi in dirty_gis:
                g = replace(prev.groups[gi], pod_names=new_names[gi])
                g._narrow_ctx = getattr(prev.groups[gi], "_narrow_ctx", None)
                if g.ledger is not None:
                    # ledger copy-on-write: the stage counts are count-
                    # independent (recheck_narrow above proved the one
                    # count-dependent decision unchanged), so only the
                    # pods field moves — a delta-built pass explains
                    # identically to a full rebuild (parity-pinned)
                    g.ledger = g.ledger.with_count(len(new_names[gi]))
                groups[gi] = g
        problem = replace(
            prev, groups=groups, count=count,
            existing=list(existing),
            unschedulable=(unschedulable if unschedulable is not None
                           else dict(prev.unschedulable)),
            **(e_arrays or {}))

        self._prev = problem
        self._rev = dirty.rev
        self._sig_to_gi = {g.signature: gi for gi, g in enumerate(groups)} \
            if dirty_gis else self._sig_to_gi
        return BuildResult(problem=problem, incremental=True,
                           dirty_groups=tuple(sorted(dirty_gis)),
                           rev=self._rev)

    @staticmethod
    def _existing_arrays(existing, lattice: Lattice,
                         prev: Problem) -> Dict[str, np.ndarray]:
        """The existing-bin tail of build_problem for the simple shape
        (no affinity classes, no virtual pools): an O(E) pass over
        hundreds of bins where the full build re-scans tens of thousands
        of pods."""
        E = len(existing)
        from ..apis.resources import R
        e_used = np.zeros((E, R), np.float32)
        e_alloc = np.zeros((E, R), np.float32)
        e_type = np.zeros((E,), np.int32)
        e_zone = np.zeros((E,), np.int32)
        e_cap = np.zeros((E,), np.int32)
        e_np = np.full((E,), -1, np.int32)
        pool_index = {p.name: i for i, p in enumerate(prev.node_pools)}
        zone_index = {z: i for i, z in enumerate(lattice.zones)}
        cap_index = {c: i for i, c in enumerate(lattice.capacity_types)}
        for ei, b in enumerate(existing):
            ti = lattice.name_to_idx[b.instance_type]
            e_used[ei] = b.used
            if b.alloc_override is not None:
                ov = b.alloc_override
                e_alloc[ei] = np.where(np.isnan(ov), lattice.alloc[ti], ov)
            else:
                e_alloc[ei] = lattice.alloc[ti]
            e_type[ei] = ti
            e_zone[ei] = zone_index[b.zone]
            e_cap[ei] = cap_index[b.capacity_type]
            e_np[ei] = pool_index.get(b.node_pool, -1)
        A = prev.A
        return dict(e_used=e_used, e_alloc=e_alloc, e_type=e_type,
                    e_zone=e_zone, e_cap=e_cap, e_np=e_np,
                    e_pm=np.zeros((E, A), np.int32),
                    e_po=np.zeros((E, A), bool))
