"""Decision explainability: constraint-elimination ledgers + audit ring.

The observability stack answers *how fast* (traces, stage timings, SLO
burn) and *how contended* (profiler, lock-order witness); this module
answers *why this decision* — the question the reference's
`FailedScheduling` events and nodeclaim status conditions exist for.

During problem build, every signature group gets a **candidate-
elimination ledger**: how many (and which, top-k) instance-type × zone ×
capacity-type offerings each constraint stage removed —

    offered → resource-fit → requirements → pools → ice → narrowing

— computed per GROUP, so the cost is O(G × stages) dot products over the
[T] axis (the per-(zone,captype)-pattern offering counts are memoized),
never O(pods × 759). After the solve, the provisioning controller folds
the plan's outcome on top (placed/unplaced per group, the chosen
offering + runner-up + price delta per created claim, unschedulable
reason codes from solver/taxonomy.py) into a :class:`PassExplanation`,
and a bounded :class:`DecisionAuditRing` keyed by pass/trace id serves
it everywhere the existing stack taught us to look: the ``explain``
introspection provider, ``/debug/explain`` on both HTTP servers, and
``kpctl explain pod|nodeclaim|pass``.

Ledgers survive the delta path: `IncrementalProblemBuilder` patches a
retained group's ledger copy-on-write (`GroupLedger.with_count`) — the
stage counts are count-independent and `recheck_narrow` already proved
the one count-dependent decision (price narrowing) unchanged, so a
delta-built pass explains identically to a full rebuild
(tests/test_explain.py parity test).
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import taxonomy

# ledger stage names, waterfall order (docs/reference/explain.md)
STAGE_OFFERED = "offered"
STAGE_RESOURCES = "resource-fit"
STAGE_REQUIREMENTS = "requirements"
STAGE_POOLS = "pools"
STAGE_ICE = "ice"
STAGE_NARROWING = "narrowing"
STAGES = (STAGE_OFFERED, STAGE_RESOURCES, STAGE_REQUIREMENTS,
          STAGE_POOLS, STAGE_ICE, STAGE_NARROWING)

_MAX_EXAMPLES = 3


@dataclass(frozen=True)
class StageRow:
    """One waterfall row: offerings remaining after this stage, how many
    the stage removed, and up to top-k concrete eliminated offerings."""

    stage: str
    remaining: int
    eliminated: int
    examples: Tuple[str, ...] = ()

    def to_doc(self) -> dict:
        d = {"stage": self.stage, "remaining": self.remaining,
             "eliminated": self.eliminated}
        if self.examples:
            d["examples"] = list(self.examples)
        return d


@dataclass(frozen=True)
class GroupLedger:
    """Per-signature-group elimination record. Count-independent except
    the ``pods`` field — exactly what lets the incremental builder patch
    a retained group's ledger with :meth:`with_count` instead of
    recomputing (the narrowing stage's count-dependence is guarded by
    recheck_narrow, which forces a full rebuild on any flip)."""

    label: str                     # human request label ("cpu=500m ...")
    signature: str                 # the group's interned signature repr
    pods: int
    stages: Tuple[StageRow, ...]
    pools_ok: int = 0              # compatible NodePools
    pools_total: int = 0
    notes: Tuple[str, ...] = ()    # affinity/topology-class constraints

    @property
    def remaining(self) -> int:
        return self.stages[-1].remaining if self.stages else 0

    def blame(self) -> str:
        """The stage that first took the group to zero offerings, or ""
        while offerings remain."""
        if self.remaining > 0:
            return ""
        prev = None
        for row in self.stages:
            if row.remaining == 0 and (prev is None or prev.remaining > 0):
                return row.stage
            prev = row
        return self.stages[0].stage if self.stages else ""

    def blame_code(self) -> str:
        """Refine a zero-offering group into a taxonomy code: an ICE-
        zeroed group is weather-caused pending (ice-hold), anything else
        is genuinely incompatible (no-offering)."""
        b = self.blame()
        if not b:
            return ""
        return taxonomy.ICE_HOLD if b == STAGE_ICE else taxonomy.NO_OFFERING

    def with_count(self, pods: int) -> "GroupLedger":
        """Copy-on-write count patch for the incremental build path."""
        return self if pods == self.pods else replace(self, pods=pods)

    def to_doc(self) -> dict:
        return {
            "label": self.label, "pods": self.pods,
            "poolsOk": self.pools_ok, "poolsTotal": self.pools_total,
            "remaining": self.remaining, "blame": self.blame(),
            "stages": [s.to_doc() for s in self.stages],
            **({"notes": list(self.notes)} if self.notes else {}),
        }


def request_label(vec: np.ndarray) -> str:
    """A human label for a group's request vector ("cpu=500m
    memory=1024Mi"), rendered from the non-zero axes. The implicit
    one-pod occupancy every real pod carries is dropped — it is not a
    user request."""
    from ..apis.resources import vec_to_quantities
    q = vec_to_quantities(vec)
    if q.get("pods") == "1":
        del q["pods"]
    parts = [f"{k}={v}" for k, v in q.items()]
    return " ".join(parts) or "(no requests)"


class LedgerCapture:
    """Per-build elimination accounting. One instance per build_problem
    call; the per-(availability, zone-mask, captype-mask) PATTERN type
    counts are memoized, so each group's stage rows cost a handful of
    [T] dot products — groups stamped from the same deployment share
    every pattern."""

    def __init__(self, lattice):
        base = getattr(lattice, "base_available", None)
        self.base = base if base is not None else lattice.available
        self.masked = lattice.available
        self.lattice = lattice
        self.offered = int(self.base.sum())
        self._counts: Dict[tuple, np.ndarray] = {}
        self._gone: Optional[np.ndarray] = None   # base & ~masked, lazy
        self._ones_z = np.ones((lattice.Z,), dtype=bool)
        self._ones_c = np.ones((lattice.C,), dtype=bool)

    def _per_type(self, which: str, zm: np.ndarray,
                  cm: np.ndarray) -> np.ndarray:
        key = (which, zm.tobytes(), cm.tobytes())
        c = self._counts.get(key)
        if c is None:
            av = self.base if which == "base" else self.masked
            c = (av & zm[None, :, None]
                 & cm[None, None, :]).sum(axis=(1, 2)).astype(np.int64)
            self._counts[key] = c
        return c

    def count(self, which: str, tm: np.ndarray, zm: np.ndarray,
              cm: np.ndarray) -> int:
        return int(self._per_type(which, zm, cm) @ tm)

    def _examples(self, tmask: np.ndarray, zm: np.ndarray, cm: np.ndarray,
                  gone: np.ndarray, k: int = _MAX_EXAMPLES) -> Tuple[str, ...]:
        """Up to k concrete offerings in (tmask × zm × cm) present in
        ``gone`` (a [T,Z,C] bool of eliminated cells). Early-exits at k."""
        lat = self.lattice
        out: List[str] = []
        for ti in np.nonzero(tmask)[0]:
            cells = gone[ti] & zm[:, None] & cm[None, :]
            for zi, ci in np.argwhere(cells):
                out.append(f"{lat.names[ti]}/{lat.zones[zi]}/"
                           f"{lat.capacity_types[ci]}")
                if len(out) >= k:
                    return tuple(out)
        return tuple(out)

    def ledger(self, vec: np.ndarray, fits_t: np.ndarray,
               req_tmask: np.ndarray, zm: np.ndarray, cm: np.ndarray,
               pool_tmask: np.ndarray, pool_zmask: np.ndarray,
               pool_cmask: np.ndarray, final_tmask: Optional[np.ndarray],
               signature: str, pods: int, pools_ok: int, pools_total: int,
               notes: Sequence[str] = ()) -> GroupLedger:
        """Build one group's waterfall. ``fits_t`` = types whose empty
        node holds one pod; ``req_tmask``/``zm``/``cm`` = the compiled
        requirement masks (pre-narrowing); ``pool_*`` = the union of
        compatible pools' masks; ``final_tmask`` = the narrowed type
        mask actually shipped (None when narrowing didn't engage)."""
        rows: List[StageRow] = [StageRow(STAGE_OFFERED, self.offered, 0)]

        def push(stage, remaining, examples=()):
            rows.append(StageRow(stage, remaining,
                                 max(rows[-1].remaining - remaining, 0),
                                 tuple(examples)))

        push(STAGE_RESOURCES,
             self.count("base", fits_t, self._ones_z, self._ones_c))
        tm_req = fits_t & req_tmask
        push(STAGE_REQUIREMENTS, self.count("base", tm_req, zm, cm))
        tm_pool = tm_req & pool_tmask
        zm_pool = zm & pool_zmask
        cm_pool = cm & pool_cmask
        push(STAGE_POOLS, self.count("base", tm_pool, zm_pool, cm_pool))
        r_ice = self.count("masked", tm_pool, zm_pool, cm_pool)
        ex: Tuple[str, ...] = ()
        if r_ice < rows[-1].remaining:
            if self._gone is None:
                # once per build, not per ICE-affected group (an ice-age
                # pass can touch most groups)
                self._gone = self.base & ~self.masked
            ex = self._examples(tm_pool, zm_pool, cm_pool, self._gone)
        push(STAGE_ICE, r_ice, ex)
        if final_tmask is not None:
            tm_f = tm_pool & final_tmask
            r_nar = self.count("masked", tm_f, zm_pool, cm_pool)
            gone_types = np.nonzero(tm_pool & ~tm_f)[0][:_MAX_EXAMPLES]
            push(STAGE_NARROWING, r_nar,
                 tuple(self.lattice.names[t] for t in gone_types))
        return GroupLedger(
            label=request_label(vec), signature=signature, pods=pods,
            stages=tuple(rows), pools_ok=pools_ok, pools_total=pools_total,
            notes=tuple(notes))


_UNPLACED_DETAILS = {
    taxonomy.ICE_HOLD: "all compatible offerings currently unavailable",
    taxonomy.NO_OFFERING: "no compatible nodepool/instance-type offering",
    taxonomy.NO_EXISTING_FIT:
        "only existing capacity could host this pod and none fits",
    taxonomy.NO_NEW_NODE_SHAPE:
        "no empty node of any feasible type can hold this pod",
    taxonomy.NO_FIT: "does not fit any existing node or new-node shape",
}


def unplaced_reason(group, fallback: str = taxonomy.NO_FIT) -> str:
    """The coded reason for a pod the packer could not place. The
    group's ledger refines it — a group whose offerings were zeroed by
    the ICE stage is weather-caused pending, not a shape problem — and
    ``fallback`` carries the packer's own distinction (the host-FFD rung
    knows no-existing-fit from no-new-node-shape; the device decode only
    knows no-fit)."""
    led = getattr(group, "ledger", None)
    code = (led.blame_code() if led is not None else "") or fallback
    return taxonomy.reason(code, _UNPLACED_DETAILS.get(code, ""))


# ---- pass-level explanation -----------------------------------------------

# bounds keeping one PassExplanation's footprint sane at 50k-pod scale:
# group entries keep the interesting ones (unplaced first, then largest),
# placements/unschedulable maps cap with an overflow count
MAX_GROUP_ENTRIES = 256
MAX_UNSCHEDULABLE = 4096
MAX_PLACEMENTS = 4096


@dataclass
class GroupOutcome:
    ledger: GroupLedger
    placed: int = 0
    unplaced: int = 0
    code: str = ""                  # reason code when unplaced > 0
    dropped: bool = False           # eliminated at build (never packed)

    def to_doc(self) -> dict:
        return {**self.ledger.to_doc(), "placed": self.placed,
                "unplaced": self.unplaced, "code": self.code,
                "dropped": self.dropped}


@dataclass
class PassExplanation:
    pass_id: int
    trace_id: str
    t: float
    pods: int
    groups: List[GroupOutcome] = field(default_factory=list)
    groups_total: int = 0                       # before MAX_GROUP_ENTRIES
    unschedulable: Dict[str, str] = field(default_factory=dict)  # pod->reason
    unschedulable_total: int = 0
    pod_group: Dict[str, int] = field(default_factory=dict)  # pod->groups idx
    placements: Dict[str, str] = field(default_factory=dict)  # pod->node
    placements_total: int = 0
    claims: Dict[str, dict] = field(default_factory=dict)  # claim->rationale
    eliminations: Dict[str, int] = field(default_factory=dict)  # stage->n
    reason_counts: Dict[str, int] = field(default_factory=dict)  # code->pods
    degraded_reason: str = ""
    note: str = ""

    def to_doc(self, full: bool = True) -> dict:
        d = {
            "pass": self.pass_id, "traceId": self.trace_id,
            "t": round(self.t, 3), "pods": self.pods,
            "groups": self.groups_total,
            "unschedulable": self.unschedulable_total,
            "placements": self.placements_total,
            "reasons": dict(self.reason_counts),
            "eliminations": dict(self.eliminations),
        }
        if self.degraded_reason:
            d["degradedReason"] = self.degraded_reason
        if self.note:
            d["note"] = self.note
        if full:
            d["groupDetails"] = [g.to_doc() for g in self.groups]
            d["claims"] = dict(self.claims)
        return d


def explain_pass(problem, plan, pass_id: int, trace_id: str,
                 now: float) -> PassExplanation:
    """Fold a solved plan's outcome onto the problem's ledgers. Cheap on
    the steady path: the pod→group index is only built when the pass has
    unschedulable pods, and placement maps cover THIS pass's placements
    (new binds/claims), never the whole cluster."""
    expl = PassExplanation(pass_id=pass_id, trace_id=trace_id, t=now,
                           pods=0)
    unsched = dict(plan.unschedulable) if plan is not None else {}
    expl.unschedulable_total = len(unsched)
    for name, r in unsched.items():
        code = taxonomy.code_of(r)
        expl.reason_counts[code] = expl.reason_counts.get(code, 0) + 1

    groups = list(getattr(problem, "groups", ()) or ())
    dropped = list(getattr(problem, "dropped_groups", ()) or ())
    outcomes: List[GroupOutcome] = []
    out_gi: List[int] = []      # outcome idx -> group idx (splits can
                                # SHARE a signature — never key on it)
    unplaced_by_group: Dict[int, int] = {}
    first_reason: Dict[int, str] = {}
    gi_of: Dict[str, int] = {}
    if unsched:
        # pod → group index, built ONLY when the pass has unschedulable
        # pods (the steady no-unsched path stays O(G), never O(pods))
        for gi, g in enumerate(groups + dropped):
            for n in g.pod_names:
                gi_of[n] = gi
        for n, r in unsched.items():
            gi = gi_of.get(n)
            if gi is not None:
                unplaced_by_group[gi] = unplaced_by_group.get(gi, 0) + 1
                first_reason.setdefault(gi, r)
    for gi, g in enumerate(groups + dropped):
        led = getattr(g, "ledger", None)
        if led is None:
            continue
        is_dropped = gi >= len(groups)
        n_un = (len(g.pod_names) if is_dropped
                else unplaced_by_group.get(gi, 0))
        code = ""
        if n_un:
            # the group's pods all share one signature, hence one reason
            first = first_reason.get(gi, "")
            code = taxonomy.code_of(first) if first else (
                led.blame_code() or taxonomy.NO_FIT)
        outcomes.append(GroupOutcome(
            ledger=led, placed=len(g.pod_names) - n_un, unplaced=n_un,
            code=code, dropped=is_dropped))
        out_gi.append(gi)
        expl.pods += len(g.pod_names)
        for row in led.stages:
            if row.eliminated:
                expl.eliminations[row.stage] = \
                    expl.eliminations.get(row.stage, 0) + row.eliminated
    expl.groups_total = len(outcomes)
    # keep the interesting entries: unplaced groups first, then largest
    # (ties keep build order — deterministic, and a later split never
    # shadows an earlier one)
    order = sorted(range(len(outcomes)),
                   key=lambda i: (-outcomes[i].unplaced,
                                  -outcomes[i].ledger.pods,
                                  outcomes[i].ledger.signature, i))
    kept = order[:MAX_GROUP_ENTRIES]
    expl.groups = [outcomes[i] for i in kept]
    gi_to_entry = {out_gi[i]: pos for pos, i in enumerate(kept)}

    # pod → retained-group-entry index for every (bounded) unschedulable
    # pod, via the gi_of map already built above — keyed by GROUP INDEX,
    # never signature (topology splits share signatures)
    for n, r in unsched.items():
        if len(expl.unschedulable) >= MAX_UNSCHEDULABLE:
            break
        expl.unschedulable[n] = r
        gi = gi_of.get(n)
        if gi is not None and gi in gi_to_entry:
            expl.pod_group[n] = gi_to_entry[gi]

    # this pass's placements onto existing capacity (claim placements are
    # appended by the provisioner as claims are created)
    if plan is not None:
        for node_name, pods in plan.existing_assignments.items():
            for p in pods:
                expl.placements_total += 1
                if len(expl.placements) < MAX_PLACEMENTS:
                    expl.placements[p] = node_name
    expl.degraded_reason = getattr(plan, "degraded_reason", "") or ""
    return expl


def add_placements(expl: PassExplanation, plan) -> None:
    """Fold a retry-round plan's existing-capacity placements into an
    already-built pass explanation (the limit-fallback loop re-solves
    dropped pods and may bind them onto existing nodes — symmetric with
    add_claim for the retry rounds' new claims)."""
    for node_name, pods in plan.existing_assignments.items():
        for p in pods:
            if p in expl.placements:
                continue
            expl.placements_total += 1
            if len(expl.placements) < MAX_PLACEMENTS:
                expl.placements[p] = node_name


def add_unschedulable(expl: PassExplanation, name: str,
                      reason_str: str) -> None:
    """Fold a late unschedulable pod (limit-fallback drop, retry-round
    leftover) into an already-built pass explanation."""
    if name in expl.unschedulable:
        return
    code = taxonomy.code_of(reason_str)
    expl.reason_counts[code] = expl.reason_counts.get(code, 0) + 1
    expl.unschedulable_total += 1
    if len(expl.unschedulable) < MAX_UNSCHEDULABLE:
        expl.unschedulable[name] = reason_str


def add_claim(expl: PassExplanation, claim_name: str, node,
              runner_up: Optional[Tuple[str, float]] = None) -> None:
    """Record a created claim's placement rationale: the chosen offering
    and (when the bin had launch flexibility) the runner-up type with
    its price delta."""
    doc = {
        "nodePool": node.node_pool,
        "instanceType": node.instance_type, "zone": node.zone,
        "capacityType": node.capacity_type,
        "pricePerHour": round(float(node.price_per_hour), 6),
        "pods": len(node.pods),
        "flexibleTypes": len(node.feasible_types),
    }
    if runner_up is not None:
        doc["runnerUpType"] = runner_up[0]
        doc["runnerUpPricePerHour"] = round(float(runner_up[1]), 6)
        doc["runnerUpPriceDelta"] = round(
            float(runner_up[1]) - float(node.price_per_hour), 6)
    expl.claims[claim_name] = doc
    for p in node.pods:
        expl.placements_total += 1
        if len(expl.placements) < MAX_PLACEMENTS:
            expl.placements[p] = claim_name


# ---- the bounded per-pass decision-audit ring -----------------------------

class DecisionAuditRing:
    """Bounded ring of PassExplanations keyed by pass/trace id — the
    store behind the ``explain`` introspection provider, /debug/explain,
    and ``kpctl explain``. Thread-safe; stats() is flat numeric so the
    sampler rings (and therefore soak artifacts) carry the per-pass
    reason-code histogram as ordinary per-subsystem series."""

    # per-node decision entries kept (newest wins; move-to-end on update)
    NODE_LEDGER_MAX = 256

    def __init__(self, size: int = 64):
        self._ring: deque = deque(maxlen=size)
        self._lock = threading.Lock()
        self.passes_recorded = 0
        self._reason_totals: Dict[str, int] = {}
        self._elim_totals: Dict[str, int] = {}
        # node -> latest "why was this node NOT disrupted" decision (the
        # consolidation engine's skip codes land here: kpctl explain node)
        self._node_ledger: "OrderedDict[str, dict]" = OrderedDict()

    def record(self, expl: PassExplanation) -> None:
        with self._lock:
            self._ring.append(expl)
            self.passes_recorded += 1
            for code, n in expl.reason_counts.items():
                self._reason_totals[code] = \
                    self._reason_totals.get(code, 0) + n
            for stage, n in expl.eliminations.items():
                self._elim_totals[stage] = \
                    self._elim_totals.get(stage, 0) + n

    def record_node(self, node_name: str, code: str, detail: str = "",
                    t: float = 0.0) -> None:
        """Record a per-node skip decision (taxonomy-coded). Counted into
        the same reason totals the pass explanations feed, so the skip
        codes surface in stats()/soak series as reason_* like every other
        code; the per-node entry keeps only the LATEST decision with a
        per-(node, code) repeat count."""
        assert code in taxonomy.CODES, code
        with self._lock:
            self._reason_totals[code] = self._reason_totals.get(code, 0) + 1
            prev = self._node_ledger.pop(node_name, None)
            seen = (prev["count"] if prev is not None
                    and prev["code"] == code else 0)
            self._node_ledger[node_name] = {
                "node": node_name, "code": code, "detail": detail,
                "t": round(float(t), 3), "count": seen + 1}
            while len(self._node_ledger) > self.NODE_LEDGER_MAX:
                self._node_ledger.popitem(last=False)

    def headroom_probe(self) -> Dict[str, float]:
        """Audit-ring occupancy (introspect/headroom.py). ``kind="ring"``
        — evicting the oldest pass explanation is the retention policy
        /debug/explain documents; "drops" counts evicted passes."""
        with self._lock:
            depth = len(self._ring)
            return {"depth": float(depth),
                    "capacity": float(self._ring.maxlen or 0),
                    "drops": float(max(self.passes_recorded - depth, 0)),
                    "kind": "ring"}

    # ---- lookups ---------------------------------------------------------

    def _snapshot(self) -> List[PassExplanation]:
        with self._lock:
            return list(self._ring)

    def find_pass(self, pass_id: Optional[int] = None
                  ) -> Optional[PassExplanation]:
        snap = self._snapshot()
        if not snap:
            return None
        if pass_id is None:
            return snap[-1]
        for e in reversed(snap):
            if e.pass_id == pass_id or e.trace_id == str(pass_id):
                return e
        return None

    def find_pod(self, name: str) -> Optional[dict]:
        """Newest-first search: the pod's current reason + ledger (when
        unschedulable) or its placement (when this ring saw it bind)."""
        for e in reversed(self._snapshot()):
            if name in e.unschedulable:
                r = e.unschedulable[name]
                doc = {"pod": name, "pass": e.pass_id,
                       "traceId": e.trace_id, "outcome": "unschedulable",
                       "code": taxonomy.code_of(r), "reason": r}
                gi = e.pod_group.get(name)
                if gi is not None:
                    doc["group"] = e.groups[gi].to_doc()
                return doc
            if name in e.placements:
                target = e.placements[name]
                doc = {"pod": name, "pass": e.pass_id,
                       "traceId": e.trace_id, "outcome": "scheduled",
                       "node": target}
                if target in e.claims:
                    doc["rationale"] = e.claims[target]
                return doc
        return None

    def find_claim(self, name: str) -> Optional[dict]:
        for e in reversed(self._snapshot()):
            if name in e.claims:
                return {"nodeclaim": name, "pass": e.pass_id,
                        "traceId": e.trace_id, "rationale": e.claims[name]}
        return None

    def find_node(self, name: str) -> Optional[dict]:
        """The node's latest skip decision ("why was this node NOT
        consolidated"), recorded by the consolidation engine."""
        with self._lock:
            entry = self._node_ledger.get(name)
            return dict(entry) if entry is not None else None

    # ---- surfaces --------------------------------------------------------

    def stats(self) -> Dict[str, float]:
        """The ``explain`` introspection provider: flat numeric, so
        kpctl top's EXPLAIN row and the sampler's soak series both read
        it directly."""
        with self._lock:
            last = self._ring[-1] if self._ring else None
            out: Dict[str, float] = {
                "passes": float(self.passes_recorded),
                "ring": float(len(self._ring)),
                "last_pass": float(last.pass_id) if last else 0.0,
                "last_unschedulable": float(
                    last.unschedulable_total) if last else 0.0,
                "last_groups": float(last.groups_total) if last else 0.0,
                "node_entries": float(len(self._node_ledger)),
            }
            for code, n in sorted(self._reason_totals.items()):
                out["reason_" + code.replace("-", "_")] = float(n)
            for stage, n in sorted(self._elim_totals.items()):
                out["elim_" + stage.replace("-", "_")] = float(n)
            return out

    def doc(self, query: Dict[str, List[str]]) -> dict:
        """The /debug/explain JSON document (both HTTP servers route
        here via introspect.debug_doc)."""
        def q(key):
            v = query.get(key, [])
            return v[0] if v else None

        if q("pod"):
            found = self.find_pod(q("pod"))
            return found if found is not None else {
                "pod": q("pod"), "found": False,
                "message": "pod not seen in the decision-audit ring "
                           "(already scheduled before the ring, or never "
                           "pending)"}
        if q("nodeclaim"):
            found = self.find_claim(q("nodeclaim"))
            return found if found is not None else {
                "nodeclaim": q("nodeclaim"), "found": False,
                "message": "nodeclaim not in the decision-audit ring"}
        if q("node"):
            found = self.find_node(q("node"))
            return found if found is not None else {
                "node": q("node"), "found": False,
                "message": "node has no recorded skip decision (it was "
                           "consolidated, never a candidate, or the entry "
                           "aged out of the node ledger)"}
        if q("pass"):
            try:
                pid = int(q("pass"))
            except ValueError:
                pid = q("pass")   # trace id form
            e = self.find_pass(pid)
            return (e.to_doc(full=True) if e is not None
                    else {"pass": q("pass"), "found": False})
        with self._lock:
            snap = list(self._ring)
            reasons = dict(self._reason_totals)
            elims = dict(self._elim_totals)
        return {
            "passes": [e.to_doc(full=False) for e in snap],
            "recorded": self.passes_recorded,
            "reasons": reasons, "eliminations": elims,
        }
