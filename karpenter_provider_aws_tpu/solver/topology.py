"""Topology spread + pod (anti-)affinity resolution.

The reference enforces these constraints inside its sequential scheduling
simulation (core provisioner; behavioral spec: reference
website/content/en/preview/concepts/scheduling.md:312-446 — zonal/hostname/
capacity-type topologySpreadConstraints, required podAffinity /
podAntiAffinity). A per-pod simulator can consult mutable domain counters
before every placement; a batched device kernel cannot. The TPU-first
decomposition used here splits each constraint by *topology key*:

- **zone / capacity-type scoped** constraints are resolved HOST-SIDE, before
  the scan, by splitting a pod group into per-domain subgroups:
  - topology spread  → exact integer water-fill over eligible domains
    (equivalent to the reference's greedy "place each pod in the min-count
    domain", which never exceeds maxSkew>=1 — see _water_fill).
  - self anti-affinity → one pod per domain; surplus pods are
    unschedulable, like the reference when it runs out of domains.
  - self affinity → the whole group pins to one domain (the domain
    holding bound matches, else the first eligible one), mirroring the
    reference's first-pod-seeds-the-domain behavior.
  - cross-class zone anti-affinity → zones holding bound matching pods are
    masked out; pending-vs-pending overlap gets a warning (the sequential
    reference can interleave them; the batched form separates classes).

- **hostname scoped** constraints run IN-KERNEL, because hostname domains
  (bins) are created during the scan itself:
  - spread(maxSkew=s) → per-bin placement cap ``max_per_bin=s`` (while any
    eligible empty node exists, per-node counts in [0,s] keep skew<=s).
  - anti-affinity → per-bin affinity-class presence masks: the scan carries
    ``pm[B,A]`` ("bin holds a pod matching class a") and ``po[B,A]`` ("bin
    holds a pod owning anti-term a"); group g may enter bin b only if
    ``~any(pm[b]&owner[g]) & ~any(po[b]&match[g])`` — both directions of
    the k8s symmetry check.
  - affinity → ``need[g,a]`` requires ``pm[b,a]`` (join a seeded bin);
    self-affinity sets ``single_bin`` (all replicas co-locate on one node).

A = number of distinct affinity/spread label selectors ("classes"); G x A
and B x A stay tiny because selectors are deduplicated exactly like pod
groups are.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..apis import wellknown as wk
from ..apis.objects import Pod, PodAffinityTerm, TopologySpreadConstraint

_BIG = np.iinfo(np.int32).max


@dataclass(frozen=True)
class BoundPod:
    """An already-scheduled pod, for topology accounting: domain counts for
    spread, zone occupancy for zone anti-affinity, and per-existing-bin
    class presence for hostname terms (node_name links to ExistingBin.name)."""

    pod: Pod
    node_name: str
    zone: str
    capacity_type: str = wk.CAPACITY_TYPE_ON_DEMAND
    node_labels: Mapping[str, str] = field(default_factory=dict)  # custom-key spread domains


def _selector_key(sel: Tuple[Tuple[str, str], ...]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(sel))


def _matches(sel: Tuple[Tuple[str, str], ...], labels: Mapping[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in sel)


@dataclass
class ClassRegistry:
    """Deduplicated label selectors referenced by hostname-scoped terms."""

    keys: List[Tuple[Tuple[str, str], ...]] = field(default_factory=list)
    index: Dict[Tuple[Tuple[str, str], ...], int] = field(default_factory=dict)

    def intern(self, sel: Tuple[Tuple[str, str], ...]) -> int:
        k = _selector_key(sel)
        if k not in self.index:
            self.index[k] = len(self.keys)
            self.keys.append(k)
        return self.index[k]

    @property
    def A(self) -> int:
        return len(self.keys)

    def match_row(self, labels: Mapping[str, str]) -> np.ndarray:
        return np.array([_matches(sel, labels) for sel in self.keys], dtype=bool)


@dataclass
class GroupTopology:
    """Per-group-row topology attributes consumed by the kernel."""

    max_per_bin: int = _BIG
    spread_class: int = -1               # class whose per-bin count the cap tracks
    single_bin: bool = False
    match: Optional[np.ndarray] = None   # [A]
    owner: Optional[np.ndarray] = None   # [A]
    need: Optional[np.ndarray] = None    # [A]


def _water_fill(counts: np.ndarray, n: int) -> np.ndarray:
    """Distribute n units over domains with existing ``counts``, greedily to
    the min-count domain (exact integer water-fill). Returns additions per
    domain. Equivalent to the reference's per-pod min-domain placement: each
    step raises a current minimum, so resulting skew never exceeds
    max(initial_skew, 1) and spread stays maxSkew-feasible for maxSkew>=1."""
    counts = counts.astype(np.int64)
    k = len(counts)
    if k == 0 or n <= 0:
        return np.zeros((k,), dtype=np.int64)
    order = np.argsort(counts, kind="stable")
    sorted_c = counts[order]
    add = np.zeros((k,), dtype=np.int64)
    remaining = n
    # raise the lowest level up to the next level, domain by domain
    for i in range(k):
        level = sorted_c[i]
        width = i + 1
        nxt = sorted_c[i + 1] if i + 1 < k else None
        room = remaining if nxt is None else min(remaining, (nxt - level) * width)
        if room <= 0:
            continue
        base, extra = divmod(room, width)
        for j in range(width):
            add[order[j]] += base + (1 if j < extra else 0)
        sorted_c[: width] += base
        for j in range(int(extra)):
            sorted_c[j] += 1
        remaining -= room
        if remaining == 0:
            break
    if remaining > 0:  # all domains level: round-robin the tail
        base, extra = divmod(remaining, k)
        for j in range(k):
            add[order[j]] += base + (1 if j < extra else 0)
    return add


@dataclass
class _Split:
    """One output row: a slice of the group's pods with narrowed domain masks."""

    count: int
    zone_mask: np.ndarray
    cap_mask: np.ndarray
    # custom-key label values this slice pins (spread over user-defined
    # labels — the reference's 'virtual domains' ratio-split technique,
    # scheduling.md:558-614); build_problem routes the slice to pools
    # carrying/offering exactly these values
    custom: Dict[str, str] = field(default_factory=dict)


def resolve_group_topology(
    pod: Pod,
    count: int,
    zone_mask: np.ndarray,
    cap_mask: np.ndarray,
    zones: Sequence[str],
    capacity_types: Sequence[str],
    registry: ClassRegistry,
    bound: Sequence[BoundPod],
    warnings: List[str],
    pending_counts: Optional[Dict] = None,
    custom_domains: Optional[Mapping[str, Sequence[str]]] = None,
) -> Tuple[List[_Split], GroupTopology, int]:
    """Resolve one pod group's topology constraints.

    Returns (splits, per-row topology attributes, pods_cut) where pods_cut
    is the number of pods made unschedulable by domain exhaustion (zone
    self-anti-affinity with more replicas than eligible zones).

    ``pending_counts`` maps (selector, topology_key) → per-domain additions
    already planned for earlier groups in this batch, so sibling groups
    sharing a spread selector fill against the COMBINED counts (the skew
    bound is per selector, not per group; the kernel's pm counters do the
    same for hostname).
    """
    topo = GroupTopology()
    zmask = zone_mask.copy()
    cmask = cap_mask.copy()
    cut = 0
    zone_index = {z: i for i, z in enumerate(zones)}
    cap_index = {c: i for i, c in enumerate(capacity_types)}

    # ---- pod (anti-)affinity --------------------------------------------
    match_row = None
    owner = np.zeros((0,), dtype=bool)
    need = np.zeros((0,), dtype=bool)
    for term in pod.pod_affinity:
        sel = tuple(term.label_selector)
        self_match = _matches(sel, pod.labels)
        if term.topology_key == wk.LABEL_HOSTNAME:
            a = registry.intern(sel)
            if a >= len(owner):
                pad = a + 1 - len(owner)
                owner = np.concatenate([owner, np.zeros((pad,), dtype=bool)])
                need = np.concatenate([need, np.zeros((pad,), dtype=bool)])
            if term.anti:
                owner[a] = True
                if self_match:
                    topo.max_per_bin = min(topo.max_per_bin, 1)
            else:
                if self_match:
                    topo.single_bin = True
                else:
                    need[a] = True
        elif term.topology_key == wk.LABEL_ZONE:
            if term.anti:
                # zones already holding matching pods are off-limits
                for bp in bound:
                    if _matches(sel, bp.pod.labels) and bp.zone in zone_index:
                        zmask[zone_index[bp.zone]] = False
                # and symmetric: bound pods owning zone-anti terms against us
                if not self_match:
                    warnings.append(
                        "zone-scoped podAntiAffinity between distinct pending classes is "
                        "resolved against bound pods only; pending-vs-pending interleave "
                        "is not separated in one batch")
            else:
                # co-locate in one zone: prefer a zone with bound matches
                target = None
                for bp in bound:
                    if _matches(sel, bp.pod.labels) and bp.zone in zone_index and zmask[zone_index[bp.zone]]:
                        target = zone_index[bp.zone]
                        break
                if target is None:
                    elig = np.nonzero(zmask)[0]
                    target = int(elig[0]) if elig.size else None
                    if not self_match:
                        warnings.append(
                            "zone-scoped podAffinity to a class with no bound pods pins "
                            "to an arbitrary eligible zone; the pending target class is "
                            "not co-anchored in one batch")
                if target is not None:
                    pin = np.zeros_like(zmask)
                    pin[target] = True
                    zmask = pin
        else:
            warnings.append(f"pod (anti-)affinity on topology key {term.topology_key!r} is not supported")

    # symmetric direction: bound pods owning hostname anti-terms that match us
    # are accounted via po-seeding of existing bins (build_problem).

    # ---- zone self-anti: one replica per eligible zone ------------------
    zone_self_anti = any(
        term.anti and term.topology_key == wk.LABEL_ZONE
        and _matches(tuple(term.label_selector), pod.labels)
        for term in pod.pod_affinity)

    # ---- topology spread ------------------------------------------------
    zone_spread: Optional[TopologySpreadConstraint] = None
    cap_spread: Optional[TopologySpreadConstraint] = None
    custom_spreads: List[TopologySpreadConstraint] = []
    for c in pod.topology_spread:
        if c.topology_key == wk.LABEL_ZONE:
            if zone_spread is not None:
                warnings.append("multiple zone topologySpreadConstraints on one pod; first wins")
            else:
                zone_spread = c
        elif c.topology_key == wk.LABEL_HOSTNAME:
            # the kernel tracks the per-bin count of this selector's class so
            # bound pods and sibling groups with the same labels are counted
            a = registry.intern(tuple(c.label_selector))
            if topo.spread_class >= 0 and topo.spread_class != a:
                warnings.append("multiple hostname topologySpreadConstraints with "
                                "distinct selectors on one pod; first selector wins")
            else:
                topo.spread_class = a
            topo.max_per_bin = min(topo.max_per_bin, max(1, c.max_skew))
        elif c.topology_key == wk.LABEL_CAPACITY_TYPE:
            if cap_spread is not None:
                warnings.append("multiple capacity-type topologySpreadConstraints; first wins")
            else:
                cap_spread = c
        elif c.when_unsatisfiable == "ScheduleAnyway":
            # advisory skew on a custom key: never a split/unschedulable
            # cause (matches the zone/captype ScheduleAnyway treatment)
            pass
        elif custom_domains is not None and custom_domains.get(c.topology_key):
            custom_spreads.append(c)
        else:
            warnings.append(
                f"topologySpreadConstraint on key {c.topology_key!r} has no "
                f"discoverable domains (no NodePool offers the key, no node "
                f"carries it)")

    # finalize class rows at full registry width later (build_problem pads);
    # here record the sparse rows
    topo.owner = owner
    topo.need = need
    topo.match = None  # filled by build_problem once the registry is final

    # ---- build splits ---------------------------------------------------
    splits: List[_Split] = []

    def spread_counts(sel: Tuple[Tuple[str, str], ...], key: str) -> np.ndarray:
        """Matching-pod counts per domain: bound pods + additions already
        planned for earlier sibling groups in this batch."""
        if key == wk.LABEL_ZONE:
            out = np.zeros((len(zones),), dtype=np.int64)
            for bp in bound:
                if _matches(sel, bp.pod.labels) and bp.zone in zone_index:
                    out[zone_index[bp.zone]] += 1
        else:
            out = np.zeros((len(capacity_types),), dtype=np.int64)
            for bp in bound:
                if _matches(sel, bp.pod.labels) and bp.capacity_type in cap_index:
                    out[cap_index[bp.capacity_type]] += 1
        if pending_counts is not None:
            prior = pending_counts.get((_selector_key(sel), key))
            if prior is not None:
                out = out + prior
        return out

    def record_adds(sel: Tuple[Tuple[str, str], ...], key: str,
                    domain_indices, adds) -> None:
        if pending_counts is None:
            return
        k = (_selector_key(sel), key)
        size = len(zones) if key == wk.LABEL_ZONE else len(capacity_types)
        acc = pending_counts.setdefault(k, np.zeros((size,), dtype=np.int64))
        for di, n in zip(domain_indices, adds):
            acc[di] += int(n)

    if zone_self_anti:
        elig = np.nonzero(zmask)[0]
        # zones already holding a match were masked above; one new pod per zone
        for zi in elig[: count]:
            m = np.zeros_like(zmask)
            m[zi] = True
            splits.append(_Split(1, m, cmask.copy()))
        cut = max(0, count - elig.size)
    elif zone_spread is not None:
        elig = np.nonzero(zmask)[0]
        if elig.size == 0:
            splits.append(_Split(count, zmask, cmask))
        else:
            sel = tuple(zone_spread.label_selector)
            existing = spread_counts(sel, wk.LABEL_ZONE)[elig]
            adds = _water_fill(existing, count)
            if _matches(sel, pod.labels):
                record_adds(sel, wk.LABEL_ZONE, elig, adds)
            for zi, n in zip(elig, adds):
                if n <= 0:
                    continue
                m = np.zeros_like(zmask)
                m[zi] = True
                splits.append(_Split(int(n), m, cmask.copy()))
    else:
        splits.append(_Split(count, zmask, cmask))

    if cap_spread is not None:
        out: List[_Split] = []
        # the skew constraint is global across all zone splits: fold each
        # split's additions into the running domain counts so later splits
        # keep topping up the lowest capacity type
        sel = tuple(cap_spread.label_selector)
        running = spread_counts(sel, wk.LABEL_CAPACITY_TYPE)
        for s in splits:
            elig = np.nonzero(s.cap_mask)[0]
            if elig.size == 0:
                out.append(s)
                continue
            adds = _water_fill(running[elig], s.count)
            running[elig] += adds
            if _matches(sel, pod.labels):
                record_adds(sel, wk.LABEL_CAPACITY_TYPE, elig, adds)
            for ci, n in zip(elig, adds):
                if n <= 0:
                    continue
                m = np.zeros_like(s.cap_mask)
                m[ci] = True
                out.append(_Split(int(n), s.zone_mask.copy(), m,
                                  custom=dict(s.custom)))
        splits = out

    # ---- custom-key spread: the 'virtual domains' split -----------------
    # (reference scheduling.md:558-614: spreading across a user-defined
    # label whose values come from NodePool requirements — e.g. the
    # capacity-spread on-demand/spot ratio technique). Domains are
    # discovered by build_problem; counting works exactly like
    # zone/captype: existing matching pods per node-label value + pending
    # sibling additions, then exact water-fill.
    for c in custom_spreads:
        key = c.topology_key
        # counts and pending records index the CANONICAL domain list (the
        # full discovered set) so sibling groups with different per-group
        # eligibility still accumulate into the same axis
        domains = list(custom_domains[key])
        own = pod.hard_scheduling_requirements()
        elig = np.array([key not in set(own.keys()) or own.get(key).matches(d)
                         for d in domains], dtype=bool)
        if not elig.any():
            continue
        sel = tuple(c.label_selector)
        running = np.zeros((len(domains),), dtype=np.int64)
        dom_index = {d: i for i, d in enumerate(domains)}
        for bp in bound:
            if _matches(sel, bp.pod.labels):
                di = dom_index.get(bp.node_labels.get(key))
                if di is not None:
                    running[di] += 1
        pk = (_selector_key(sel), key)
        prior = None
        if pending_counts is not None:
            prior = pending_counts.get(pk)
            if prior is not None and len(prior) == len(domains):
                running = running + prior
            else:
                prior = None
        adds_total = np.zeros((len(domains),), dtype=np.int64)
        elig_idx = np.nonzero(elig)[0]
        out2: List[_Split] = []
        for s in splits:
            adds = _water_fill(running[elig_idx], s.count)
            running[elig_idx] += adds
            adds_total[elig_idx] += adds
            for di, n in zip(elig_idx, adds):
                if n <= 0:
                    continue
                out2.append(_Split(int(n), s.zone_mask.copy(),
                                   s.cap_mask.copy(),
                                   custom={**s.custom, key: domains[di]}))
        if pending_counts is not None and _matches(sel, pod.labels):
            # record ADDS only (bound pods recount for every group)
            pending_counts[pk] = (prior if prior is not None
                                  else np.zeros((len(domains),), np.int64)) + adds_total
        splits = out2

    return splits, topo, cut
