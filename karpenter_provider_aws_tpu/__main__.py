"""`python -m karpenter_provider_aws_tpu` → the controller CLI."""

import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
