"""Solver-pool failover: health-checked sidecar circuit breakers.

PR 4 opened the process boundary (`sidecar.py RemoteSolver`,
``--solver-address``) as ONE address with ONE 60 s timeout and a
one-rung local fallback. That shape has two failure modes the paper's
<200 ms p50 bar cannot absorb: a *hung* sidecar (accepts the connection,
never answers) stalls a pass ~300x past the latency budget before the
flat timeout fires, and a dead sidecar turns every subsequent pass into
a connection-refused round trip plus a local solve. This module is the
fleet-shaped answer (ROADMAP item 4 "health-checked sidecar
discovery/failover"), mirroring the reference's operational posture —
controller restarts and dependency outages are routine, not exceptional:

- ``--solver-address`` grows to a comma-separated endpoint list
  (env ``SOLVER_ADDRESSES``); each endpoint is wrapped in a
  :class:`CircuitBreaker` (closed → open on consecutive failures or one
  deadline-class failure → half-open probation probe on the INJECTED
  clock, never wall time) with jittered exponential backoff;
- RPC deadlines split by purpose: the solve deadline derives from the
  SLO latency budget with a small multiplier
  (:data:`SOLVE_DEADLINE_MULTIPLIER`), the health deadline is ~1 s —
  previously both shared ``timeout=60.0``;
- a cheap periodic health check (:data:`HEALTH_INTERVAL_SECONDS`)
  catches silently-dead endpoints between solves, so a solve never has
  to be the thing that discovers an outage;
- failover routes among healthy endpoints — least-outstanding first,
  deterministic index tie-break — and the LOCAL solve is the final rung
  only when the whole pool is dark (``degraded_reason=pool-exhausted``,
  a declared taxonomy code);
- per-endpoint mesh/imbalance observation generalizes the PR 12
  "report the sidecar that actually solved" contract: the operator's
  mesh gauges describe whichever endpoint carried the pass, and fall
  back to the local view the moment nothing delegates.

Surfaces: ``pool_stats()`` feeds the ``solver_pool`` introspection
provider (``kpctl top`` POOL row) and the ``karpenter_solver_pool_*``
gauges (docs/reference/solver-pool.md).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence, Tuple

from .. import trace
from ..solver.solve import NodePlan, Solver
from ..solver.taxonomy import POOL_EXHAUSTED, SIDECAR_HUNG
from ..utils.clock import WALL
from ..utils.logging import get_logger

# the solve deadline, derived from the SLO latency budget: generous
# enough for a sidecar-side cold compile of a new bucket shape, still
# ~6x tighter than the old flat 60 s (a hung endpoint costs at most one
# deadline before its breaker opens and the pass fails over)
SOLVE_DEADLINE_MULTIPLIER = 50.0
# health probes answer from the resident lattice without touching the
# device — a hung process should cost a probe ~1 s, not a minute
HEALTH_DEADLINE_SECONDS = 1.0
# cadence of the cheap closed-endpoint health check (injected clock)
HEALTH_INTERVAL_SECONDS = 5.0
# breaker tuning: consecutive cheap failures before opening, the base
# probation window, and its exponential-backoff ceiling
BREAKER_FAILURE_THRESHOLD = 3
BREAKER_OPEN_SECONDS = 2.0
BREAKER_MAX_OPEN_SECONDS = 30.0

# numeric breaker-state encoding for gauges / sampler rings / kpctl
STATE_NUM = {"closed": 0, "half-open": 1, "open": 2}


def parse_addresses(spec) -> Tuple[str, ...]:
    """``"unix:/a.sock, host:50051"`` → ``("unix:/a.sock", "host:50051")``.
    Accepts a comma-separated string or any sequence of addresses."""
    if isinstance(spec, str):
        parts = [a.strip() for a in spec.split(",")]
    else:
        parts = [str(a).strip() for a in spec]
    out = tuple(a for a in parts if a)
    if not out:
        raise ValueError(f"solver pool: no endpoint in {spec!r}")
    return out


class CircuitBreaker:
    """Per-endpoint breaker on the INJECTED clock.

    closed → (consecutive failures ≥ threshold, or one deadline-class
    failure) → open → [probation elapses] → half-open (exactly one probe
    rides through) → closed on success / re-open with doubled, jittered
    probation on failure. Probation jitter draws from a per-endpoint
    seeded RNG so N breakers opened by one outage don't probe in
    lockstep — and two runs with the same endpoints behave identically.
    """

    def __init__(self, clock, name: str = "",
                 failure_threshold: int = BREAKER_FAILURE_THRESHOLD,
                 open_seconds: float = BREAKER_OPEN_SECONDS,
                 max_open_seconds: float = BREAKER_MAX_OPEN_SECONDS):
        self._clock = clock
        self.failure_threshold = int(failure_threshold)
        self.open_seconds = float(open_seconds)
        self.max_open_seconds = float(max_open_seconds)
        self.state = "closed"
        self.consecutive_failures = 0
        self.opens = 0              # lifetime opens (monotonic evidence)
        self._open_streak = 0       # consecutive opens (backoff exponent)
        self._probe_at = 0.0
        self._rng = random.Random(f"breaker:{name}")

    def record_success(self) -> None:
        self.state = "closed"
        self.consecutive_failures = 0
        self._open_streak = 0

    def record_failure(self, fatal: bool = False) -> None:
        """``fatal`` marks a deadline-class failure (a hung endpoint):
        one costs a full solve deadline, so the breaker opens
        immediately instead of paying the threshold out ``N`` times."""
        self.consecutive_failures += 1
        if (fatal or self.state == "half-open"
                or self.consecutive_failures >= self.failure_threshold):
            self._open()

    def _open(self) -> None:
        self.state = "open"
        self.opens += 1
        self._open_streak += 1
        base = min(self.open_seconds * (2.0 ** (self._open_streak - 1)),
                   self.max_open_seconds)
        # jitter in [0.5, 1.5): deterministic per endpoint, de-phased
        # across endpoints
        self._probe_at = (self._clock.monotonic()
                          + base * (0.5 + self._rng.random()))

    def probe_due(self) -> bool:
        return (self.state == "open"
                and self._clock.monotonic() >= self._probe_at)

    def begin_probe(self) -> None:
        """Open → half-open: exactly one probe may ride through; its
        outcome decides close vs re-open (record_success/record_failure)."""
        self.state = "half-open"


class PoolEndpoint:
    """One sidecar endpoint: client + breaker + routing/observation
    state. The client is built lazily so constructing a pool (and
    validating options) never imports grpc or opens channels."""

    def __init__(self, index: int, address: str, clock,
                 solve_deadline: float, health_deadline: float):
        self.index = index
        self.address = address
        self.breaker = CircuitBreaker(clock, name=address)
        self.solve_deadline = solve_deadline
        self.health_deadline = health_deadline
        self.outstanding = 0        # in-flight solve RPCs (routing key)
        self.solves = 0             # delegated solves this endpoint won
        self.failures = 0           # lifetime failed attempts/probes
        self.last_health = -1e18    # injected-clock stamp of last check
        self.last_error = ""
        # the PR 12 observation contract, per endpoint: mesh shape and
        # imbalance of the plans THIS endpoint returned
        self.mesh_devices = 0
        self.shard_imbalance = 0.0
        self.sharded_solves = 0
        self._client = None

    def client(self):
        if self._client is None:
            from .sidecar import SolverClient
            self._client = SolverClient(
                self.address, timeout=self.solve_deadline,
                health_timeout=self.health_deadline)
        return self._client

    def observe_plan(self, plan: NodePlan) -> None:
        self.mesh_devices = plan.mesh_devices
        self.shard_imbalance = plan.shard_imbalance
        if plan.mesh_devices > 1:
            self.sharded_solves += 1

    def close(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None


class SolverPool(Solver):
    """A Solver whose provisioning solves run on a POOL of sidecar
    processes, failing over between them and degrading to the local
    in-process solve only when every endpoint is dark.

    Subclasses Solver exactly like RemoteSolver: probe_batch (the
    disruption controller's vmapped what-ifs), lattice queries, and
    warmup stay local, and the local solver IS the final ladder rung."""

    # provisioning solves belong to the pool; the in-process delta fast
    # path would silently bypass delegation (same contract as
    # RemoteSolver)
    supports_delta = False

    def __init__(self, lattice, addresses, clock=None,
                 solve_deadline: Optional[float] = None,
                 health_deadline: float = HEALTH_DEADLINE_SECONDS,
                 health_interval: float = HEALTH_INTERVAL_SECONDS,
                 latency_budget_seconds: float = 0.2,
                 pipeline: bool = True, mesh=None):
        super().__init__(lattice, pipeline=pipeline, clock=clock, mesh=mesh)
        self.log = get_logger("solver_pool")
        if solve_deadline is None or solve_deadline <= 0:
            solve_deadline = derive_solve_deadline(latency_budget_seconds)
        self.solve_deadline = float(solve_deadline)
        self.health_deadline = float(health_deadline)
        self.health_interval = float(health_interval)
        # breakers/health ride the INJECTED clock (FakeClock tests step
        # probation deterministically); grpc deadlines are wall-time by
        # nature and use the deadline values directly
        self._pool_clock = clock if clock is not None else WALL
        self.endpoints: List[PoolEndpoint] = [
            PoolEndpoint(i, a, self._pool_clock,
                         self.solve_deadline, self.health_deadline)
            for i, a in enumerate(parse_addresses(addresses))]
        # bookkeeping guarded by the instrumented pool lock (counter
        # mutations only — RPCs NEVER run under it)
        from ..introspect.contention import lock as _ilock
        self._plock = _ilock("solver_pool")
        self.failovers = 0          # failed endpoint attempts that fell
        #                             through to another endpoint / local
        self.delegated_solves = 0
        self.local_solves = 0
        self.health_checks = 0
        self.probes = 0
        self._last_ep: Optional[int] = None   # endpoint that last solved

    # ---- health / probation ---------------------------------------------

    def _health_ok(self, ep: PoolEndpoint) -> bool:
        try:
            doc = ep.client().health()
            return bool(doc.get("ok"))
        except Exception as e:   # RpcError, protocol junk — all unhealthy
            ep.last_error = f"{type(e).__name__}: {e}"
            return False

    def check_endpoints(self) -> None:
        """The cheap periodic pass: half-open probes for due breakers,
        interval health checks for closed endpoints. Runs at every solve
        entry (and callable directly — soaks/smokes poll it while no
        solve is in flight, so recovery is observed between passes)."""
        now = self._pool_clock.monotonic()
        for ep in self.endpoints:
            br = ep.breaker
            if br.probe_due():
                with self._plock:
                    self.probes += 1
                br.begin_probe()
                ok = self._health_ok(ep)
                ep.last_health = now
                if ok:
                    br.record_success()
                    self.log.info("solver pool endpoint recovered",
                                  endpoint=ep.address)
                else:
                    with self._plock:
                        ep.failures += 1
                    br.record_failure()
            elif (br.state == "closed"
                  and now - ep.last_health >= self.health_interval):
                with self._plock:
                    self.health_checks += 1
                ep.last_health = now
                if not self._health_ok(ep):
                    with self._plock:
                        ep.failures += 1
                    br.record_failure()
                # NB a liveness success is deliberately NOT a breaker
                # success: a flapping sidecar whose health answers but
                # whose solves keep failing must still open after the
                # threshold — only a real successful RPC resets the
                # streak (record_success at the solve site)

    # ---- routing ---------------------------------------------------------

    def _routable(self) -> List[PoolEndpoint]:
        """Healthy endpoints, least-outstanding first; index breaks
        ties deterministically."""
        eps = [ep for ep in self.endpoints if ep.breaker.state == "closed"]
        return sorted(eps, key=lambda e: (e.outstanding, e.index))

    def solve_relaxed(self, pods, node_pools, lattice=None, existing=(),
                      daemonset_pods=(), bound_pods=(), pvcs=None,
                      storage_classes=None, mesh=None,
                      pool_headroom=None, problem0=None) -> NodePlan:
        import grpc
        from .sidecar import SidecarProtocolError, classify_sidecar_failure
        self.check_endpoints()
        attempts: List[str] = []     # "addr: reason" per failed attempt
        for ep in self._routable():
            with self._plock:
                ep.outstanding += 1
            # the attempt span keeps the cross-process trace contract:
            # the winning endpoint's sidecar spans ingest under it, a
            # failed attempt stays in the tree marked status=error
            sp = trace.span("solver.remote", pods=len(pods),
                            address=ep.address, endpoint=ep.index,
                            attempt=len(attempts))
            try:
                with sp:
                    plan = ep.client().solve(
                        pods, node_pools, existing=existing,
                        daemonset_pods=daemonset_pods,
                        bound_pods=bound_pods, pvcs=pvcs,
                        storage_classes=storage_classes,
                        pool_headroom=pool_headroom,
                        unavailable=self._unavailable_entries(lattice))
                    sp.set(path=plan.solver_path, degraded=plan.degraded,
                           reason=plan.degraded_reason)
            except (grpc.RpcError, SidecarProtocolError) as e:
                reason = classify_sidecar_failure(e)
                # the span already closed status=error (the exception
                # crossed its __exit__); pin the bounded reason on it
                sp.set(reason=reason)
                detail = (f"{type(e).__name__}: {e.code()}"
                          if isinstance(e, grpc.RpcError)
                          and hasattr(e, "code") else str(e))
                with self._plock:
                    ep.failures += 1
                    self.failovers += 1
                ep.last_error = detail
                ep.breaker.record_failure(fatal=reason == SIDECAR_HUNG)
                attempts.append(f"{ep.address}: {reason}")
                self.log.warning("solver pool endpoint failed, failing over",
                                 endpoint=ep.address, reason=reason,
                                 error=detail)
                continue
            finally:
                # ALL exits, including an unexpected exception escaping
                # the attempt: a leaked +1 would permanently demote this
                # endpoint in least-outstanding routing
                with self._plock:
                    ep.outstanding -= 1
            with self._plock:
                ep.solves += 1
                self.delegated_solves += 1
                self._last_ep = ep.index
            ep.breaker.record_success()
            ep.observe_plan(plan)
            if attempts:
                # the pass survived on a healthy endpoint; record the
                # attempts it burned (human detail — the plan itself is
                # NOT degraded, the pool did exactly its job)
                plan.warnings.extend(
                    f"solver pool failover: {a}" for a in attempts)
            return plan
        # the whole pool is dark: the LOCAL solver is the final rung —
        # provenance marks the plan so the flight recorder tail-retains
        # the trace and the degraded counter/gauge surfaces say WHY
        with self._plock:
            self.local_solves += 1
            self._last_ep = None
        self._count_degraded(POOL_EXHAUSTED)
        with trace.span("solver.local_fallback",
                        reason=POOL_EXHAUSTED, pods=len(pods)) as sp:
            sp.set(attempts=len(attempts))
            plan = super().solve_relaxed(
                pods, node_pools, lattice=lattice, existing=existing,
                daemonset_pods=daemonset_pods, bound_pods=bound_pods,
                pvcs=pvcs, storage_classes=storage_classes, mesh=mesh,
                pool_headroom=pool_headroom, problem0=problem0)
        plan.degraded = True
        plan.degraded_reason = plan.degraded_reason or POOL_EXHAUSTED
        plan.warnings.extend(
            f"solver pool failover: {a}" for a in attempts)
        return plan

    # _unavailable_entries is shared with RemoteSolver (the ICE triples
    # that cross the wire); import here to avoid a copy drifting
    def _unavailable_entries(self, view):
        from .sidecar import RemoteSolver
        return RemoteSolver._unavailable_entries(self, view)

    # ---- observation / introspection ------------------------------------

    def stats(self) -> Dict[str, object]:
        out = super().stats()
        # cumulative sharded evidence: local solves + every sharded plan
        # any endpoint returned (never goes backwards)
        out["mesh_solves"] = (out.get("mesh_solves", 0)
                              + sum(ep.sharded_solves
                                    for ep in self.endpoints))
        last = self._last_ep
        if last is not None and self.endpoints[last].mesh_devices:
            # the gauges describe the endpoint that actually solved; a
            # dark pool reports the local view (super()'s) instead of
            # advertising a mesh nothing solves on
            ep = self.endpoints[last]
            out["mesh_devices"] = ep.mesh_devices
            out["mesh_shard_imbalance"] = round(ep.shard_imbalance, 4)
        return out

    def pool_stats(self) -> Dict[str, object]:
        """The ``solver_pool`` introspection provider (kpctl top POOL
        row; karpenter_solver_pool_* gauges). Counter reads only — no
        RPC, no lock wait on an in-flight solve."""
        out: Dict[str, object] = {
            "endpoints": len(self.endpoints),
            "healthy": sum(1 for ep in self.endpoints
                           if ep.breaker.state == "closed"),
            "failovers": self.failovers,
            "delegated_solves": self.delegated_solves,
            "local_solves": self.local_solves,
            "health_checks": self.health_checks,
            "probes": self.probes,
            "solve_deadline_s": self.solve_deadline,
            "health_deadline_s": self.health_deadline,
        }
        # the total rides the SAME read the headroom registry probes —
        # one source of truth, never a second hand-summed code path
        out["outstanding_total"] = self.headroom_probe()["depth"]
        for ep in self.endpoints:
            pre = f"ep{ep.index}"
            out[f"{pre}_address"] = ep.address
            out[f"{pre}_state"] = STATE_NUM[ep.breaker.state]
            out[f"{pre}_outstanding"] = ep.outstanding
            out[f"{pre}_solves"] = ep.solves
            out[f"{pre}_failures"] = ep.failures
            out[f"{pre}_breaker_opens"] = ep.breaker.opens
            out[f"{pre}_mesh_devices"] = ep.mesh_devices
        return out

    def headroom_probe(self) -> Dict[str, float]:
        """In-flight solve RPCs across the pool (introspect/headroom.py).
        Unbounded in code — the forecast watches the fill rate: a rate
        that outruns the sidecars' drain is the elastic-fleet scale-up
        signal. drops = failovers (attempts that fell through)."""
        return {"depth": float(sum(ep.outstanding
                                   for ep in self.endpoints)),
                "capacity": 0.0,
                "drops": float(self.failovers)}

    def breaker_states(self) -> Dict[str, str]:
        """address → breaker state (the per-endpoint gauge labels)."""
        return {ep.address: ep.breaker.state for ep in self.endpoints}

    def close(self) -> None:
        for ep in self.endpoints:
            ep.close()


def derive_solve_deadline(latency_budget_seconds: float) -> float:
    """The solve RPC deadline from the SLO latency budget (0.2 s budget
    → 10 s): small multiplier, documented in one place."""
    return float(latency_budget_seconds) * SOLVE_DEADLINE_MULTIPLIER
