from .mesh import solver_mesh
from .sharded import ShardedPack, sharded_pack, split_counts

__all__ = ["ShardedPack", "solver_mesh", "sharded_pack", "split_counts"]
