from .mesh import MeshPlan, plan_mesh, solver_mesh
from .sharded import (ShardedPack, shard_groups, sharded_pack,
                      split_counts)

__all__ = ["ChaosSidecar", "MeshPlan", "RemoteSolver", "ShardedPack",
           "SidecarProtocolError", "SolverClient", "SolverPool",
           "SolverService", "plan_mesh", "serve_sidecar", "shard_groups",
           "solver_mesh", "sharded_pack", "split_counts"]

_SIDECAR = {"ChaosSidecar": "ChaosSidecar", "RemoteSolver": "RemoteSolver",
            "SidecarProtocolError": "SidecarProtocolError",
            "SolverClient": "SolverClient", "SolverService": "SolverService",
            "serve_sidecar": "serve"}


def __getattr__(name):
    # lazy: the sidecar/pool pull in grpc, which must stay optional for
    # the sharded-solve path (solver/solve.py imports this package on
    # every multi-chip solve)
    if name in _SIDECAR:
        from . import sidecar
        return getattr(sidecar, _SIDECAR[name])
    if name == "SolverPool":
        from .pool import SolverPool
        return SolverPool
    raise AttributeError(name)
