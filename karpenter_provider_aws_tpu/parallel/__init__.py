from .mesh import solver_mesh
from .sharded import sharded_pack, split_counts

__all__ = ["solver_mesh", "sharded_pack", "split_counts"]
