from .mesh import MeshPlan, plan_mesh, solver_mesh
from .sharded import (ShardedPack, shard_groups, sharded_pack,
                      split_counts)

__all__ = ["MeshPlan", "RemoteSolver", "ShardedPack", "SolverClient",
           "SolverService", "plan_mesh", "serve_sidecar", "shard_groups",
           "solver_mesh", "sharded_pack", "split_counts"]

_SIDECAR = {"RemoteSolver": "RemoteSolver", "SolverClient": "SolverClient",
            "SolverService": "SolverService", "serve_sidecar": "serve"}


def __getattr__(name):
    # lazy: the sidecar pulls in grpc, which must stay optional for the
    # sharded-solve path (solver/solve.py imports this package on every
    # multi-chip solve)
    if name in _SIDECAR:
        from . import sidecar
        return getattr(sidecar, _SIDECAR[name])
    raise AttributeError(name)
