"""Solver sidecar: the host↔solver gRPC transport.

SURVEY §2.3 ("communication backend") and §7 ("calls the solver — gRPC
sidecar in-process first"): the device solver runs as a service so a
controller in another process — or another language; the wire format is
plain JSON (apis/serde.py) over unary gRPC — can ship cluster state in
and get NodePlans back. The reference's equivalent transport is the kube
API watch stream + SQS long-poll (pkg/providers/sqs/sqs.go:52-72); here
the hot path is the Solve RPC, and the lattice stays RESIDENT in the
sidecar process (SURVEY §7 hard part (d): ship only pod deltas, never the
700-type lattice).

Methods (all unary, raw-bytes payloads so no protoc codegen is needed):
- /karpenter.solver.v1.Solver/Solve   — pods+pools+state → NodePlan
- /karpenter.solver.v1.Solver/Health  — lattice shape + price version

Transport: any gRPC address. ``unix:`` sockets for the local sidecar
(no TCP hop), ``host:port`` when the solver pool lives across DCN.
"""

from __future__ import annotations

import json
import math
from typing import Dict, List, Optional, Sequence

import grpc
import numpy as np

from .. import trace
from ..apis import serde
from ..solver.solve import NodePlan, Solver

_SOLVE = "/karpenter.solver.v1.Solver/Solve"
_HEALTH = "/karpenter.solver.v1.Solver/Health"

# liveness deadline: the Health RPC answers from the resident lattice
# (no device work), so ~1 s bounds a probe against a hung process
HEALTH_TIMEOUT_SECONDS = 1.0


class SidecarProtocolError(RuntimeError):
    """The sidecar ANSWERED, but not with a NodePlan: the connection
    died after a partial body, or the body failed to decode (garbage
    JSON back). Distinct from grpc.RpcError — the transport worked —
    but it classifies exactly the same way at the call site: a sidecar
    failure that falls through the ladder (breaker failure + failover /
    local fallback), never a json.JSONDecodeError out of a provisioning
    pass."""


class SolverService:
    """Server-side request handling around a resident Solver.

    ``window`` (batcher/solve_window.py SolveWindow) fronts the Solve
    RPC with the device-batch admission window: concurrent RPCs coalesce
    into one back-to-back drain under a single solver-lock acquisition
    instead of paying the tunneled link serially, caller by caller."""

    def __init__(self, solver: Solver, window=None):
        # Solver is thread-safe (its public entry points serialize on an
        # internal RLock), so RPCs and in-process controller solves on the
        # same instance interleave safely
        self.solver = solver
        self.window = window
        self._mask_memo = None  # (key, view) — see _masked_lattice

    def solve(self, payload: bytes) -> bytes:
        req = json.loads(payload.decode())
        # trace context crosses the process boundary as a field in the
        # JSON body (the wire stays plain cross-language JSON — no gRPC
        # metadata dependency); the handler's span is the remote child of
        # the caller's span, marked svc=sidecar so a merged Perfetto
        # export shows which process ran what
        tc = req.get("traceContext")
        sp = trace.span("sidecar.solve", parent=tc, svc="sidecar",
                        pods=len(req.get("pods", ())))
        with sp:
            doc = self._solve(req)
        if tc and isinstance(sp, trace.Span):
            # ship this process's completed spans back in the response:
            # the CALLER's flight recorder then holds one connected tree
            # across the process boundary (SolverClient ingests them,
            # deduped by span id) — the sidecar is a leaf service with no
            # query surface of its own in the operator's deployment
            rec = trace.recorder()
            spans = rec.get(sp.trace_id) if rec is not None else None
            if spans:
                doc["traceSpans"] = [s.to_dict() for s in spans]
        return json.dumps(doc).encode()

    def _solve(self, req: dict) -> dict:
        from ..solver.topology import BoundPod

        pods = [serde.pod_from_dict(p) for p in req.get("pods", ())]
        pools = [serde.nodepool_from_dict(p)
                 for p in req.get("nodePools", ())]
        existing = [serde.existing_bin_from_dict(b)
                    for b in req.get("existing", ())]
        ds = [serde.pod_from_dict(p) for p in req.get("daemonsetPods", ())]
        bound = [BoundPod(pod=serde.pod_from_dict(b["pod"]),
                          node_name=b["nodeName"], zone=b.get("zone", ""),
                          capacity_type=b.get("capacityType", "on-demand"),
                          node_labels=dict(b.get("nodeLabels", {})))
                 for b in req.get("boundPods", ())]
        pvcs = {c["name"]: serde.pvc_from_dict(c)
                for c in req.get("pvcs", ())} or None
        scs = {s["name"]: serde.storage_class_from_dict(s)
               for s in req.get("storageClasses", ())} or None
        # null = unlimited axis (np.inf is not representable in strict
        # RFC 8259 JSON, and the wire must stay cross-language)
        headroom = {k: np.asarray([np.inf if x is None else x for x in v],
                                  np.float32)
                    for k, v in (req.get("poolHeadroom") or {}).items()} or None
        view = self._masked_lattice(req.get("unavailable"))
        entry = self.window if self.window is not None else self.solver
        plan = entry.solve_relaxed(
            pods, pools, lattice=view, existing=existing, daemonset_pods=ds,
            bound_pods=bound, pvcs=pvcs, storage_classes=scs,
            pool_headroom=headroom)
        return serde.plan_to_dict(plan)

    def _masked_lattice(self, unavailable):
        """Apply the caller's ICE'd offerings to the RESIDENT lattice.

        A remote caller (RemoteSolver) cannot ship its masked lattice view
        — the whole point of the sidecar is that the lattice never crosses
        the wire — so it ships the unavailable (capacityType, instanceType,
        zone) triples instead and the mask is rebuilt here. None/empty =
        the unmasked resident lattice (and solve_relaxed's ``lattice=None``
        default path)."""
        if not unavailable:
            return None
        from ..cache.unavailable import mask_from_entries
        from ..lattice.tensors import masked_view
        lat = self.solver.lattice
        key = (lat.price_version, tuple(sorted(map(tuple, unavailable))))
        if self._mask_memo is not None and self._mask_memo[0] == key:
            return self._mask_memo[1]
        view = masked_view(lat, mask_from_entries(lat, unavailable))
        # memoize ONE view: a steady operator re-sends the same ICE set
        # every pass, and view identity keys the solver's narrowing cache
        self._mask_memo = (key, view)
        return view

    def health(self, payload: bytes) -> bytes:
        lat = self.solver.lattice
        return json.dumps({
            "ok": True,
            "types": lat.T, "zones": lat.Z, "capacityTypes": lat.C,
            "priceVersion": lat.price_version,
            # the sidecar's mesh shape: a caller (and `kpctl top`
            # against the sidecar's own introspection) sees whether the
            # accelerator-resident solve is sharded and how wide
            "meshDevices": getattr(self.solver, "mesh_devices", 1),
        }).encode()


class _Handler(grpc.GenericRpcHandler):
    def __init__(self, service: SolverService):
        self._service = service

    def service(self, handler_call_details):
        if handler_call_details.method == _SOLVE:
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: self._service.solve(req))
        if handler_call_details.method == _HEALTH:
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: self._service.health(req))
        return None


def serve(solver: Solver, address: str = "unix:/tmp/karpenter-solver.sock",
          max_workers: int = 4, admission_window: bool = True) -> grpc.Server:
    """Start the sidecar on ``address``; returns the running server.

    ``admission_window`` fronts the Solve RPC with the device-batch
    coalescing window (batcher/solve_window.py) so concurrent RPC
    workers fuse into one device drain instead of serializing on the
    link; disable it for single-caller latency tests."""
    from concurrent.futures import ThreadPoolExecutor
    window = None
    if admission_window:
        from ..batcher import SolveWindow
        window = SolveWindow(solver)
        # the coalescing window reports occupancy/fusion counters to the
        # process's introspection registry (docs/reference/introspection.md)
        from .. import introspect
        introspect.registry().register("solve_window", window.stats)
        introspect.registry().register("solver", solver.stats)
    server = grpc.server(ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers(
        (_Handler(SolverService(solver, window=window)),))
    # add_insecure_port signals bind failure by returning 0, not raising
    # (unix: sockets return 1 on success)
    if server.add_insecure_port(address) == 0:
        raise RuntimeError(f"sidecar failed to bind {address!r}")
    server.start()
    return server


class SolverClient:
    """Thin client. ``solve()`` mirrors Solver.solve_relaxed's signature
    and returns a real NodePlan (decoded from the wire)."""

    def __init__(self, address: str = "unix:/tmp/karpenter-solver.sock",
                 timeout: float = 60.0,
                 health_timeout: float = HEALTH_TIMEOUT_SECONDS):
        self.address = address
        # bound the channel's OWN reconnect backoff: grpc's default
        # schedule grows toward 120 s after repeated failures, which
        # would push the first post-restart connection attempt far past
        # a 1 s health probe's wait_for_ready window — recovery would be
        # detected minutes late. ≤500 ms keeps at least one attempt
        # inside every probe deadline.
        self._channel = grpc.insecure_channel(address, options=[
            ("grpc.initial_reconnect_backoff_ms", 250),
            ("grpc.min_reconnect_backoff_ms", 250),
            ("grpc.max_reconnect_backoff_ms", 500),
        ])
        self._solve = self._channel.unary_unary(_SOLVE)
        self._health = self._channel.unary_unary(_HEALTH)
        self.timeout = timeout
        # liveness must NEVER share the solve deadline: a health probe
        # against a HUNG sidecar (accepts, stalls) has to answer in ~1 s
        # so kpctl and the pool's breaker probes are cheap — with the
        # old shared timeout it stalled a full solve timeout (60 s)
        self.health_timeout = health_timeout

    def solve(self, pods: Sequence, node_pools: Sequence,
              existing: Sequence = (), daemonset_pods: Sequence = (),
              bound_pods: Sequence = (), pvcs: Optional[Dict] = None,
              storage_classes: Optional[Dict] = None,
              pool_headroom: Optional[Dict] = None,
              unavailable: Sequence = ()) -> NodePlan:
        req = {
            "pods": [serde.pod_to_dict(p) for p in pods],
            "nodePools": [serde.nodepool_to_dict(p) for p in node_pools],
            "existing": [serde.existing_bin_to_dict(b) for b in existing],
            "daemonsetPods": [serde.pod_to_dict(p) for p in daemonset_pods],
            "boundPods": [
                {"pod": serde.pod_to_dict(b.pod), "nodeName": b.node_name,
                 "zone": b.zone, "capacityType": b.capacity_type,
                 "nodeLabels": dict(b.node_labels)}
                for b in bound_pods],
            "pvcs": [serde.pvc_to_dict(c)
                     for c in (pvcs or {}).values()],
            "storageClasses": [serde.storage_class_to_dict(s)
                               for s in (storage_classes or {}).values()],
            "poolHeadroom": ({k: [None if not math.isfinite(float(x))
                                  else float(x) for x in v]
                              for k, v in pool_headroom.items()}
                             if pool_headroom else None),
        }
        if unavailable:
            # the caller's ICE'd offerings, as (capacityType,
            # instanceType, zone) triples — the sidecar rebuilds the mask
            # over ITS resident lattice (SolverService._masked_lattice)
            req["unavailable"] = [list(o) for o in unavailable]
        tc = trace.capture()
        if tc:
            # propagate the caller's span as the RPC's remote parent so
            # the sidecar's device solve joins this trace across the
            # process boundary (docs/reference/tracing.md wire format)
            req["traceContext"] = tc
        resp = self._solve(json.dumps(req).encode(), timeout=self.timeout)
        # a response that is not a NodePlan document classifies as a
        # SIDECAR failure (SidecarProtocolError), exactly like an
        # RpcError: the caller's ladder/pool handles it — a junk body
        # must never surface as a JSONDecodeError out of a pass
        try:
            doc = json.loads(resp.decode())
            if not isinstance(doc, dict):
                raise ValueError(f"non-object response ({type(doc).__name__})")
        except (ValueError, UnicodeDecodeError) as e:
            raise SidecarProtocolError(
                f"sidecar {self.address} returned an undecodable "
                f"response: {e}") from e
        remote_spans = doc.pop("traceSpans", None)
        if remote_spans and tc:
            # the sidecar shipped its completed spans back: land them in
            # THIS process's flight recorder so /debug/traces serves one
            # connected tree across the process boundary
            rec = trace.recorder()
            if rec is not None:
                rec.ingest(remote_spans)
        try:
            return serde.plan_from_dict(doc)
        except (KeyError, TypeError, ValueError) as e:
            raise SidecarProtocolError(
                f"sidecar {self.address} returned a malformed plan "
                f"document: {type(e).__name__}: {e}") from e

    def health(self) -> Dict:
        # wait_for_ready: a probe against a just-restarted endpoint must
        # FORCE a reconnect attempt instead of failing fast out of the
        # channel's own TRANSIENT_FAILURE backoff — recovery detection
        # is this RPC's whole job, and the ~1 s deadline bounds it
        return json.loads(
            self._health(b"{}", timeout=self.health_timeout,
                         wait_for_ready=True).decode())

    def close(self) -> None:
        self._channel.close()


def classify_sidecar_failure(exc) -> str:
    """Sidecar RPC failure → bounded taxonomy code (solver/taxonomy.py):
    ``sidecar-hung`` for a deadline-class failure (the endpoint accepted
    and stalled — the failure mode that costs a whole solve deadline),
    ``sidecar-unreachable`` for everything else (connection refused /
    reset, junk response, mid-body death)."""
    from ..solver.taxonomy import SIDECAR_HUNG, SIDECAR_UNREACHABLE
    if isinstance(exc, grpc.RpcError) and hasattr(exc, "code"):
        if exc.code() == grpc.StatusCode.DEADLINE_EXCEEDED:
            return SIDECAR_HUNG
    return SIDECAR_UNREACHABLE


class RemoteSolver(Solver):
    """A Solver whose provisioning solves run in the solver SIDECAR
    process (``--solver-address``): the operator ships pod deltas + the
    ICE mask over the Solve RPC and the lattice stays resident next to
    the accelerator. Everything else — probe_batch (the disruption
    controller's vmapped what-ifs), lattice queries, warmup — stays on
    the LOCAL Solver this subclasses, so a sidecar outage degrades to the
    in-process ladder instead of stalling the control plane."""

    # provisioning solves belong to the sidecar: the provisioner's
    # steady-state delta path (an in-process resident-cache fast path)
    # would silently bypass the delegation, so it stays off here
    supports_delta = False

    def __init__(self, lattice, address: str, timeout: float = 60.0,
                 pipeline: bool = True, mesh=None):
        # the planned mesh applies to the LOCAL fallback ladder; the
        # sidecar process plans its own (its stats/health report it)
        super().__init__(lattice, pipeline=pipeline, mesh=mesh)
        self.client = SolverClient(address, timeout=timeout)
        # the SIDECAR's mesh as observed from returned plans (the wire
        # carries meshDevices + shardImbalance per plan): the operator's
        # mesh gauges and kpctl top must describe the process that
        # actually solves — the sidecar while delegation works, THIS
        # process's local fallback the moment it doesn't (the
        # unreachable path resets the observation, so an outage never
        # keeps advertising a mesh nothing is solving on). Updated
        # lock-free from each solve — stats() must stay non-blocking,
        # so no health RPC from the introspection path.
        self._remote_mesh_devices = 0
        self._remote_mesh_solves = 0
        self._remote_mesh_imbalance = 0.0

    def stats(self) -> Dict[str, object]:
        out = super().stats()
        # mesh_solves is cumulative EVIDENCE (local fallback + every
        # sharded plan the sidecar returned) — it never goes backwards
        out["mesh_solves"] = (out.get("mesh_solves", 0)
                              + self._remote_mesh_solves)
        if self._remote_mesh_devices:
            out["mesh_devices"] = self._remote_mesh_devices
            # the imbalance of the mesh that actually solved — never the
            # local fallback's (which has run no sharded solve)
            out["mesh_shard_imbalance"] = round(
                self._remote_mesh_imbalance, 4)
        return out

    def _unavailable_entries(self, view) -> List:
        """Recover the ICE'd offerings from a masked lattice view by
        diffing availability against the base lattice — the provisioner
        hands solve_relaxed a VIEW (lattice/tensors.py masked_view), and
        the triples are what crosses the wire."""
        base = self.lattice
        if view is None or view is base:
            return []
        diff = base.available & ~view.available
        if not diff.any():
            return []
        return [(base.capacity_types[ci], base.names[ti], base.zones[zi])
                for ti, zi, ci in np.argwhere(diff)]

    def solve_relaxed(self, pods, node_pools, lattice=None, existing=(),
                      daemonset_pods=(), bound_pods=(), pvcs=None,
                      storage_classes=None, mesh=None,
                      pool_headroom=None, problem0=None) -> NodePlan:
        # problem0 is a LOCAL-build shortcut; the remote path ships pods
        # and rebuilds sidecar-side, so it is meaningful only for the
        # unreachable-fallback local solve below
        with trace.span("solver.remote", pods=len(pods),
                        address=self.client.address) as sp:
            try:
                plan = self.client.solve(
                    pods, node_pools, existing=existing,
                    daemonset_pods=daemonset_pods, bound_pods=bound_pods,
                    pvcs=pvcs, storage_classes=storage_classes,
                    pool_headroom=pool_headroom,
                    unavailable=self._unavailable_entries(lattice))
                sp.set(path=plan.solver_path, degraded=plan.degraded,
                       reason=plan.degraded_reason)
                self._remote_mesh_devices = plan.mesh_devices
                self._remote_mesh_imbalance = plan.shard_imbalance
                if plan.mesh_devices > 1:
                    self._remote_mesh_solves += 1
                return plan
            except (grpc.RpcError, SidecarProtocolError) as e:
                # the sidecar is down, hung, or talking garbage: the
                # local solver this subclasses is fully functional —
                # degrade to it (one more rung under the device ladder)
                # rather than failing the pass; provenance marks the
                # plan with the bounded taxonomy code so the flight
                # recorder tail-retains the trace and operators see WHY.
                # A mid-response failure (connection died after a
                # partial body / junk JSON back) arrives here as
                # SidecarProtocolError — never a JSONDecodeError out of
                # the pass.
                reason = classify_sidecar_failure(e)
                sp.set(degraded=True, reason=reason,
                       error=f"{type(e).__name__}: {e.code() if isinstance(e, grpc.RpcError) and hasattr(e, 'code') else e}")
        # delegation failed: the LOCAL solver is what solves now — stop
        # reporting the unreachable sidecar's mesh shape (stats falls
        # back to super()'s view until a delegated solve succeeds
        # again; the cumulative sharded-solve count stays)
        self._remote_mesh_devices = 0
        self._remote_mesh_imbalance = 0.0
        self._count_degraded(reason)
        plan = super().solve_relaxed(
            pods, node_pools, lattice=lattice, existing=existing,
            daemonset_pods=daemonset_pods, bound_pods=bound_pods,
            pvcs=pvcs, storage_classes=storage_classes, mesh=mesh,
            pool_headroom=pool_headroom, problem0=problem0)
        plan.degraded = True
        plan.degraded_reason = plan.degraded_reason or reason
        return plan


class ChaosSolverService(SolverService):
    """A SolverService with injectable failure modes — the server half
    of control-plane weather (weather/scenario.py ``SidecarOutage``) and
    the pool failover tests:

    - **hang**: the handler ACCEPTS the RPC and stalls until the mode
      clears (bounded far past any deadline) — the failure mode a
      connect error never exercises; the caller's deadline, not the
      sidecar, ends the wait;
    - **junk**: the handler answers with bytes that are not a NodePlan
      document — the mid-response/garbage failure SolverClient must
      classify as SidecarProtocolError, never leak as JSONDecodeError.
    """

    # hang cap: far past any sane deadline, bounded so a torn-down test
    # or soak can never leak a stalled worker thread forever
    HANG_CAP_SECONDS = 120.0

    def __init__(self, solver: Solver, window=None):
        super().__init__(solver, window)
        import threading
        self._hanging = False
        self._junk = False
        self._release = threading.Event()
        self._release.set()

    def set_hang(self, on: bool) -> None:
        if on:
            self._release.clear()
            self._hanging = True
        else:
            self._hanging = False
            self._release.set()

    def set_junk(self, on: bool) -> None:
        self._junk = bool(on)

    def _maybe_misbehave(self) -> Optional[bytes]:
        if self._hanging:
            # stall in small waits so set_hang(False) releases promptly;
            # the loop bound (not a deadline of our own) caps a leak
            waited = 0.0
            while self._hanging and waited < self.HANG_CAP_SECONDS:
                if self._release.wait(0.1):
                    break
                waited += 0.1
        if self._junk:
            return b"\x7bgarbage: this is not a NodePlan\x00"
        return None

    def solve(self, payload: bytes) -> bytes:
        bad = self._maybe_misbehave()
        return bad if bad is not None else super().solve(payload)

    def health(self, payload: bytes) -> bytes:
        # a hung PROCESS hangs everything, liveness included — that is
        # exactly what the split health deadline exists to bound
        bad = self._maybe_misbehave()
        return bad if bad is not None else super().health(payload)


class ChaosSidecar:
    """One controllable sidecar endpoint: serve/kill/restart on a fixed
    address plus the ChaosSolverService failure modes. The handle the
    weather simulator drives (``WeatherSimulator(sidecars=[...])``) and
    tools/soak.py ``--solver-pool`` / tools/smoke_pool.py manage."""

    def __init__(self, solver: Solver, address: str):
        self.solver = solver
        self.address = address
        self.service = ChaosSolverService(solver)
        self.server: Optional[grpc.Server] = None
        self.alive = False

    def start(self) -> "ChaosSidecar":
        from concurrent.futures import ThreadPoolExecutor
        server = grpc.server(ThreadPoolExecutor(max_workers=4))
        server.add_generic_rpc_handlers((_Handler(self.service),))
        if server.add_insecure_port(self.address) == 0:
            raise RuntimeError(f"chaos sidecar failed to bind "
                               f"{self.address!r}")
        server.start()
        self.server = server
        self.alive = True
        return self

    def kill(self) -> None:
        """The endpoint goes DARK (connection refused), releasing any
        hung handlers so worker threads never leak."""
        self.service.set_hang(False)
        if self.server is not None:
            self.server.stop(grace=None)
            self.server = None
        self.alive = False

    def restart(self) -> None:
        """Re-serve on the SAME address (the pool's endpoint list is
        fixed — recovery means the address answers again), with failure
        modes cleared: a restarted process comes back healthy."""
        self.service.set_hang(False)
        self.service.set_junk(False)
        if not self.alive:
            self.start()

    def set_hang(self, on: bool) -> None:
        self.service.set_hang(on)

    def set_junk(self, on: bool) -> None:
        self.service.set_junk(on)

    def restore(self) -> None:
        """Fair weather: alive, no failure modes."""
        self.restart()

    def stop(self) -> None:
        self.kill()


def main(argv: Optional[Sequence[str]] = None) -> int:
    """Standalone solver sidecar: ``python -m
    karpenter_provider_aws_tpu.parallel.sidecar --address ADDR``.

    The deployment shape the paper's architecture implies — the device
    solver as its own accelerator-resident process, the operator's
    control loop elsewhere pointing at it via ``--solver-address``."""
    import argparse
    import signal
    import threading

    p = argparse.ArgumentParser(
        prog="karpenter-solver-sidecar", description=main.__doc__)
    p.add_argument("--address", default="unix:/tmp/karpenter-solver.sock",
                   help="gRPC bind address (unix:/path or host:port)")
    p.add_argument("--catalog", default=None,
                   help="path to a real-data catalog JSON "
                        "(lattice/realdata.py schema); default = the "
                        "bundled reference catalog")
    p.add_argument("--synthetic-catalog", action="store_true",
                   help="use the generated synthetic catalog instead of "
                        "the bundled reference data")
    p.add_argument("--no-admission-window", action="store_true",
                   help="serve without the solve-coalescing window")
    p.add_argument("--mesh", default=None,
                   help="device mesh for the sharded solve (env "
                        "SOLVER_MESH; parallel/mesh.py plan_mesh): "
                        "'auto' (default), an integer device count, or "
                        "'off' — the sidecar is the accelerator-resident "
                        "process, so this is where the mesh actually "
                        "lives in a --solver-address deployment")
    p.add_argument("--trace", action="store_true",
                   help="enable tracing: the Solve handler's span tree "
                        "ships back to callers in the RPC response")
    args = p.parse_args(argv)

    if args.trace:
        from .. import trace as _trace
        _trace.enable()
        # every span this process opens is the sidecar's (a merged
        # Perfetto export renders it as its own process row)
        _trace.get_tracer().service = "sidecar"
    from ..lattice import build_lattice
    if args.synthetic_catalog:
        lattice = build_lattice()
    else:
        from ..lattice.realdata import load_catalog
        lattice = build_lattice(load_catalog(args.catalog,
                                             require_price=True))
    import os

    from .mesh import plan_mesh
    mesh_plan = plan_mesh(args.mesh or os.environ.get("SOLVER_MESH", "auto"))
    solver = Solver(lattice, mesh=mesh_plan.mesh)
    server = serve(solver, args.address,
                   admission_window=not args.no_admission_window)
    print(f"solver sidecar serving on {args.address} "
          f"(T={lattice.T} Z={lattice.Z} C={lattice.C} "
          f"mesh={mesh_plan.devices})", flush=True)
    stop = threading.Event()
    try:
        signal.signal(signal.SIGINT, lambda *_: stop.set())
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
    except ValueError:
        pass
    stop.wait()
    server.stop(grace=None)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
