"""Solver sidecar: the host↔solver gRPC transport.

SURVEY §2.3 ("communication backend") and §7 ("calls the solver — gRPC
sidecar in-process first"): the device solver runs as a service so a
controller in another process — or another language; the wire format is
plain JSON (apis/serde.py) over unary gRPC — can ship cluster state in
and get NodePlans back. The reference's equivalent transport is the kube
API watch stream + SQS long-poll (pkg/providers/sqs/sqs.go:52-72); here
the hot path is the Solve RPC, and the lattice stays RESIDENT in the
sidecar process (SURVEY §7 hard part (d): ship only pod deltas, never the
700-type lattice).

Methods (all unary, raw-bytes payloads so no protoc codegen is needed):
- /karpenter.solver.v1.Solver/Solve   — pods+pools+state → NodePlan
- /karpenter.solver.v1.Solver/Health  — lattice shape + price version

Transport: any gRPC address. ``unix:`` sockets for the local sidecar
(no TCP hop), ``host:port`` when the solver pool lives across DCN.
"""

from __future__ import annotations

import json
import math
from typing import Dict, List, Optional, Sequence

import grpc
import numpy as np

from ..apis import serde
from ..solver.solve import NodePlan, Solver

_SOLVE = "/karpenter.solver.v1.Solver/Solve"
_HEALTH = "/karpenter.solver.v1.Solver/Health"


class SolverService:
    """Server-side request handling around a resident Solver.

    ``window`` (batcher/solve_window.py SolveWindow) fronts the Solve
    RPC with the device-batch admission window: concurrent RPCs coalesce
    into one back-to-back drain under a single solver-lock acquisition
    instead of paying the tunneled link serially, caller by caller."""

    def __init__(self, solver: Solver, window=None):
        # Solver is thread-safe (its public entry points serialize on an
        # internal RLock), so RPCs and in-process controller solves on the
        # same instance interleave safely
        self.solver = solver
        self.window = window

    def solve(self, payload: bytes) -> bytes:
        from ..solver.topology import BoundPod

        req = json.loads(payload.decode())
        pods = [serde.pod_from_dict(p) for p in req.get("pods", ())]
        pools = [serde.nodepool_from_dict(p)
                 for p in req.get("nodePools", ())]
        existing = [serde.existing_bin_from_dict(b)
                    for b in req.get("existing", ())]
        ds = [serde.pod_from_dict(p) for p in req.get("daemonsetPods", ())]
        bound = [BoundPod(pod=serde.pod_from_dict(b["pod"]),
                          node_name=b["nodeName"], zone=b.get("zone", ""),
                          capacity_type=b.get("capacityType", "on-demand"),
                          node_labels=dict(b.get("nodeLabels", {})))
                 for b in req.get("boundPods", ())]
        pvcs = {c["name"]: serde.pvc_from_dict(c)
                for c in req.get("pvcs", ())} or None
        scs = {s["name"]: serde.storage_class_from_dict(s)
               for s in req.get("storageClasses", ())} or None
        # null = unlimited axis (np.inf is not representable in strict
        # RFC 8259 JSON, and the wire must stay cross-language)
        headroom = {k: np.asarray([np.inf if x is None else x for x in v],
                                  np.float32)
                    for k, v in (req.get("poolHeadroom") or {}).items()} or None
        entry = self.window if self.window is not None else self.solver
        plan = entry.solve_relaxed(
            pods, pools, existing=existing, daemonset_pods=ds,
            bound_pods=bound, pvcs=pvcs, storage_classes=scs,
            pool_headroom=headroom)
        return json.dumps(serde.plan_to_dict(plan)).encode()

    def health(self, payload: bytes) -> bytes:
        lat = self.solver.lattice
        return json.dumps({
            "ok": True,
            "types": lat.T, "zones": lat.Z, "capacityTypes": lat.C,
            "priceVersion": lat.price_version,
        }).encode()


class _Handler(grpc.GenericRpcHandler):
    def __init__(self, service: SolverService):
        self._service = service

    def service(self, handler_call_details):
        if handler_call_details.method == _SOLVE:
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: self._service.solve(req))
        if handler_call_details.method == _HEALTH:
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: self._service.health(req))
        return None


def serve(solver: Solver, address: str = "unix:/tmp/karpenter-solver.sock",
          max_workers: int = 4, admission_window: bool = True) -> grpc.Server:
    """Start the sidecar on ``address``; returns the running server.

    ``admission_window`` fronts the Solve RPC with the device-batch
    coalescing window (batcher/solve_window.py) so concurrent RPC
    workers fuse into one device drain instead of serializing on the
    link; disable it for single-caller latency tests."""
    from concurrent.futures import ThreadPoolExecutor
    window = None
    if admission_window:
        from ..batcher import SolveWindow
        window = SolveWindow(solver)
    server = grpc.server(ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers(
        (_Handler(SolverService(solver, window=window)),))
    # add_insecure_port signals bind failure by returning 0, not raising
    # (unix: sockets return 1 on success)
    if server.add_insecure_port(address) == 0:
        raise RuntimeError(f"sidecar failed to bind {address!r}")
    server.start()
    return server


class SolverClient:
    """Thin client. ``solve()`` mirrors Solver.solve_relaxed's signature
    and returns a real NodePlan (decoded from the wire)."""

    def __init__(self, address: str = "unix:/tmp/karpenter-solver.sock",
                 timeout: float = 60.0):
        self._channel = grpc.insecure_channel(address)
        self._solve = self._channel.unary_unary(_SOLVE)
        self._health = self._channel.unary_unary(_HEALTH)
        self.timeout = timeout

    def solve(self, pods: Sequence, node_pools: Sequence,
              existing: Sequence = (), daemonset_pods: Sequence = (),
              bound_pods: Sequence = (), pvcs: Optional[Dict] = None,
              storage_classes: Optional[Dict] = None,
              pool_headroom: Optional[Dict] = None) -> NodePlan:
        req = {
            "pods": [serde.pod_to_dict(p) for p in pods],
            "nodePools": [serde.nodepool_to_dict(p) for p in node_pools],
            "existing": [serde.existing_bin_to_dict(b) for b in existing],
            "daemonsetPods": [serde.pod_to_dict(p) for p in daemonset_pods],
            "boundPods": [
                {"pod": serde.pod_to_dict(b.pod), "nodeName": b.node_name,
                 "zone": b.zone, "capacityType": b.capacity_type,
                 "nodeLabels": dict(b.node_labels)}
                for b in bound_pods],
            "pvcs": [serde.pvc_to_dict(c)
                     for c in (pvcs or {}).values()],
            "storageClasses": [serde.storage_class_to_dict(s)
                               for s in (storage_classes or {}).values()],
            "poolHeadroom": ({k: [None if not math.isfinite(float(x))
                                  else float(x) for x in v]
                              for k, v in pool_headroom.items()}
                             if pool_headroom else None),
        }
        resp = self._solve(json.dumps(req).encode(), timeout=self.timeout)
        return serde.plan_from_dict(json.loads(resp.decode()))

    def health(self) -> Dict:
        return json.loads(self._health(b"{}", timeout=self.timeout).decode())

    def close(self) -> None:
        self._channel.close()
