"""Device mesh plumbing for the sharded solver.

The reference scales its scheduling loop with controller concurrency and
batching windows (SURVEY.md §2.3); the TPU-native scale axis is the pod
dimension sharded over a `jax.sharding.Mesh` ('pods' axis), with XLA
collectives (psum / all_gather over ICI) reducing pack results — the
DP/SP slot of this build. Multi-host extends the same mesh over DCN.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh


def solver_mesh(n_devices: Optional[int] = None, axis: str = "pods") -> Mesh:
    """A 1-D mesh over the pod axis.

    ``n_devices=None`` uses every default-backend device. When the default
    backend is short (e.g. a single real TPU chip while the virtual CPU
    backend carries 8 forced host devices for sharding dry-runs), falls back
    to the cpu backend's device list.
    """
    devices = jax.devices()
    if n_devices is not None and len(devices) < n_devices:
        try:
            cpu = jax.devices("cpu")
        except RuntimeError:
            cpu = []
        if len(cpu) >= n_devices:
            devices = cpu
        else:
            raise ValueError(f"need {n_devices} devices, have {len(devices)} "
                             f"(default backend) and {len(cpu)} (cpu)")
    if n_devices is not None:
        devices = devices[:n_devices]
    import numpy as np
    return Mesh(np.array(devices), (axis,))
