"""Device-mesh planning for the sharded solver.

The reference scales its scheduling loop with controller concurrency and
batching windows (SURVEY.md §2.3); the TPU-native scale axis is the pod
dimension sharded over a `jax.sharding.Mesh` ('pods' axis), with XLA
collectives (psum / all_gather over ICI) reducing pack results — the
DP/SP slot of this build. Multi-host extends the same mesh over DCN.

Since PR 12 the mesh is a boot-time decision, not a per-call argument:
:func:`plan_mesh` resolves the operator's ``--mesh``/``SOLVER_MESH``
setting against the devices JAX actually sees and hands the resulting
:class:`MeshPlan` to the Solver, which then runs EVERY solve — full,
wave-split, and the steady-state delta path — over that mesh
(docs/reference/sharding.md).

Auto policy: a real multi-chip backend (tpu/gpu with >1 device)
auto-meshes over every device. The **cpu backend never auto-meshes**:
its device count is the ``--xla_force_host_platform_device_count``
dry-run knob, not hardware — 8 virtual devices time-slicing one host
would make every solve slower, so auto stays single-device there and a
virtual mesh must be FORCED (``--mesh 8``), exactly how the multichip
dry-run, the sharded tests, and ``tools/smoke_sharded.py`` run.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
from jax.sharding import Mesh


@dataclass(frozen=True)
class MeshPlan:
    """A resolved mesh decision: how many devices, why, and the mesh
    itself (``None`` = the single-device passthrough — the Solver's
    non-sharded path, byte-identical to the pre-mesh behavior)."""

    devices: int
    axis: str
    source: str               # "auto" | "forced" | "single" | "off"
    mesh: Optional[Mesh]


def _single(axis: str, source: str) -> MeshPlan:
    return MeshPlan(devices=1, axis=axis, source=source, mesh=None)


def plan_mesh(spec: Optional[str] = None, axis: str = "pods") -> MeshPlan:
    """Resolve a mesh spec against the visible devices.

    ``spec``: ``None``/``""``/``"auto"`` auto-selects (all devices of a
    real multi-chip backend; single-device on cpu — see the module
    docstring), ``"off"``/``"none"``/``"single"``/``"1"`` pins the
    single-device passthrough, and an integer string forces an N-way
    mesh (falling back to the virtual cpu device list when the default
    backend is short, as ``__graft_entry__.dryrun_multichip`` does).
    Raises ValueError for an unparseable spec or an unsatisfiable
    forced device count.
    """
    s = (spec or "auto").strip().lower()
    if s in ("off", "none", "single", "1"):
        return _single(axis, "off")
    if s == "auto":
        devices = jax.devices()
        if len(devices) <= 1 or jax.default_backend() == "cpu":
            return _single(axis, "single")
        return MeshPlan(devices=len(devices), axis=axis, source="auto",
                        mesh=solver_mesh(len(devices), axis=axis))
    try:
        n = int(s)
    except ValueError:
        raise ValueError(
            f"invalid mesh spec {spec!r}: expected 'auto', 'off', or a "
            "positive device count")
    if n < 1:
        raise ValueError(f"mesh device count must be >= 1, got {n}")
    if n == 1:
        return _single(axis, "off")
    return MeshPlan(devices=n, axis=axis, source="forced",
                    mesh=solver_mesh(n, axis=axis))


def solver_mesh(n_devices: Optional[int] = None, axis: str = "pods") -> Mesh:
    """A 1-D mesh over the pod axis.

    ``n_devices=None`` uses every default-backend device. When the default
    backend is short (e.g. a single real TPU chip while the virtual CPU
    backend carries 8 forced host devices for sharding dry-runs), falls back
    to the cpu backend's device list.
    """
    devices = jax.devices()
    if n_devices is not None and len(devices) < n_devices:
        try:
            cpu = jax.devices("cpu")
        except RuntimeError:
            cpu = []
        if len(cpu) >= n_devices:
            devices = cpu
        else:
            raise ValueError(f"need {n_devices} devices, have {len(devices)} "
                             f"(default backend) and {len(cpu)} (cpu)")
    if n_devices is not None:
        devices = devices[:n_devices]
    import numpy as np
    return Mesh(np.array(devices), (axis,))
