"""Pod-axis sharded solve (DP over the pod dimension + ICI reductions).

Distributed design (the CP/ring-attention slot of this build, SURVEY.md §5
"long-context"): the 50k-pod axis is the long sequence. Strategy:

1. **Shard pods, replicate the lattice.** Each device receives an equal
   slice of every group's pod count (`split_counts`) and runs the full
   grouped-FFD scan locally against the replicated type lattice — a
   blockwise-greedy pack with zero cross-device traffic during the scan.
   Groups whose pods must co-locate (hostname self-affinity) or join a
   seeded bin (positive affinity) stay whole on one shard.
2. **Reduce with ICI collectives.** Total cost / node counts / leftovers
   reduce with `psum`; the full per-shard bin tables return stacked on the
   device axis for the host-side tail-bin merge (solver/solve.py
   ``Solver.solve(..., mesh=...)`` dissolves under-filled tail bins and
   re-packs them in one small single-device refinement solve — the ≤2%
   envelope guard, SURVEY.md §7 hard part a).
3. **Multi-host**: the same program over a DCN-spanning mesh; XLA routes the
   psum hierarchically (ICI within host, DCN across) — nothing to change in
   the program.

The shard_map'd function below is what dryrun_multichip compiles over an
N-device mesh.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import binpack


def split_counts(count: np.ndarray, n_devices: int,
                 keep_whole: Optional[np.ndarray] = None,
                 pin_shard0: Optional[np.ndarray] = None) -> np.ndarray:
    """[G] pod counts -> [D,G] balanced split (device d gets ~count/D).

    Groups flagged in ``keep_whole`` (co-location groups) are not split:
    each lands entirely on one shard, round-robin. Groups flagged in
    ``pin_shard0`` (presence-requiring ``need`` groups) go whole to shard 0,
    the only shard holding existing bins and their bound-pod affinity
    seeding (e_pm/e_po) — elsewhere their needs could never be met.
    """
    base = count // n_devices
    extra = count % n_devices
    out = np.tile(base, (n_devices, 1))
    for d in range(n_devices):
        out[d] += (d < extra).astype(count.dtype)
    if keep_whole is not None and keep_whole.any():
        whole = keep_whole.copy()
        if pin_shard0 is not None:
            whole &= ~pin_shard0
        whole = np.nonzero(whole)[0]
        for i, g in enumerate(whole):
            out[:, g] = 0
            out[i % n_devices, g] = count[g]
    if pin_shard0 is not None and pin_shard0.any():
        for g in np.nonzero(pin_shard0)[0]:
            out[:, g] = 0
            out[0, g] = count[g]
    return out


class ShardedPack(NamedTuple):
    """Per-shard pack results + ICI-reduced global aggregates.

    ``packed`` stacks each shard's fused decode buffer (ops/binpack.py
    ``_encode_decode_set``) along a leading device axis ([D, B+n, W] u8) —
    the host fetches ONE array for all shards (the host↔device link charges
    ~fixed latency per transfer) and decodes each shard's bin table exactly
    like a single-device result before merging tail bins.
    """

    packed: jnp.ndarray          # [D, B+n_trailer, W] u8
    total_cost: jnp.ndarray      # psum over shards: $/hr of live new bins
    total_nodes: jnp.ndarray     # psum over shards: live new-bin count
    total_leftover: jnp.ndarray  # psum over shards: pods no bin could take


def _local_pack(alloc, avail, price, pools, req, count_shard, init_shard, g_type, g_zone,
                g_cap, g_np, max_per_bin, spread_class, single_bin, match, owner, need,
                strict_custom):
    """Runs on each device over its pod-count shard; reduces over 'pods'."""
    count_local = count_shard.reshape(count_shard.shape[-1])  # [1,G] block -> [G]
    # each device gets its own bin table (existing capacity lives on shard 0
    # only — replicating it would fill the same physical nodes D times)
    init = binpack.BinState(*(x.reshape(x.shape[1:]) for x in init_shard))
    groups = binpack.GroupBatch(req=req, count=count_local, g_type=g_type,
                                g_zone=g_zone, g_cap=g_cap, g_np=g_np,
                                max_per_bin=max_per_bin, spread_class=spread_class,
                                single_bin=single_bin,
                                match=match, owner=owner, need=need,
                                strict_custom=strict_custom)
    res = binpack.pack(alloc, avail, price, groups, pools, init)
    live = res.state.open & ~res.state.fixed & (res.state.npods > 0)
    local_cost = jnp.sum(jnp.where(live, res.chosen_price, 0.0))
    local_nodes = jnp.sum(live.astype(jnp.int32))
    local_leftover = jnp.sum(res.leftover)
    # ICI reductions: global cost / node count / leftover
    total_cost = jax.lax.psum(local_cost, "pods")
    total_nodes = jax.lax.psum(local_nodes, "pods")
    total_leftover = jax.lax.psum(local_leftover, "pods")
    # fused per-shard decode buffer; the P('pods') out-spec stacks them
    return (binpack._encode_decode_set(res)[None],
            total_cost, total_nodes, total_leftover)


def sharded_pack(mesh: Mesh, alloc, avail, price, groups: binpack.GroupBatch,
                 pools: binpack.PoolParams, init: binpack.BinState,
                 count_split: np.ndarray) -> ShardedPack:
    """Compile + run the pod-sharded solve over ``mesh``.

    ``count_split`` is [D,G] from split_counts; the lattice and group masks
    are replicated (the lattice is the 'weights' of this model — resident on
    every device, exactly the TP-style layout that avoids re-sharding the
    lattice per step); the bin table is sharded so existing capacity lives on
    shard 0 only.
    """
    D = mesh.devices.size
    B = init.cum.shape[0]
    empty = binpack.empty_state(B, init.tmask.shape[1], init.zmask.shape[1],
                                init.cmask.shape[1], init.cum.shape[1],
                                init.pm.shape[1])
    init_stack = binpack.BinState(*(
        jnp.concatenate([jnp.asarray(a)[None], jnp.broadcast_to(jnp.asarray(e)[None], (D - 1,) + e.shape)])
        if D > 1 else jnp.asarray(a)[None]
        for a, e in zip(init, empty)
    ))

    repl = P()
    fn = jax.shard_map(
        partial(_local_pack, alloc, avail, price, pools),
        mesh=mesh,
        in_specs=(repl, P("pods"), jax.tree.map(lambda _: P("pods"), empty),
                  repl, repl, repl, repl, repl, repl, repl, repl, repl, repl, repl),
        out_specs=(P("pods"), repl, repl, repl),
        check_vma=False,
    )
    out = jax.jit(fn)(groups.req, jnp.asarray(count_split), init_stack, groups.g_type,
                      groups.g_zone, groups.g_cap, groups.g_np, groups.max_per_bin,
                      groups.spread_class, groups.single_bin, groups.match,
                      groups.owner, groups.need, groups.strict_custom)
    return ShardedPack(*out)
