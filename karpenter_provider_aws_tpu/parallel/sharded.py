"""Pod-axis sharded solve (DP over the pod dimension + ICI reductions).

Distributed design (the CP/ring-attention slot of this build, SURVEY.md §5
"long-context"): the 50k-pod axis is the long sequence. Strategy:

1. **Shard pods, replicate the lattice.** Each device receives an equal
   slice of every group's pod count (`split_counts`) and runs the full
   grouped-FFD scan locally against the replicated type lattice — a
   blockwise-greedy pack with zero cross-device traffic during the scan.
   Groups whose pods must co-locate (hostname self-affinity) or join a
   seeded bin (positive affinity) stay whole on one shard.
2. **Reduce with ICI collectives.** Total cost / node counts / leftovers
   reduce with `psum`; the full per-shard bin tables return stacked on the
   device axis for the host-side tail-bin merge (solver/solve.py
   ``Solver.solve(..., mesh=...)`` dissolves under-filled tail bins and
   re-packs them in one small single-device refinement solve — the ≤2%
   envelope guard, SURVEY.md §7 hard part a).
3. **Multi-host**: the same program over a DCN-spanning mesh; XLA routes the
   psum hierarchically (ICI within host, DCN across) — nothing to change in
   the program.

Every tensor's placement is an explicit PartitionSpec (the
``match_partition_rules``/``make_shard_and_gather_fns`` pattern from the
exemplar repos, collapsed to this solver's handful of tensors —
:data:`PARTITION_SPECS` is the single table both the in_specs and the
out_specs derive from): the 759-type lattice replicates like model
weights, the [D,G] pod-count split shards on the 'pods' axis, existing
bins replicate but materialize on shard 0 only (replicating real
capacity would fill the same physical nodes D times), and the fused
per-shard decode buffers come back stacked on the device axis so the
host pays ONE device→host transfer for all shards.

Since PR 12 the compiled program is cached per (mesh, static dims)
(:func:`_compiled_pack`): the production path re-solves every
provisioning pass, and rebuilding the shard_map closure per call would
re-trace — and re-compile — the whole program each time. The lattice
tensors and the fused input buffers arrive as ARGUMENTS (not closure
constants), so the Solver can keep them device-resident across passes
(solver/pipeline.py ResidentInputCache) and ship only dirty blocks.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import binpack

if hasattr(jax, "shard_map"):          # jax >= 0.6: top-level, check_vma kwarg
    def _shard_map(f, *, mesh, in_specs, out_specs):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
else:                                  # jax 0.4/0.5: experimental, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map(f, *, mesh, in_specs, out_specs):
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)


_REPL = P()

# The partition-spec table — every tensor the sharded program touches,
# named once (docs/reference/sharding.md renders this table verbatim).
# Inputs: the lattice trio replicates (resident "weights"), the fused
# group+pool and existing-bin buffers replicate (shard 0 alone
# materializes the existing table — see _local_pack), the pod-count
# split shards its leading device axis. Outputs: the fused decode
# buffers stack per-shard on 'pods'; the psum'd aggregates replicate.
PARTITION_SPECS = {
    "alloc": _REPL,           # [T,R]   lattice allocatable
    "avail": _REPL,           # [T,Z,C] lattice availability (ICE-masked)
    "price": _REPL,           # [T,Z,C] lattice prices
    "gbuf": _REPL,            # fused group+pool upload (u8)
    "count_split": P("pods"),  # [D,G] per-shard pod counts
    "init_buf": _REPL,        # fused existing-bin upload (u8)
    "n_existing": _REPL,      # scalar; zeroed off shard 0 in-program
    "packed": P("pods"),      # [D, B+n_trailer, W] per-shard decode buffers
    "total_cost": _REPL,      # psum over shards
    "total_nodes": _REPL,
    "total_leftover": _REPL,
}

_IN_SPECS = tuple(PARTITION_SPECS[k] for k in (
    "alloc", "avail", "price", "gbuf", "count_split", "init_buf",
    "n_existing"))
_OUT_SPECS = tuple(PARTITION_SPECS[k] for k in (
    "packed", "total_cost", "total_nodes", "total_leftover"))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """The replicated placement over ``mesh`` — what the resident input
    cache pins its device buffers with so a steady-state delta pass
    never re-replicates an unchanged buffer across the mesh."""
    return NamedSharding(mesh, P())


def split_counts(count: np.ndarray, n_devices: int,
                 keep_whole: Optional[np.ndarray] = None,
                 pin_shard0: Optional[np.ndarray] = None) -> np.ndarray:
    """[G] pod counts -> [D,G] balanced split (device d gets ~count/D).

    Groups flagged in ``keep_whole`` (co-location groups) are not split:
    each lands entirely on one shard, round-robin. Groups flagged in
    ``pin_shard0`` (presence-requiring ``need`` groups) go whole to shard 0,
    the only shard holding existing bins and their bound-pod affinity
    seeding (e_pm/e_po) — elsewhere their needs could never be met.
    """
    base = count // n_devices
    extra = count % n_devices
    out = np.tile(base, (n_devices, 1))
    for d in range(n_devices):
        out[d] += (d < extra).astype(count.dtype)
    if keep_whole is not None and keep_whole.any():
        whole = keep_whole.copy()
        if pin_shard0 is not None:
            whole &= ~pin_shard0
        whole = np.nonzero(whole)[0]
        for i, g in enumerate(whole):
            out[:, g] = 0
            out[i % n_devices, g] = count[g]
    if pin_shard0 is not None and pin_shard0.any():
        for g in np.nonzero(pin_shard0)[0]:
            out[:, g] = 0
            out[0, g] = count[g]
    return out


@partial(jax.jit, static_argnames=("D", "offset", "G"))
def device_split_counts(gbuf: jnp.ndarray, D: int, offset: int,
                        G: int) -> jnp.ndarray:
    """Balanced [D,G] pod-count split derived ON DEVICE from the fused
    group buffer's resident ``count`` field (``offset`` from
    ops/binpack.group_layout). The device-resident microloop uses this
    instead of shipping a host-built count_split every pass — the count
    bytes already crossed the link inside the dirty-block delta, so
    re-uploading their split is a pure extra leg. Bit-identical to
    ``split_counts`` with no keep_whole/pin flags (the microloop aborts
    to the standard path when co-location or shard-0 pinning is in
    play); device d gets count // D plus one of the count % D
    remainders."""
    count = jax.lax.bitcast_convert_type(
        gbuf[offset: offset + 4 * G].reshape(G, 4), jnp.int32).reshape(G)
    base = count // D
    extra = count % D
    d = jnp.arange(D, dtype=jnp.int32)[:, None]
    return base[None, :] + (d < extra[None, :]).astype(jnp.int32)


def shard_groups(count_split: np.ndarray) -> np.ndarray:
    """Per-shard pod load [D] of a split — balanced splitting plus the
    round-robin whole-group assignment and the shard-0 pinning all land
    here. max/mean of this vector is the
    ``karpenter_solver_shard_imbalance_ratio`` gauge: 1.0 is a
    perfectly balanced mesh; a pinned-heavy workload (everything
    co-located or need-seeded) shows up as shard 0 carrying the wave."""
    return count_split.sum(axis=1)


class ShardedPack(NamedTuple):
    """Per-shard pack results + ICI-reduced global aggregates.

    ``packed`` stacks each shard's fused decode buffer (ops/binpack.py
    ``_encode_decode_set``) along a leading device axis ([D, B+n, W] u8) —
    the host fetches ONE array for all shards (the host↔device link charges
    ~fixed latency per transfer) and decodes each shard's bin table exactly
    like a single-device result before merging tail bins.
    """

    packed: jnp.ndarray          # [D, B+n_trailer, W] u8
    total_cost: jnp.ndarray      # psum over shards: $/hr of live new bins
    total_nodes: jnp.ndarray     # psum over shards: live new-bin count
    total_leftover: jnp.ndarray  # psum over shards: pods no bin could take


def _local_pack(dims, alloc, avail, price, gbuf, count_shard, init_buf,
                n_existing):
    """Runs on each device over its pod-count shard; reduces over 'pods'.

    Inputs arrive as the same fused uint8 buffers the single-device solve
    ships (ops/binpack.group_layout / init_layout): one replicated upload
    for groups+pools, one for existing bins — on a real multi-chip slice
    the host link charges per transfer exactly like the single-chip
    tunnel. Existing capacity lives on shard 0 only (replicating it would
    fill the same physical nodes D times): every shard unpacks the same
    init buffer with n_existing masked to zero off shard 0."""
    B, G, T, Z, C, NP, A, R = dims
    count_local = count_shard.reshape(count_shard.shape[-1])  # [1,G] -> [G]
    groups, pools = binpack._unpack_inputs(gbuf, G, T, Z, C, NP, A, R)
    groups = groups._replace(count=count_local)
    d = jax.lax.axis_index("pods")
    n_e = jnp.where(d == 0, n_existing.astype(jnp.int32), 0)
    init = binpack._unpack_init(init_buf, n_e, B, T, Z, C, A, R)
    res = binpack.pack(alloc, avail, price, groups, pools, init)
    live = res.state.open & ~res.state.fixed & (res.state.npods > 0)
    local_cost = jnp.sum(jnp.where(live, res.chosen_price, 0.0))
    local_nodes = jnp.sum(live.astype(jnp.int32))
    local_leftover = jnp.sum(res.leftover)
    # ICI reductions: global cost / node count / leftover
    total_cost = jax.lax.psum(local_cost, "pods")
    total_nodes = jax.lax.psum(local_nodes, "pods")
    total_leftover = jax.lax.psum(local_leftover, "pods")
    # fused per-shard decode buffer; the P('pods') out-spec stacks them
    return (binpack._encode_decode_set(res)[None],
            total_cost, total_nodes, total_leftover)


@lru_cache(maxsize=64)
def _compiled_pack(mesh: Mesh, B: int, G: int, T: int, Z: int, C: int,
                   NP: int, A: int, R: int):
    """ONE jitted shard_map program per (mesh, static dims) — the
    production path re-solves every pass, so the compiled executable
    must be reused, not re-traced per call (Mesh hashes by device set +
    axis names, so equal meshes built in different places share the
    entry). Bounded by the bucket ladder: G/B bucket combinations are
    finite by construction."""
    dims = (B, G, T, Z, C, NP, A, R)

    def fn(alloc, avail, price, gbuf, count_shard, init_buf, n_existing):
        return _local_pack(dims, alloc, avail, price, gbuf, count_shard,
                           init_buf, n_existing)

    return jax.jit(_shard_map(fn, mesh=mesh, in_specs=_IN_SPECS,
                              out_specs=_OUT_SPECS))


def sharded_pack(mesh: Mesh, alloc, avail, price, gbuf, init_buf,
                 n_existing: int, count_split: np.ndarray,
                 B: int, G: int, T: int, Z: int, C: int, NP: int,
                 A: int) -> ShardedPack:
    """Run the pod-sharded solve over ``mesh`` (compiled once per shape).

    ``gbuf``/``init_buf`` are the fused group+pool / existing-bin uploads
    (solver/solve.py _fused_inputs / _fused_init_np; init_buf None = no
    existing capacity) — host arrays or already-device-resident buffers
    (the delta path hands in ResidentInputCache entries pinned with
    :func:`replicated_sharding`, so an unchanged buffer never re-crosses
    the link); ``count_split`` is [D,G] from split_counts. The lattice
    and the fused buffers are replicated (the lattice is the 'weights'
    of this model — resident on every device, exactly the TP-style
    layout that avoids re-sharding the lattice per step); the bin table
    is per-shard, with existing capacity materialized on shard 0 only
    (see _local_pack).
    """
    if init_buf is None:
        _, i_total = binpack.init_layout(B, alloc.shape[1], A)
        init_buf = jnp.zeros((i_total,), jnp.uint8)
        n_existing = 0
    fn = _compiled_pack(mesh, B, G, T, Z, C, NP, A, alloc.shape[1])
    return ShardedPack(*fn(
        jnp.asarray(alloc), jnp.asarray(avail), jnp.asarray(price),
        jnp.asarray(gbuf), jnp.asarray(count_split), jnp.asarray(init_buf),
        jnp.asarray(n_existing, jnp.int32)))
