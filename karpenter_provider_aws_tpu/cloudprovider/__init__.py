from .cloudprovider import CloudProvider, InstanceType, nodeclass_hash

__all__ = ["CloudProvider", "InstanceType", "nodeclass_hash"]
