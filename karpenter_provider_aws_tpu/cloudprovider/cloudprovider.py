"""The CloudProvider plugin boundary.

Mirror of the reference's six-method seam between the core scheduler and
the cloud (reference pkg/cloudprovider/cloudprovider.go:56-212): Create,
Delete, Get, List, GetInstanceTypes, IsDrifted (+ LivenessProbe). This is
the boundary the TPU solver hides behind — the provisioner's NodePlan
becomes NodeClaims, and each claim's launch resolves here.

Launch semantics mirror the reference instance provider
(pkg/providers/instance/instance.go):
- capacity type = spot iff the claim allows spot and a spot offering
  exists (instance.go:356-372),
- spot overrides pricier than the cheapest on-demand are dropped
  (instance.go:413-437),
- metal/GPU/accelerator types are dropped when a generic type also fits
  and the claim doesn't ask for them (instance.go:439-463),
- overrides are the (type x zone) cross-product sorted by price, capped at
  60 types; the fleet picks the cheapest available pool,
- insufficient-capacity errors feed the UnavailableOfferings cache
  (instance.go:348-354) before propagating,
- launches coalesce through the request batcher (35 ms idle window,
  reference batcher/createfleet.go:70-72).
"""

from __future__ import annotations

import hashlib
import json
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..apis import wellknown as wk
from ..apis.objects import (WINDOWS_BUILD, NodeClaim, NodeClaimPhase,
                            NodeClass, NodePool)
from ..apis.requirements import Requirements
from ..apis.resources import vec_to_resources
from ..batcher import Batcher, BatcherOptions
from ..cache.unavailable import UnavailableOfferings
from ..cloud.fake import CloudInstance, FakeCloud, LaunchOverride, parse_instance_id
from ..errors import NotFoundError, UnfulfillableCapacityError
from ..events import Recorder
from ..lattice.tensors import Lattice
from ..ops.masks import compile_masks
from ..utils.clock import Clock

MAX_INSTANCE_TYPES = 60            # instance.go:50
FLEXIBILITY_THRESHOLD = 5          # instance.go:52 (OD-fallback warning)


# bump when the hash FORMULA changes (fields added/removed), so pre-upgrade
# claims are re-stamped instead of mass-drifting the fleet (same mechanism as
# provisioning.NODEPOOL_HASH_VERSION; reference karpenter.k8s.aws/
# ec2nodeclass-hash-version migration). v2: + instance_store_policy
NODECLASS_HASH_VERSION = "v2"


def nodeclass_hash(nc: NodeClass) -> str:
    """Static spec hash for drift detection (reference
    pkg/apis/v1beta1/ec2nodeclass.go:338-344 Hash + drift.go:137-151)."""
    payload = json.dumps({
        "ami_family": nc.ami_family, "user_data": nc.user_data, "role": nc.role,
        "instance_profile": nc.instance_profile, "tags": sorted(nc.tags.items()),
        "metadata_options": vars(nc.metadata_options),
        "block_device_mappings": nc.block_device_mappings,
        "instance_store_policy": nc.instance_store_policy,
        "detailed_monitoring": nc.detailed_monitoring,
        "associate_public_ip": nc.associate_public_ip,
    }, sort_keys=True, default=str)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


@dataclass
class OfferingView:
    zone: str
    capacity_type: str
    price: float
    available: bool


@dataclass
class InstanceType:
    """Per-type view the scheduler-facing API returns (reference
    pkg/providers/instancetype/types.go:56-66 {Name, Requirements,
    Offerings, Capacity, Overhead})."""

    name: str
    labels: Dict[str, str]
    capacity: Dict[str, float]
    allocatable: Dict[str, float]
    offerings: List[OfferingView] = field(default_factory=list)


class CloudProvider:
    """The plugin seam; backed by the pluggable cloud (FakeCloud by default)."""

    name = "tpu-sim"

    def __init__(self, lattice: Lattice, cloud: FakeCloud,
                 unavailable: UnavailableOfferings,
                 recorder: Optional[Recorder] = None,
                 clock: Optional[Clock] = None,
                 node_classes: Optional[Dict[str, NodeClass]] = None,
                 batch_options: Optional[BatcherOptions] = None,
                 subnets=None, launch_templates=None, version=None):
        self.lattice = lattice
        self.cloud = cloud
        self.unavailable = unavailable
        self.recorder = recorder or Recorder(clock)
        self.clock = clock or Clock()
        self.node_classes: Dict[str, NodeClass] = node_classes or {
            "default": NodeClass(name="default", role="KarpenterNodeRole-sim")}
        # optional domain providers (reference pkg/providers/*); absent in
        # bare-solver setups, wired by the operator
        self.subnets = subnets
        self.launch_templates = launch_templates
        self.version = version
        self._launch_batcher: Batcher = Batcher(
            self._launch_batch,
            batch_options or BatcherOptions(idle_seconds=0.005),
            clock=self.clock)
        self._terminate_batcher: Batcher = Batcher(
            self._terminate_batch,
            batch_options or BatcherOptions(idle_seconds=0.005),
            clock=self.clock)
        self._lock = threading.Lock()

    # ---- Create ----------------------------------------------------------

    def create(self, claim: NodeClaim) -> NodeClaim:
        """Launch capacity satisfying the claim's requirements
        (cloudprovider.go:80-109 → instance.go:84-244): resolve the
        NodeClass, ensure launch templates, cross overrides with zonal
        subnets, launch, book in-flight IPs."""
        nc = self.node_classes.get(claim.node_class_ref)
        lts_by_arch = {}
        if self.launch_templates is not None and nc is not None:
            k8s_version = self.version.get() if self.version is not None else "1.29"
            # kubelet cluster-DNS: the pool's kubelet block wins; else the
            # kube-dns service IP discovered best-effort at startup
            # (reference operator.go:125-132; ipv6 suite exercises both)
            dns = claim.cluster_dns or self.cloud.network.kube_dns_ip
            for lt in self.launch_templates.ensure_all(nc, k8s_version,
                                                       cluster_dns=dns):
                img = self.cloud.network.images.get(lt.image_id)
                if img is not None:
                    lts_by_arch[img.arch] = lt
        zonal_subnets = None
        if self.subnets is not None and nc is not None:
            zonal_subnets = self.subnets.zonal_subnets_for_launch(nc)
        overrides = self._resolve_overrides(claim)
        if zonal_subnets is not None:
            # zones with no resolvable subnet cannot host a launch
            # (instance.go:306-346 overrides x zonal subnets cross-product)
            overrides = [o for o in overrides if o.zone in zonal_subnets]
        if not overrides:
            raise UnfulfillableCapacityError(offerings=[])
        if (overrides[0].capacity_type == wk.CAPACITY_TYPE_SPOT
                and len({o.instance_type for o in overrides}) < FLEXIBILITY_THRESHOLD):
            self.recorder.publish(
                "Warning", "SpotFlexibilityLow", "NodeClaim", claim.name,
                f"launching spot with {len({o.instance_type for o in overrides})} instance "
                f"types; >= {FLEXIBILITY_THRESHOLD} recommended for reliable fallback")
        try:
            fleet = self._launch_batcher.add(tuple(overrides))
        except UnfulfillableCapacityError as e:
            self.unavailable.mark_unavailable_for_error(e)
            self.recorder.publish("Warning", "InsufficientCapacity", "NodeClaim",
                                  claim.name, str(e))
            raise
        instance = fleet.instance
        # a successful fleet still reports the exhausted offerings its
        # lowest-price walk skipped; cache them so the next solve masks
        # them out (reference instance.go:348-354)
        for ct, it, zone in fleet.ice:
            self.unavailable.mark_unavailable("fleet-error", ct, it, zone)
        if zonal_subnets is not None and instance.zone in zonal_subnets:
            subnet = zonal_subnets[instance.zone]
            self.subnets.update_inflight_ips(subnet.id)
            instance.tags["subnet-id"] = subnet.id
            instance.subnet_id = subnet.id
        arch = self.lattice.labels[self.lattice.name_to_idx[instance.instance_type]].get(
            wk.LABEL_ARCH, "amd64")
        lt = lts_by_arch.get(arch)
        if lt is not None:
            instance.tags["launch-template"] = lt.name
            instance.image_id = lt.image_id
            instance.security_group_ids = tuple(lt.security_group_ids)
            claim.image_id = lt.image_id
        return self._instance_to_claim(instance, claim)

    def _launch_batch(self, batch: List[Tuple[LaunchOverride, ...]]) -> List[object]:
        """Coalesced launch: one locked pass over the fake fleet API
        (reference coalesces N single-instance requests into one CreateFleet
        with capacity N and splits results back, createfleet.go:67-130)."""
        out: List[object] = []
        for overrides in batch:
            try:
                out.append(self.cloud.create_fleet(list(overrides)))
            except BaseException as e:
                out.append(e)
        return out

    def _resolve_overrides(self, claim: NodeClaim) -> List[LaunchOverride]:
        lat = self.lattice
        reqs = claim.scheduling_requirements()
        masks = compile_masks(reqs, lat, extra_labels=claim.labels)
        offer = (lat.available
                 & masks.type_mask[:, None, None]
                 & masks.zone_mask[None, :, None]
                 & masks.cap_mask[None, None, :]
                 & self.unavailable.mask(lat))
        if not offer.any():
            return []
        # capacity type: spot iff allowed and offered (instance.go:356-372)
        spot_ci = lat.capacity_types.index(wk.CAPACITY_TYPE_SPOT) if wk.CAPACITY_TYPE_SPOT in lat.capacity_types else -1
        od_ci = lat.capacity_types.index(wk.CAPACITY_TYPE_ON_DEMAND) if wk.CAPACITY_TYPE_ON_DEMAND in lat.capacity_types else -1
        use_spot = spot_ci >= 0 and offer[:, :, spot_ci].any()
        ci = spot_ci if use_spot else od_ci
        if ci < 0:
            return []
        # price filter: spot overrides pricier than the cheapest on-demand
        # offering are never worth launching (instance.go:413-437)
        price_cap = np.inf
        if use_spot and od_ci >= 0 and offer[:, :, od_ci].any():
            price_cap = float(np.where(offer[:, :, od_ci], lat.price[:, :, od_ci], np.inf).min())
        # exotic-type filter (instance.go:439-463): drop metal/gpu/accelerator
        # types when a generic type fits and the claim doesn't require them,
        # unless minValues forbids narrowing (instance.go:86-89)
        tmask = offer[:, :, ci].any(axis=1)
        has_min_values = any(r.min_values is not None for r in reqs.requirements)
        if not has_min_values:
            wants_gpu = any(claim.resource_requests.get(r, 0) > 0
                            for r in ("nvidia.com/gpu", "aws.amazon.com/neuron"))
            generic = np.array([
                lat.specs[t].gpu_count == 0 and lat.specs[t].accelerator_count == 0
                and lat.specs[t].size != "metal"
                for t in range(lat.T)])
            if not wants_gpu and (tmask & generic).any():
                tmask = tmask & generic
        overrides: List[LaunchOverride] = []
        for t in np.nonzero(tmask)[0]:
            for z in np.nonzero(offer[t, :, ci])[0]:
                p = float(lat.price[t, z, ci])
                if p > price_cap:
                    continue
                overrides.append(LaunchOverride(
                    instance_type=lat.names[t], zone=lat.zones[z],
                    capacity_type=lat.capacity_types[ci], price=p))
        overrides.sort(key=lambda o: o.price)
        # cap the *type* flexibility at 60 like CreateFleet (instance.go:50)
        seen_types: Dict[str, None] = {}
        capped: List[LaunchOverride] = []
        for o in overrides:
            if o.instance_type not in seen_types and len(seen_types) >= MAX_INSTANCE_TYPES:
                continue
            seen_types.setdefault(o.instance_type, None)
            capped.append(o)
        return capped

    def _instance_to_claim(self, instance: CloudInstance, claim: NodeClaim) -> NodeClaim:
        """instance → NodeClaim status (cloudprovider.go:282-325)."""
        lat = self.lattice
        ti = lat.name_to_idx[instance.instance_type]
        claim.provider_id = instance.provider_id
        claim.internal_ip = instance.private_ip
        claim.instance_type = instance.instance_type
        claim.zone = instance.zone
        claim.capacity_type = instance.capacity_type
        claim.capacity = vec_to_resources(lat.capacity[ti])
        claim.allocatable = vec_to_resources(lat.alloc[ti])
        if claim.max_pods is not None:
            # the pool's kubelet maxPods caps pod density below the
            # ENI-derived number — applied HERE so the claim never exists
            # in a LAUNCHED state with the unclamped value visible
            for res in (claim.capacity, claim.allocatable):
                if "pods" in res:
                    res["pods"] = min(res["pods"], float(claim.max_pods))
        claim.labels = {
            **lat.labels[ti],
            **claim.labels,
            wk.LABEL_INSTANCE_TYPE: instance.instance_type,
            wk.LABEL_ZONE: instance.zone,
            wk.LABEL_CAPACITY_TYPE: instance.capacity_type,
            wk.LABEL_NODEPOOL: claim.node_pool,
        }
        if claim.labels.get(wk.LABEL_OS) == "windows":
            # every windows node carries the AMI's build (well-known
            # node.kubernetes.io/windows-build, reference labels.go
            # v1.LabelWindowsBuild) — keyed on the claim's resolved OS so
            # the stamp can never diverge from what the solver advertised
            claim.labels.setdefault(wk.LABEL_WINDOWS_BUILD, WINDOWS_BUILD)
        nc = self.node_classes.get(claim.node_class_ref)
        if nc is not None:
            claim.annotations[wk.ANNOTATION_NODECLASS_HASH] = nodeclass_hash(nc)
            claim.annotations[wk.ANNOTATION_NODECLASS_HASH_VERSION] = \
                NODECLASS_HASH_VERSION
        claim.phase = NodeClaimPhase.LAUNCHED
        claim.launched_at = self.clock.now()
        return claim

    # ---- Delete / Get / List --------------------------------------------

    def delete(self, claim: NodeClaim) -> None:
        if claim.provider_id is None:
            raise NotFoundError(f"claim {claim.name} has no provider id")
        iid = parse_instance_id(claim.provider_id)
        self._terminate_batcher.add(iid)

    def _terminate_batch(self, ids: List[str]) -> List[object]:
        """Coalesced terminate (reference batcher/terminateinstances.go)."""
        results: List[object] = []
        known = {i.id for i in self.cloud.list_instances(include_terminated=True)}
        present = [i for i in ids if i in known]
        if present:
            self.cloud.terminate_instances(present)
        for i in ids:
            results.append(None if i in known else NotFoundError(f"instance not found: {i}"))
        return results

    def get(self, provider_id: str) -> CloudInstance:
        iid = parse_instance_id(provider_id)
        found = self.cloud.describe_instances([iid])
        if not found or found[0].state == "terminated":
            raise NotFoundError(f"instance not found: {iid}")
        return found[0]

    def list_instances(self) -> List[CloudInstance]:
        return self.cloud.list_instances()

    # ---- GetInstanceTypes ------------------------------------------------

    def get_instance_types(self, pool: NodePool) -> List[InstanceType]:
        """The scheduler's lattice feed (cloudprovider.go:149-169), with
        per-offering availability reflecting the ICE cache."""
        lat = self.lattice
        reqs = pool.scheduling_requirements()
        masks = compile_masks(reqs, lat, extra_labels=pool.labels)
        ice = self.unavailable.mask(lat)
        out: List[InstanceType] = []
        for t in np.nonzero(masks.type_mask)[0]:
            offerings = []
            for z in range(lat.Z):
                for c in range(lat.C):
                    if not lat.available[t, z, c]:
                        continue
                    offerings.append(OfferingView(
                        zone=lat.zones[z], capacity_type=lat.capacity_types[c],
                        price=float(lat.price[t, z, c]),
                        available=bool(ice[t, z, c] and masks.zone_mask[z] and masks.cap_mask[c])))
            out.append(InstanceType(
                name=lat.names[t], labels=dict(lat.labels[t]),
                capacity=vec_to_resources(lat.capacity[t]),
                allocatable=vec_to_resources(lat.alloc[t]),
                offerings=offerings))
        return out

    # ---- IsDrifted -------------------------------------------------------

    def is_drifted(self, claim: NodeClaim) -> Optional[str]:
        """Drift reasons (reference pkg/cloudprovider/drift.go:44-151):
        NodeClassDrift on static-hash mismatch (checked first to save the
        live lookups), InstanceDrift when the backing instance disappeared,
        then live AMI/subnet/SG comparison of the instance's actual launch
        materialization against the NodeClass's currently-resolved status
        (drift.go:73-135). Each live check is skipped when either side is
        unknown — the reference treats undiscovered state as an error, not
        as drift."""
        nc = self.node_classes.get(claim.node_class_ref)
        if nc is not None:
            have = claim.annotations.get(wk.ANNOTATION_NODECLASS_HASH)
            have_ver = claim.annotations.get(
                wk.ANNOTATION_NODECLASS_HASH_VERSION)
            if have is not None and have_ver != NODECLASS_HASH_VERSION:
                # the hash formula changed between controller versions:
                # re-stamp under the new formula instead of treating the
                # formula change as drift (it would roll the whole fleet)
                claim.annotations[wk.ANNOTATION_NODECLASS_HASH] = \
                    nodeclass_hash(nc)
                claim.annotations[wk.ANNOTATION_NODECLASS_HASH_VERSION] = \
                    NODECLASS_HASH_VERSION
            elif have is not None and have != nodeclass_hash(nc):
                return "NodeClassDrift"
        if claim.provider_id is not None:
            try:
                inst = self.get(claim.provider_id)
            except NotFoundError:
                return "InstanceDrift"
            if nc is not None:
                if inst.image_id and nc.status_amis:
                    # AMIs map to instance types by arch (drift.go:91-96):
                    # an amd64 node must not drift because the arm64
                    # default AMI rolled
                    arch = self.lattice.labels[
                        self.lattice.name_to_idx[inst.instance_type]].get(
                        wk.LABEL_ARCH, "amd64")
                    allowed = {a["id"] for a in nc.status_amis
                               if a.get("arch") in (None, arch)}
                    if allowed and inst.image_id not in allowed:
                        return "AMIDrift"
                if inst.subnet_id and nc.status_subnets:
                    if inst.subnet_id not in {s["id"] for s in nc.status_subnets}:
                        return "SubnetDrift"
                if inst.security_group_ids and nc.status_security_groups:
                    if (set(inst.security_group_ids)
                            != {g["id"] for g in nc.status_security_groups}):
                        return "SecurityGroupDrift"
        return None

    def liveness_probe(self) -> bool:
        try:
            self.cloud.list_instances()
            return True
        except Exception:
            return False
