"""CloudProvider metrics decoration.

Mirror of the reference's `metrics.Decorate(cloudProvider)`
(reference cmd/controller/main.go:44): every CloudProvider method call is
wrapped with a duration histogram and an error counter
(karpenter_cloudprovider_duration_seconds /
karpenter_cloudprovider_errors_total, website reference/metrics.md:175).
Non-decorated attributes proxy through, so the decorated provider is a
drop-in at the plugin seam.
"""

from __future__ import annotations

import time
from typing import Optional

from ..metrics import Registry

_DECORATED = ("create", "delete", "get", "list_instances", "get_instance_types",
              "is_drifted")


class MetricsDecoratedCloudProvider:
    def __init__(self, inner, registry: Registry, controller: str = "operator"):
        self._inner = inner
        self._controller = controller
        self._duration = registry.histogram(
            "karpenter_cloudprovider_duration_seconds",
            "Duration of cloud provider method calls.", ("controller", "method"))
        self._errors = registry.counter(
            "karpenter_cloudprovider_errors_total",
            "Total number of errors returned from CloudProvider calls.",
            ("controller", "method", "error"))
        for name in _DECORATED:
            setattr(self, name, self._wrap(name))

    def _wrap(self, name: str):
        fn = getattr(self._inner, name)
        duration, errors, controller = self._duration, self._errors, self._controller

        def wrapped(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            except BaseException as e:
                errors.inc(controller=controller, method=name, error=type(e).__name__)
                raise
            finally:
                duration.observe(time.perf_counter() - t0,
                                 controller=controller, method=name)
        wrapped.__name__ = name
        return wrapped

    def __getattr__(self, item):
        return getattr(self._inner, item)


def decorate(cloud_provider, registry: Optional[Registry],
             controller: str = "operator"):
    if registry is None:
        return cloud_provider
    return MetricsDecoratedCloudProvider(cloud_provider, registry, controller)
