"""On-demand build + load of the native (C++) components.

The reference ships no native code (100% Go); this framework keeps its
host-side hot paths native where Python would bottleneck the benchmarks
(SURVEY.md §2: the runtime around the device compute path). No pybind11 in
the image, so the ABI is plain extern "C" + ctypes. The shared object is
compiled once per checkout with g++ and cached next to the source.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_SRC = _REPO_ROOT / "native" / "ffd.cc"
_LIB = _REPO_ROOT / "native" / "libffd.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def ensure_built() -> Optional[ctypes.CDLL]:
    """Compile (if stale) and load the native library; None if no toolchain."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        try:
            if (not _LIB.exists()
                    or _LIB.stat().st_mtime < _SRC.stat().st_mtime):
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                     "-o", str(_LIB), str(_SRC)],
                    check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(str(_LIB))
        except (OSError, subprocess.SubprocessError):
            _build_failed = True
            return None
        lib.ffd_pack.restype = ctypes.c_int
        _lib = lib
        return _lib


def native_available() -> bool:
    return ensure_built() is not None
