from .build import ensure_built, native_available
from .oracle import native_ffd_pack

__all__ = ["ensure_built", "native_available", "native_ffd_pack"]
