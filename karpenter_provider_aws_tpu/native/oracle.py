"""ctypes wrapper for the native FFD referee (native/ffd.cc).

Same per-pod sequential semantics as solver/oracle.py (the reference's Go
scheduler loop) over the full feature surface — new-node packing,
existing bins with bound-pod seeds, per-pool allocatable ceilings, and
hostname affinity classes; only strict custom keys over unknown-pool
nodes stay Python-side. Runs the 50k-pod x 700-type benchmark configs in
about a second, so full-scale cost parity (BASELINE.md <=2% envelope) is
asserted against the native referee on every bench run for all five
configs, not only on small regression fixtures.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import ctypes
import numpy as np

from ..solver.problem import Problem
from .build import ensure_built


@dataclass
class NativeOraclePlan:
    num_new_nodes: int
    new_node_cost: float
    leftover: int
    chosen: List[Tuple[int, int, int]]   # (type, zone, captype) per NEW bin
    e_npods: Optional[np.ndarray] = None  # [E] pods ADDED per existing bin


def _c(a: np.ndarray, dtype):
    a = np.ascontiguousarray(a, dtype=dtype)
    return a, a.ctypes.data_as(ctypes.c_void_p)


def native_ffd_pack(problem: Problem, max_bins: int = 200_000) -> Optional[NativeOraclePlan]:
    """Run the native referee; None if the toolchain/library is
    unavailable or the problem uses strict custom keys over unknown-pool
    nodes (the one remaining Python-only scope) — callers fall back to
    the Python oracle. Existing (fixed) bins, per-pool allocatable
    ceilings, and hostname affinity classes (pm/po symmetry, presence
    needs, spread-class caps, single-bin co-location, bound-pod seeds)
    are all in native scope."""
    lib = ensure_built()
    if lib is None:
        return None
    if problem.strict_custom.any() and problem.E > 0 \
            and (problem.e_np < 0).any():
        # unknown-pool existing bins cannot be verified against custom-key
        # selectors; the Python oracle holds that logic. With no
        # unknown-pool bins the strictness resolves entirely through the
        # np masks, which are native scope.
        return None
    lat = problem.lattice
    G = problem.G
    from ..apis.resources import R

    holders = []

    def arr(a, dtype):
        h, p = _c(a, dtype)
        holders.append(h)
        return p

    out_cost = ctypes.c_float(0.0)
    out_leftover = ctypes.c_int64(0)
    chosen_t = np.zeros((max_bins,), np.int32)
    chosen_z = np.zeros((max_bins,), np.int32)
    chosen_c = np.zeros((max_bins,), np.int32)
    E = problem.E
    e_npods = np.zeros((max(E, 1),), np.int32)

    A = problem.A
    n = lib.ffd_pack(
        lat.T, lat.Z, lat.C, R, G, max(problem.NP, 1), E, A,
        arr(lat.alloc, np.float32),
        arr(lat.available, np.uint8),
        arr(np.nan_to_num(lat.price, posinf=3.4e38), np.float32),
        arr(problem.req, np.float32),
        arr(problem.count, np.int32),
        arr(problem.g_type, np.uint8),
        arr(problem.g_zone, np.uint8),
        arr(problem.g_cap, np.uint8),
        arr(problem.g_np, np.uint8),
        arr(problem.max_per_bin, np.int32),
        arr(problem.g_spread, np.int32),
        arr(problem.single_bin, np.uint8),
        arr(problem.g_match, np.uint8),
        arr(problem.g_owner, np.uint8),
        arr(problem.g_need, np.uint8),
        arr(problem.np_type, np.uint8),
        arr(problem.np_zone, np.uint8),
        arr(problem.np_cap, np.uint8),
        arr(problem.ds_overhead, np.float32),
        # +inf ceilings pass through as f32 max (no ceiling)
        arr(np.nan_to_num(problem.np_alloc_cap, posinf=3.4e38), np.float32),
        arr(problem.e_used, np.float32),
        arr(np.nan_to_num(problem.e_alloc, posinf=3.4e38), np.float32),
        arr(problem.e_type, np.int32),
        arr(problem.e_zone, np.int32),
        arr(problem.e_cap, np.int32),
        arr(problem.e_np, np.int32),
        arr(problem.e_pm, np.int32),
        arr(problem.e_po, np.uint8),
        ctypes.c_int(max_bins),
        ctypes.byref(out_cost),
        ctypes.byref(out_leftover),
        arr(chosen_t, np.int32),
        arr(chosen_z, np.int32),
        arr(chosen_c, np.int32),
        arr(e_npods, np.int32),
    )
    if n < 0:
        return None
    chosen = [(int(chosen_t[i]), int(chosen_z[i]), int(chosen_c[i]))
              for i in range(min(n, max_bins))]
    return NativeOraclePlan(num_new_nodes=n, new_node_cost=float(out_cost.value),
                            leftover=int(out_leftover.value), chosen=chosen,
                            e_npods=e_npods[:E] if E else None)
