"""Catalog → device tensors (the solver's constraint lattice).

The reference builds `[]cloudprovider.InstanceType` — per-type requirement
labels + capacity + overhead + offerings (reference
pkg/providers/instancetype/types.go:56-66,74-155). Here the same information
becomes the dense tensors the device solver consumes:

- ``alloc [T,R]``           allocatable vector per type (capacity - overhead)
- ``capacity [T,R]``        raw capacity
- ``price [T,Z,C]``         offering price (+inf where unavailable)
- ``available [T,Z,C]``     offering availability
- ``cat_ids [K_cat,T]``     categorical label value ids (vocab per key)
- ``num_vals [K_num,T]``    numeric label values (NaN = undefined)

plus host-side mirrors (label dicts per type) for the oracle and for
requirement evaluation outside jit.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..apis import wellknown as wk
from ..apis.resources import RESOURCE_AXES, R, axis
from . import catalog as cat
from .overhead import (KubeletConfiguration, allocatable, ebs_attach_limit,
                       max_pods, vm_usable_memory_mib)


def type_labels(spec: cat.InstanceTypeSpec) -> Dict[str, str]:
    """The ~20 requirement labels one instance type carries
    (types.go:74-155 computeRequirements)."""
    labels = {
        wk.LABEL_INSTANCE_TYPE: spec.name,
        wk.LABEL_ARCH: spec.arch,
        wk.LABEL_REGION: cat.REGION,
        wk.LABEL_INSTANCE_CATEGORY: spec.category,
        wk.LABEL_INSTANCE_FAMILY: spec.family,
        wk.LABEL_INSTANCE_GENERATION: str(spec.generation),
        wk.LABEL_INSTANCE_SIZE: spec.size,
        wk.LABEL_INSTANCE_CPU: str(spec.vcpus),
        wk.LABEL_INSTANCE_CPU_MANUFACTURER: spec.cpu_manufacturer,
        wk.LABEL_INSTANCE_MEMORY: str(spec.memory_mib),
        wk.LABEL_INSTANCE_NETWORK_BANDWIDTH: str(spec.network_bandwidth_mbps),
        wk.LABEL_INSTANCE_ENCRYPTION_IN_TRANSIT: "true" if spec.generation >= 5 else "false",
    }
    if spec.hypervisor:
        labels[wk.LABEL_INSTANCE_HYPERVISOR] = spec.hypervisor
    if spec.local_nvme_gb:
        labels[wk.LABEL_INSTANCE_LOCAL_NVME] = str(spec.local_nvme_gb)
    if spec.gpu_count:
        labels[wk.LABEL_INSTANCE_GPU_NAME] = spec.gpu_name
        labels[wk.LABEL_INSTANCE_GPU_MANUFACTURER] = spec.gpu_manufacturer
        labels[wk.LABEL_INSTANCE_GPU_COUNT] = str(spec.gpu_count)
        labels[wk.LABEL_INSTANCE_GPU_MEMORY] = str(spec.gpu_memory_mib)
    if spec.accelerator_count:
        labels[wk.LABEL_INSTANCE_ACCELERATOR_NAME] = spec.accelerator_name
        labels[wk.LABEL_INSTANCE_ACCELERATOR_MANUFACTURER] = spec.accelerator_manufacturer
        labels[wk.LABEL_INSTANCE_ACCELERATOR_COUNT] = str(spec.accelerator_count)
    return labels


DEFAULT_EBS_ROOT_MIB = 20 * 1024.0  # amifamily.DefaultEBS.VolumeSize (20Gi)


@dataclass(frozen=True)
class StorageConfig:
    """NodeClass storage knobs that shape per-type ephemeral capacity
    (reference types.go:210-240 ephemeralStorage). One lattice carries one
    storage config — the reference computes instance types per NodeClass;
    an operator serving NodeClasses with different storage configs builds
    a lattice per config."""

    instance_store_policy: Optional[str] = None   # None | "RAID0"
    block_device_mappings: Tuple[Mapping, ...] = ()
    ephemeral_block_device: Optional[str] = None  # AMI family's root device
    custom_ami_family: bool = False


def ephemeral_storage_mib(spec: cat.InstanceTypeSpec,
                          storage: Optional[StorageConfig] = None) -> float:
    """Node ephemeral-storage capacity, the reference's resolution order
    (types.go:210-240): RAID0 policy takes the combined local NVMe size;
    else a root-volume BDM's size; else (Custom AMI) the last BDM's size;
    else the BDM matching the family's ephemeral device; else 20Gi."""
    s = storage or StorageConfig()
    if s.instance_store_policy == "RAID0" and spec.local_nvme_gb:
        return spec.local_nvme_gb * 1000.0 / 1.048576   # GB -> MiB
    bdms = s.block_device_mappings
    if bdms:
        for b in bdms:
            if b.get("root_volume") and b.get("volume_size_mib"):
                return float(b["volume_size_mib"])
        if s.custom_ami_family:
            last = bdms[-1]
            if last.get("volume_size_mib"):
                return float(last["volume_size_mib"])
        elif s.ephemeral_block_device:
            for b in bdms:
                if (b.get("device_name") == s.ephemeral_block_device
                        and b.get("volume_size_mib")):
                    return float(b["volume_size_mib"])
    return DEFAULT_EBS_ROOT_MIB


def capacity_vec(spec: cat.InstanceTypeSpec, kc: Optional[KubeletConfiguration] = None,
                 vm_memory_overhead_percent: float = 0.075, reserved_enis: int = 0,
                 storage: Optional[StorageConfig] = None) -> Tuple[np.ndarray, int]:
    """Capacity vector + pod density (types.go:176-208 computeCapacity)."""
    vec = np.zeros((R,), dtype=np.float32)
    pods = max_pods(spec.enis, spec.ipv4_per_eni, spec.vcpus, kc, reserved_enis=reserved_enis)
    vec[axis("cpu")] = spec.vcpus * 1000.0
    vec[axis("memory")] = vm_usable_memory_mib(spec.memory_mib, spec.arch, vm_memory_overhead_percent)
    vec[axis("pods")] = pods
    vec[axis("ephemeral-storage")] = ephemeral_storage_mib(spec, storage)
    # GPUs surface as per-manufacturer extended resources (reference
    # types.go:176-192: nvidia.com/gpu, amd.com/gpu, habana.ai/gaudi)
    gm = (spec.gpu_manufacturer or "").lower()
    vec[axis("nvidia.com/gpu")] = spec.gpu_count if gm in ("", "nvidia") else 0
    vec[axis("amd.com/gpu")] = spec.gpu_count if gm == "amd" else 0
    vec[axis("habana.ai/gaudi")] = spec.gpu_count if gm == "habana" else 0
    vec[axis("aws.amazon.com/neuron")] = (
        spec.accelerator_count
        if (spec.accelerator_name or "").lower()
        in ("inferentia", "inferentia2", "trainium") else 0)
    vec[axis("vpc.amazonaws.com/efa")] = spec.efa_count
    vec[axis("vpc.amazonaws.com/pod-eni")] = spec.pod_eni_count
    vec[axis("attachable-volumes")] = ebs_attach_limit(spec.hypervisor, spec.enis)
    return vec, pods


@dataclass
class Lattice:
    """The full constraint lattice, device-ready."""

    specs: List[cat.InstanceTypeSpec]
    names: List[str]
    labels: List[Dict[str, str]]           # host-side label dicts per type
    zones: Tuple[str, ...]
    capacity_types: Tuple[str, ...]
    capacity: np.ndarray                   # [T,R] float32
    alloc: np.ndarray                      # [T,R] float32
    price: np.ndarray                      # [T,Z,C] float32, +inf unavailable
    available: np.ndarray                  # [T,Z,C] bool
    cat_vocab: Dict[str, Dict[str, int]]   # key -> value -> id (id 0 = undefined)
    cat_ids: np.ndarray                    # [K_cat,T] int32
    num_vals: np.ndarray                   # [K_num,T] float32, NaN undefined
    name_to_idx: Dict[str, int] = field(default_factory=dict)
    # bumped whenever price is rewritten in place (pricing refresh) so
    # device-resident copies know to re-upload
    price_version: int = 0
    # the UNMASKED availability this view derives from (None on a base
    # lattice): masked_view records it so the explain engine
    # (solver/explain.py) can attribute eliminations to the ICE /
    # unavailable mask specifically — "was offered, currently held out"
    # vs "never offered at all"
    base_available: Optional[np.ndarray] = field(
        default=None, repr=False, compare=False)
    # key_values_present memo (labels are static per lattice); carried
    # through masked_view's replace() too, which is correct — masked
    # views share the same labels
    _kv_cache: Optional[Dict[str, List[str]]] = field(
        default=None, repr=False, compare=False)

    @property
    def T(self) -> int:
        return len(self.names)

    @property
    def Z(self) -> int:
        return len(self.zones)

    @property
    def C(self) -> int:
        return len(self.capacity_types)

    def key_values_present(self) -> Dict[str, List[str]]:
        """key -> distinct values across the lattice (for minValues
        checks). Labels are static per lattice, so the scan memoizes —
        build_problem calls this on every batch and the T-wide dict walk
        was a measurable slice of the 50k-pod host budget."""
        if self._kv_cache is not None:
            return self._kv_cache
        out: Dict[str, set] = {}
        for lab in self.labels:
            for k, v in lab.items():
                out.setdefault(k, set()).add(v)
        self._kv_cache = {k: sorted(v) for k, v in out.items()}
        return self._kv_cache


def masked_view(lattice: Lattice, offering_mask: np.ndarray) -> Lattice:
    """A shallow lattice copy with offerings masked out (ICE feedback: AND
    the UnavailableOfferings mask into availability before a solve). All
    other tensors are shared; shapes are unchanged so jitted kernels are
    reused."""
    from dataclasses import replace

    available = lattice.available & offering_mask
    price = np.where(available, lattice.price, np.inf).astype(np.float32)
    base = (lattice.base_available if lattice.base_available is not None
            else lattice.available)
    return replace(lattice, available=available, price=price,
                   base_available=base)


# masked_view memoized per BASE lattice on (price_version, ICE seq_num):
# a steady controller pass re-solves against an unchanged price table and
# ICE set, and minting a fresh view object every pass would defeat every
# identity-keyed memo downstream (the solver's narrowing cache,
# solver/problem.py _NARROW_CACHE). TTL-expired ICE entries re-enter the
# offering set at the operator's 10 s cleanup tick, which bumps seq_num
# (cache/unavailable.py cleanup; the reference frees offerings on the
# same cadence, cache.go:39-42) — so the memoized view is never staler
# than the reference's own cache. The memo slot is per (base, ICE cache)
# PAIR — seq numbers are only comparable within one UnavailableOfferings
# instance, and two operators may share one injected base lattice — and
# both objects are held strongly: a dead one's id can never alias a
# live key.
_VIEW_MEMO: Dict[tuple, tuple] = {}  # (id(base), id(ice)) -> (base, ice, key, view)
_VIEW_MEMO_MAX = 4


def masked_view_versioned(lattice: Lattice, unavailable) -> Lattice:
    """``masked_view(lattice, unavailable.mask(lattice))`` with the view
    object REUSED while ``(lattice.price_version, unavailable.seq_num)``
    is unchanged. ``unavailable`` is duck-typed (needs ``.mask(lattice)``
    and ``.seq_num``): cache/unavailable.py's UnavailableOfferings."""
    key = (lattice.price_version, unavailable.seq_num)
    slot = (id(lattice), id(unavailable))
    e = _VIEW_MEMO.get(slot)
    if (e is not None and e[0] is lattice and e[1] is unavailable
            and e[2] == key):
        return e[3]
    view = masked_view(lattice, unavailable.mask(lattice))
    if len(_VIEW_MEMO) >= _VIEW_MEMO_MAX:
        _VIEW_MEMO.clear()
    _VIEW_MEMO[slot] = (lattice, unavailable, key, view)
    return view


def build_lattice(specs: Optional[Sequence[cat.InstanceTypeSpec]] = None,
                  kc: Optional[KubeletConfiguration] = None,
                  zones: Sequence[str] = cat.ZONES,
                  capacity_types: Sequence[str] = cat.CAPACITY_TYPES,
                  vm_memory_overhead_percent: float = 0.075,
                  reserved_enis: int = 0,
                  storage: Optional[StorageConfig] = None) -> Lattice:
    specs = list(specs) if specs is not None else cat.build_catalog()
    T, Z, C = len(specs), len(zones), len(capacity_types)

    capacity = np.zeros((T, R), dtype=np.float32)
    alloc = np.zeros((T, R), dtype=np.float32)
    labels = []
    for i, s in enumerate(specs):
        vec, pods = capacity_vec(s, kc, vm_memory_overhead_percent, reserved_enis,
                                 storage)
        capacity[i] = vec
        alloc[i] = allocatable(vec, s.vcpus * 1000.0, pods,
                               vec[axis("memory")], vec[axis("ephemeral-storage")], kc)
        labels.append(type_labels(s))

    price = np.full((T, Z, C), np.inf, dtype=np.float32)
    available = np.zeros((T, Z, C), dtype=bool)
    for i, s in enumerate(specs):
        for zi, zone in enumerate(zones):
            for ci, ct in enumerate(capacity_types):
                if not cat.offering_available(s, zone, ct):
                    continue
                available[i, zi, ci] = True
                if ct == "on-demand":
                    price[i, zi, ci] = cat.od_price(s, zone)
                else:
                    # prefer the spec's data-carried per-AZ spot price
                    # (real-data catalogs); fall back to the synthetic
                    # discount model
                    sp = s.spot_price_in(zone)
                    price[i, zi, ci] = (sp if sp is not None
                                        else cat.spot_price(s, zone))

    # categorical vocab: id 0 reserved for "undefined on this type"
    cat_keys = wk.DEVICE_CATEGORICAL_KEYS
    cat_vocab: Dict[str, Dict[str, int]] = {k: {} for k in cat_keys}
    cat_ids = np.zeros((len(cat_keys), T), dtype=np.int32)
    for ki, key in enumerate(cat_keys):
        vocab = cat_vocab[key]
        for i, lab in enumerate(labels):
            v = lab.get(key)
            if v is None:
                continue
            if v not in vocab:
                vocab[v] = len(vocab) + 1
            cat_ids[ki, i] = vocab[v]

    num_keys = wk.DEVICE_NUMERIC_KEYS
    num_vals = np.full((len(num_keys), T), np.nan, dtype=np.float32)
    for ki, key in enumerate(num_keys):
        for i, lab in enumerate(labels):
            v = lab.get(key)
            if v is not None:
                num_vals[ki, i] = float(v)

    return Lattice(
        specs=specs, names=[s.name for s in specs], labels=labels,
        zones=tuple(zones), capacity_types=tuple(capacity_types),
        capacity=capacity, alloc=alloc, price=price, available=available,
        cat_vocab=cat_vocab, cat_ids=cat_ids, num_vals=num_vals,
        name_to_idx={s.name: i for i, s in enumerate(specs)},
    )
