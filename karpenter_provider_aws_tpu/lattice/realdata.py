"""Real-data catalog loader: JSON dumps of actual EC2 facts → specs.

The alternative to the synthetic catalog (lattice/catalog.py): a JSON
document mirroring the reference's generated data tables (hack/code/
generators → zz_generated.{describe_instance_types,pricing_aws,bandwidth,
vpclimits}.go) loads into the SAME InstanceTypeSpec rows build_lattice
consumes — so the solver, overhead math, and bench run over real
hardware shapes, real ENI/pod-density limits, and real prices.

A checked-in dump converted from the reference's own fixtures ships at
``lattice/data/reference_catalog.json`` (tools/import_reference_data.py
regenerates it); ``bench.py --catalog`` and tests load arbitrary dumps
with the same schema::

    {"region": "us-east-1",
     "types": [{"name": "m5.large", "vcpus": 2, "memoryMiB": 8192,
                "arch": "amd64", "cpuManufacturer": "intel",
                "hypervisor": "nitro", "bareMetal": false,
                "enis": 3, "ipv4PerEni": 10, "podEniCount": 9,
                "networkBandwidthMbps": 750, "localNvmeGb": 0,
                "efaCount": 0, "odPrice": 0.096,
                "gpuName": null, ... "acceleratorCount": 0}, ...]}
"""

from __future__ import annotations

import json
import pathlib
import re
from typing import List, Optional, Union

from .catalog import InstanceTypeSpec

DEFAULT_PATH = pathlib.Path(__file__).parent / "data" / "reference_catalog.json"

_FAMILY_RE = re.compile(r"^([a-z]+?)(\d+)([a-z0-9-]*)$")


def parse_family(family: str):
    """'m6idn' -> (category 'm', generation 6); 'trn1' -> ('trn', 1)."""
    m = _FAMILY_RE.match(family)
    if m is None:
        return family, 0
    return m.group(1), int(m.group(2))


def spec_from_dict(d: dict) -> InstanceTypeSpec:
    name = d["name"]
    family, _, size = name.partition(".")
    category, generation = parse_family(family)
    hypervisor = d.get("hypervisor", "nitro")
    if d.get("bareMetal"):
        hypervisor = ""   # metal: no hypervisor (overhead.py's convention)
    return InstanceTypeSpec(
        name=name, family=family, category=category,
        generation=generation, size=size or "large",
        vcpus=int(d["vcpus"]), memory_mib=int(d["memoryMiB"]),
        arch=d.get("arch", "amd64"),
        cpu_manufacturer=d.get("cpuManufacturer", "intel"),
        hypervisor=hypervisor,
        enis=int(d["enis"]), ipv4_per_eni=int(d["ipv4PerEni"]),
        network_bandwidth_mbps=int(d.get("networkBandwidthMbps", 0)),
        local_nvme_gb=int(d.get("localNvmeGb", 0)),
        gpu_name=d.get("gpuName"),
        gpu_manufacturer=d.get("gpuManufacturer"),
        gpu_count=int(d.get("gpuCount", 0)),
        gpu_memory_mib=int(d.get("gpuMemoryMiB", 0)),
        accelerator_name=d.get("acceleratorName"),
        accelerator_manufacturer=d.get("acceleratorManufacturer"),
        accelerator_count=int(d.get("acceleratorCount", 0)),
        efa_count=int(d.get("efaCount", 0)),
        pod_eni_count=int(d.get("podEniCount", 0)),
        od_price=float(d.get("odPrice", 0.0)),
        spot_prices=(tuple(sorted(
            (z, float(p)) for z, p in d["spotPrices"].items()))
            if d.get("spotPrices") else None),
    )


def load_catalog(path: Union[str, pathlib.Path, None] = None,
                 require_price: bool = False) -> List[InstanceTypeSpec]:
    """Load a real-data JSON catalog into InstanceTypeSpec rows (sorted
    by name, like build_catalog). ``require_price`` drops entries without
    an on-demand price — an unpriced type would pack as free."""
    doc = json.loads(pathlib.Path(path or DEFAULT_PATH).read_text())
    specs = [spec_from_dict(t) for t in doc["types"]]
    if require_price:
        specs = [s for s in specs if s.od_price > 0]
    return sorted(specs, key=lambda s: s.name)
