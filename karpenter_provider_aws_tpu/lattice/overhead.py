"""Node capacity → allocatable math.

Reimplements the reference's overhead semantics exactly (reference
pkg/providers/instancetype/types.go:176-208 capacity, :341-431 overhead):

- VM memory overhead: advertised MiB minus ceil(mem * vmMemoryOverheadPercent),
  default 7.5% (reference options.go VM_MEMORY_OVERHEAD_PERCENT=0.075);
  arm64 loses an extra 64 MiB of CMA-reserved memory.
- ENI-limited pod density: usableENIs * (IPv4-per-ENI - 1) + 2, with
  reserved-ENI subtraction (types.go:319-333).
- kube-reserved: memory 11*maxPods + 255 Mi; ephemeral-storage 1 Gi; CPU via
  the stepwise core-percentage table 6%/1%/0.5%/0.25% (types.go:349-385).
- eviction threshold: memory 100 Mi; ephemeral-storage 10% of disk
  (types.go:387-414); kubelet eviction signal overrides (percentage or
  absolute).
- allocatable = capacity - kubeReserved - systemReserved - evictionThreshold,
  floored at zero.

All quantities use the canonical units (cpu millicores, memory/storage MiB).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, Mapping, Optional

import numpy as np

from ..apis.resources import RESOURCE_AXES, axis, resources_to_vec

DEFAULT_VM_MEMORY_OVERHEAD_PERCENT = 0.075
DEFAULT_POD_DENSITY_CAP = 110  # non-ENI-limited AMI families default to 110 pods


@dataclass
class KubeletConfiguration:
    """Subset of the kubelet config surface that affects allocatable
    (reference corev1beta1.KubeletConfiguration as consumed by types.go)."""

    max_pods: Optional[int] = None
    pods_per_core: Optional[int] = None
    kube_reserved: Dict[str, "str | int | float"] = field(default_factory=dict)
    system_reserved: Dict[str, "str | int | float"] = field(default_factory=dict)
    eviction_hard: Dict[str, str] = field(default_factory=dict)   # {"memory.available": "5%", ...}
    eviction_soft: Dict[str, str] = field(default_factory=dict)


def vm_usable_memory_mib(advertised_mib: float, arch: str = "amd64",
                         vm_memory_overhead_percent: float = DEFAULT_VM_MEMORY_OVERHEAD_PERCENT) -> float:
    mem = float(advertised_mib)
    if arch == "arm64":
        mem -= 64.0  # graviton CMA reservation (types.go:203-205)
    return mem - math.ceil(mem * vm_memory_overhead_percent)


def eni_limited_pods(enis: int, ipv4_per_eni: int, reserved_enis: int = 0) -> int:
    usable = max(enis - reserved_enis, 0)
    if usable == 0:
        return 0
    return usable * (ipv4_per_eni - 1) + 2


def ebs_attach_limit(hypervisor: str, enis: int) -> int:
    """Schedulable EBS volume attachments per node — the lattice's
    prediction of what the EBS CSI driver will report via CSINode once the
    node registers (the reference discovers it only at runtime and can
    over-schedule before CSINode exists, troubleshooting.md:277-299).
    Nitro — including bare metal ('' in the catalog), which runs the same
    nitro card — shares 28 attachment slots between ENIs, the root
    volume, and data volumes; only Xen allows 40 minus the root."""
    if hypervisor == "xen":
        return 39
    return max(28 - enis - 1, 1)


def max_pods(enis: int, ipv4_per_eni: int, vcpus: int, kc: Optional[KubeletConfiguration] = None,
             eni_limited_density: bool = True, reserved_enis: int = 0) -> int:
    """Pod density (types.go:416-431)."""
    if kc is not None and kc.max_pods is not None:
        count = kc.max_pods
    elif eni_limited_density:
        count = eni_limited_pods(enis, ipv4_per_eni, reserved_enis)
    else:
        count = DEFAULT_POD_DENSITY_CAP
    if kc is not None and kc.pods_per_core:
        count = min(kc.pods_per_core * vcpus, count)
    return count


def _stepwise_cpu_reserved_millis(cpu_millis: float) -> float:
    reserved = 0.0
    for start, end, pct in ((0, 1000, 0.06), (1000, 2000, 0.01),
                            (2000, 4000, 0.005), (4000, 1 << 31, 0.0025)):
        if cpu_millis >= start:
            span = (cpu_millis - start) if cpu_millis < end else (end - start)
            reserved += int(span * pct)
    return reserved


def kube_reserved(cpu_millis: float, pods: int, kc: Optional[KubeletConfiguration] = None) -> np.ndarray:
    """kube-reserved vector (types.go:349-385)."""
    vec = np.zeros((len(RESOURCE_AXES),), dtype=np.float32)
    vec[axis("memory")] = 11.0 * pods + 255.0
    vec[axis("ephemeral-storage")] = 1024.0  # 1Gi default
    vec[axis("cpu")] = _stepwise_cpu_reserved_millis(cpu_millis)
    if kc is not None and kc.kube_reserved:
        # keys present in the override map win outright — including explicit
        # zeros (an operator disabling a reservation must see it disabled)
        override = resources_to_vec(kc.kube_reserved)
        for name in kc.kube_reserved:
            vec[axis(name)] = override[axis(name)]
    return vec


def system_reserved(kc: Optional[KubeletConfiguration] = None) -> np.ndarray:
    if kc is not None and kc.system_reserved:
        return resources_to_vec(kc.system_reserved)
    return np.zeros((len(RESOURCE_AXES),), dtype=np.float32)


def _eviction_signal(capacity: float, signal: str) -> float:
    """Percentage or absolute eviction signal (types.go computeEvictionSignal)."""
    s = signal.strip()
    if s.endswith("%"):
        return capacity * float(s[:-1]) / 100.0
    from ..utils.units import parse_mem_mib
    return parse_mem_mib(s)


def eviction_threshold(memory_mib: float, storage_mib: float,
                       kc: Optional[KubeletConfiguration] = None,
                       eviction_soft_enabled: bool = True) -> np.ndarray:
    """Eviction overhead vector (types.go:387-414): default 100Mi memory +
    10% of disk, overridden by the max across configured eviction signals."""
    vec = np.zeros((len(RESOURCE_AXES),), dtype=np.float32)
    vec[axis("memory")] = 100.0
    vec[axis("ephemeral-storage")] = math.ceil(storage_mib / 100.0 * 10.0)
    if kc is None:
        return vec
    mem_override, fs_override = 0.0, 0.0
    signals = [kc.eviction_hard]
    if eviction_soft_enabled:
        signals.append(kc.eviction_soft)
    for m in signals:
        if not m:
            continue
        if "memory.available" in m:
            mem_override = max(mem_override, _eviction_signal(memory_mib, m["memory.available"]))
        if "nodefs.available" in m:
            fs_override = max(fs_override, _eviction_signal(storage_mib, m["nodefs.available"]))
    if mem_override > 0:
        vec[axis("memory")] = mem_override
    if fs_override > 0:
        vec[axis("ephemeral-storage")] = fs_override
    return vec


def allocatable(capacity: np.ndarray, cpu_millis: float, pods: int,
                memory_mib: float, storage_mib: float,
                kc: Optional[KubeletConfiguration] = None) -> np.ndarray:
    """capacity - kubeReserved - systemReserved - evictionThreshold, >= 0."""
    overhead = (kube_reserved(cpu_millis, pods, kc)
                + system_reserved(kc)
                + eviction_threshold(memory_mib, storage_mib, kc))
    out = capacity.astype(np.float32) - overhead
    # overhead only ever applies to cpu/memory/storage — never to counted
    # extended resources; clamp at zero like the reference's Quantity math
    return np.maximum(out, 0.0)
