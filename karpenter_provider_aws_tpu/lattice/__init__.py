from .catalog import InstanceTypeSpec, build_catalog, ZONES, CAPACITY_TYPES
from .overhead import (
    eni_limited_pods,
    kube_reserved,
    eviction_threshold,
    allocatable,
    KubeletConfiguration,
)
from .tensors import Lattice, build_lattice

__all__ = [
    "InstanceTypeSpec", "build_catalog", "ZONES", "CAPACITY_TYPES",
    "eni_limited_pods", "kube_reserved", "eviction_threshold", "allocatable",
    "KubeletConfiguration", "Lattice", "build_lattice",
]
