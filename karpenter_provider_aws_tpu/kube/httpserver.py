"""HTTP REST surface over the FakeAPIServer — the wire-reachable seam.

In-process, controllers speak to the apiserver through KubeClient. This
module serves the SAME verbs over HTTP so an external agent (kubectl-
style tooling, a non-Python writer, another host) can drive the control
plane across a process boundary — the last step of the reference's
ingest story (its controllers talk to a remote apiserver over REST;
SURVEY §1 L0). Routes, mirroring the k8s path shapes:

    GET    /apis                           discovery → {kinds: [...]}
    GET    /apis/{kind}                    list → {items, resourceVersion}
    GET    /apis/{kind}?watch=1&resourceVersion=N
                                           chunked JSON-lines watch stream
    GET    /apis/{kind}/{name}             get → envelope
    POST   /apis/{kind}                    create (spec body) → envelope
    PUT    /apis/{kind}/{name}             update (full envelope body)
    PATCH  /apis/{kind}/{name}             merge patch {spec?, status?,
                                           finalizers?}
    DELETE /apis/{kind}/{name}[?force=1]   delete (finalizer-aware)
    POST   /apis/pods/{name}/binding       {"nodeName": ...}
    POST   /apis/pods/{name}/eviction[?force=1]

Error mapping is the real protocol's: 401 Unauthorized (bad/missing
bearer token when auth is enabled), 404 NotFound, 409 Conflict /
AlreadyExists, 410 Gone (watch too old), 422 Invalid (admission, with
causes), 429 eviction blocked by a PodDisruptionBudget.

The watch stream emits one JSON object per line ({type, object,
resourceVersion}) and a periodic heartbeat line so half-open
connections die; it ends when the client disconnects.

Transport security (the real apiserver's posture): pass ``token`` to
require ``Authorization: Bearer <token>`` on every request, and
``certfile``/``keyfile`` to serve HTTPS (deploy/gen_certs.sh mints
self-signed material at render time, the analog of the reference
chart's secret-webhook-cert.yaml). The CLI refuses to bind this
surface beyond loopback without both unless --api-insecure is given.
"""

from __future__ import annotations

import hmac
import json
import ssl
import threading
from contextlib import nullcontext
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .. import introspect, trace
from ..apis import wellknown as wk
from .apiserver import (
    KINDS, AlreadyExistsError, APIError, ConflictError,
    EvictionBlockedError, FakeAPIServer, InvalidObjectError, NotFoundError,
    TooOldError,
)

WATCH_HEARTBEAT_SECONDS = 15.0


def check_bearer(auth_header: Optional[str], token: str) -> bool:
    """Constant-time check of an ``Authorization: Bearer`` header."""
    if not auth_header or not auth_header.startswith("Bearer "):
        return False
    # bytes on both sides: compare_digest(str, str) raises on the
    # non-ASCII header an arbitrary client can send
    return hmac.compare_digest(
        auth_header[len("Bearer "):].encode("utf-8", "surrogateescape"),
        token.encode("utf-8"))


class TLSThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that performs the TLS handshake in the
    PER-CONNECTION thread (finish_request), not in accept(): wrapping the
    listening socket would run do_handshake inside serve_forever, where
    one stalled client (``nc host port`` sending nothing) blocks every
    other connection — including /healthz, so the kubelet would kill the
    pod. A handshake timeout bounds the slow-client window."""

    HANDSHAKE_TIMEOUT = 10.0

    def __init__(self, addr, handler, certfile: str, keyfile: Optional[str]):
        self._ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        self._ctx.load_cert_chain(certfile, keyfile)
        super().__init__(addr, handler)

    def finish_request(self, request, client_address):
        request.settimeout(self.HANDSHAKE_TIMEOUT)
        try:
            request = self._ctx.wrap_socket(request, server_side=True)
        except (ssl.SSLError, OSError):
            # plaintext probe / handshake garbage: drop quietly
            try:
                request.close()
            except OSError:
                pass
            return
        request.settimeout(None)
        super().finish_request(request, client_address)


def maybe_gzip(body: bytes, accept_encoding: Optional[str],
               min_bytes: int = 256) -> Tuple[bytes, Optional[str]]:
    """Gzip a response body when the client advertised support.

    Returns ``(body, content_encoding-or-None)``. The introspection
    payloads this serves grew real: /debug/vars?series=1 carries 600-
    sample rings x per-subsystem series (hundreds of KB) and a kpctl
    top session polls it every 2 s — so both debug surfaces and /metrics
    honor ``Accept-Encoding: gzip``. Tiny bodies pass through (the
    header costs more than it saves)."""
    if not accept_encoding or "gzip" not in accept_encoding.lower() \
            or len(body) < min_bytes:
        return body, None
    import gzip
    return gzip.compress(body, compresslevel=6), "gzip"


def make_http_server(addr, handler, certfile: Optional[str] = None,
                     keyfile: Optional[str] = None) -> ThreadingHTTPServer:
    """The one place HTTP(S) servers are built (REST apiserver + the
    CLI's metrics/webhook server): plaintext ThreadingHTTPServer, or the
    per-connection-handshake TLS variant when a cert is given."""
    if certfile:
        return TLSThreadingHTTPServer(addr, handler, certfile, keyfile)
    return ThreadingHTTPServer(addr, handler)


def _route(path: str) -> Tuple[str, Optional[str], Optional[str]]:
    """'/apis/pods/p0/binding' → ('pods', 'p0', 'binding')."""
    parts = [p for p in path.split("/") if p]
    if len(parts) < 2 or parts[0] != "apis":
        raise NotFoundError(f"no route {path}")
    kind = parts[1]
    name = parts[2] if len(parts) > 2 else None
    sub = parts[3] if len(parts) > 3 else None
    return kind, name, sub


def serve(server: FakeAPIServer, port: int = 0,
          host: str = "127.0.0.1", token: Optional[str] = None,
          certfile: Optional[str] = None,
          keyfile: Optional[str] = None,
          queue=None) -> ThreadingHTTPServer:
    """Serve the apiserver on ``host:port`` (port 0 = ephemeral); returns
    the HTTP server (``.server_address[1]`` carries the bound port).
    Defaults to loopback: this surface is WRITE-CAPABLE — exposing it
    beyond the host is an explicit deployment decision that should come
    with ``token`` (bearer auth) and ``certfile``/``keyfile`` (TLS).

    ``queue`` (an interruption FakeQueue) additionally serves
    ``POST /queue/messages`` — the SQS-over-HTTP ingest analog (the real
    EventBridge→SQS path is an HTTP API too), so external chaos /
    integration harnesses can inject interruption events across the
    process boundary (tests/test_crossprocess_e2e.py)."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        # ---- plumbing --------------------------------------------------

        def handle_one_request(self):
            # TLS handshake failures (a plaintext client probing the
            # HTTPS port) surface as SSL errors mid-read: drop quietly
            try:
                super().handle_one_request()
            except ssl.SSLError:
                self.close_connection = True

        def parse_request(self):
            ok = super().parse_request()
            if not ok:
                return False
            if token is not None and not check_bearer(
                    self.headers.get("Authorization"), token):
                self._json(401, {"error": "Unauthorized",
                                 "message": "missing or bad bearer token"})
                self.close_connection = True
                return False
            return True

        def _json(self, code: int, doc) -> None:
            body = json.dumps(doc).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            # every response carries the server clock, so clients can
            # anchor age rendering even off single-object GETs (the
            # list-body serverTime field covers only list responses).
            # Plain numeric, NOT repr(): under a numpy-scalar clock
            # repr() renders 'np.float64(…)' on numpy>=2, which no
            # plain float() parser accepts (kpctl tolerates both forms
            # for servers that predate this fix).
            self.send_header("X-Server-Time", f"{float(server.now()):.6f}")
            sp = trace.current()
            if sp is not None:
                # context injection: the response names the server span so
                # a client can stitch its own spans to the handled request
                self.send_header("traceparent", sp.traceparent())
            self.end_headers()
            self.wfile.write(body)

        def _req_span(self, verb: str, path: str):
            """A server span for this request. Only a request that CARRIES
            context (traceparent header) or can START a causal chain (a
            write verb) gets one — read-only polling without context would
            churn the flight-recorder ring with single-span noise."""
            if not trace.enabled():
                return nullcontext()
            tp = self.headers.get("traceparent")
            if tp is None and verb == "GET":
                return nullcontext()
            return trace.span(f"http {verb} {path}", parent=tp,
                              http_method=verb)

        def _error(self, e: Exception) -> None:
            code = (404 if isinstance(e, NotFoundError) else
                    409 if isinstance(e, (ConflictError, AlreadyExistsError))
                    else 410 if isinstance(e, TooOldError) else
                    422 if isinstance(e, InvalidObjectError) else
                    429 if isinstance(e, EvictionBlockedError) else
                    400 if isinstance(e, (APIError, ValueError, KeyError))
                    else 500)
            doc = {"error": type(e).__name__, "message": str(e)}
            if isinstance(e, InvalidObjectError):
                doc["causes"] = e.causes
            self._json(code, doc)

        def _body(self) -> dict:
            length = int(self.headers.get("Content-Length", "0"))
            raw = self.rfile.read(length) if length else b"{}"
            doc = json.loads(raw or b"{}")
            if not isinstance(doc, dict):
                raise ValueError("body must be a JSON object")
            return doc

        # ---- verbs -----------------------------------------------------

        def do_GET(self):
            try:
                url = urlparse(self.path)
                # discovery: the kubectl api-resources flow (a real
                # apiserver serves its group/resource lists under /apis)
                if url.path.rstrip("/") == "/apis":
                    self._json(200, {"kinds": list(KINDS)})
                    return
                # the introspection surfaces (docs/reference/
                # introspection.md): /debug/statusz (human) and
                # /debug/vars (JSON; kpctl top + soak backbone)
                rendered = introspect.debug_doc(url.path,
                                                parse_qs(url.query))
                if rendered is not None:
                    body, ctype = rendered
                    body, enc = maybe_gzip(
                        body, self.headers.get("Accept-Encoding"))
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    if enc:
                        self.send_header("Content-Encoding", enc)
                    self.send_header("Content-Length", str(len(body)))
                    # every response carries the server clock (the PR 2
                    # invariant _json enforces): a kpctl session that
                    # only polls /debug/vars still anchors age rendering
                    self.send_header("X-Server-Time",
                                     f"{float(server.now()):.6f}")
                    self.end_headers()
                    self.wfile.write(body)
                    return
                # the flight recorder's read surface (kpctl trace):
                # list / get / Chrome-export retained + ring traces
                if url.path.startswith("/debug/traces"):
                    rec = trace.recorder()
                    doc = (rec.debug_doc(url.path, parse_qs(url.query))
                           if rec is not None else None)
                    if doc is None:
                        raise NotFoundError(
                            f"no trace at {url.path}" if rec is not None
                            else "tracing is not enabled (--trace)")
                    self._json(200, doc)
                    return
                kind, name, sub = _route(url.path)
                if sub is not None:
                    raise NotFoundError(f"no route {url.path}")
                q = parse_qs(url.query)
                # the name check stays FIRST: a named GET with a stray
                # watch=1 param returns the object (the pre-tracing
                # contract), never silently discards the name into a
                # kind-wide stream
                if name is None and q.get("watch", ["0"])[0] in ("1",
                                                                 "true"):
                    # never span a watch: the stream outlives any request
                    # scope and would pin its trace open
                    self._watch(kind, int(q.get("resourceVersion", ["0"])[0]))
                    return
                with self._req_span("GET", url.path):
                    if name is not None:
                        self._json(200, server.get(kind, name))
                        return
                    items, rv = server.list(kind)
                    # serverTime lets clients (kpctl) anchor AGE/LAST
                    # SEEN columns to the clock that stamped the
                    # timestamps, instead of their own wall clock
                    self._json(200, {"items": items, "resourceVersion": rv,
                                     "serverTime": float(server.now())})
            except Exception as e:
                self._error(e)

        def _watch(self, kind: str, rv: int) -> None:
            w = server.watch(kind, rv)   # raises TooOldError → 410

            def chunk(payload: bytes) -> None:
                self.wfile.write(f"{len(payload):x}\r\n".encode()
                                 + payload + b"\r\n")
                self.wfile.flush()

            # everything after subscription lives under the finally that
            # unsubscribes — a client dropping during the header writes
            # must not leak the Watch (its queue would grow forever)
            try:
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                while True:
                    try:
                        ev = w.get(timeout=WATCH_HEARTBEAT_SECONDS)
                    except TooOldError as e:
                        # the watcher overran its bounded server-side
                        # queue: emit the protocol's ERROR event (the
                        # 410-Gone-mid-stream analog) and end the stream
                        # — the client relists, like a reflector
                        chunk(json.dumps({
                            "type": "ERROR", "code": 410,
                            "reason": "Expired", "message": str(e),
                        }).encode() + b"\n")
                        self.wfile.write(b"0\r\n\r\n")
                        self.wfile.flush()
                        break
                    if ev is None:
                        chunk(b'{"type":"HEARTBEAT"}\n')
                        continue
                    chunk(json.dumps({
                        "type": ev.type, "object": ev.object,
                        "resourceVersion": ev.resource_version,
                    }).encode() + b"\n")
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass   # client went away: normal watch teardown
            finally:
                server.stop_watch(w)

        # every write verb nests try OUTSIDE the span (like do_GET): the
        # span must SEE a handler exception on exit — status=error is
        # what the flight recorder's tail sampler keys retention on —
        # and only then does the outer except send the error response

        def do_POST(self):
            try:
                with self._req_span("POST", urlparse(self.path).path):
                    url = urlparse(self.path)
                    if url.path == "/queue/messages":
                        if queue is None:
                            raise NotFoundError("no interruption queue served")
                        mid = queue.send(self._body())
                        self._json(201, {"messageId": mid})
                        return
                    kind, name, sub = _route(url.path)
                    q = parse_qs(url.query)
                    if kind == "pods" and name is not None and sub == "binding":
                        body = self._body()
                        self._json(200, server.bind(name, body["nodeName"]))
                        return
                    if kind == "pods" and name is not None and sub == "eviction":
                        force = q.get("force", ["0"])[0] in ("1", "true")
                        self._json(200, server.evict(name, force=force))
                        return
                    if name is not None:
                        raise NotFoundError(f"no route {url.path}")
                    spec = self._body()
                    sp = trace.current()
                    if kind == "pods" and sp is not None:
                        # stamp the admission span onto the pod: the
                        # informer delivers it to the mirror, and the
                        # provisioning pass that drains this pod JOINS
                        # this trace (REST → operator causal chain)
                        spec.setdefault("annotations", {}).setdefault(
                            wk.ANNOTATION_TRACEPARENT, sp.traceparent())
                    self._json(201, server.create(kind, spec))
            except Exception as e:
                self._error(e)

        def do_PUT(self):
            try:
                with self._req_span("PUT", urlparse(self.path).path):
                    kind, name, sub = _route(urlparse(self.path).path)
                    if sub is not None:
                        raise NotFoundError(f"no route {self.path}")
                    if name is None:
                        raise NotFoundError("PUT needs a name")
                    obj = self._body()
                    if obj.get("metadata", {}).get("name") != name:
                        raise ValueError("metadata.name must match the URL")
                    self._json(200, server.update(kind, obj))
            except Exception as e:
                self._error(e)

        def do_PATCH(self):
            try:
                with self._req_span("PATCH", urlparse(self.path).path):
                    kind, name, sub = _route(urlparse(self.path).path)
                    if sub is not None:
                        raise NotFoundError(f"no route {self.path}")
                    if name is None:
                        raise NotFoundError("PATCH needs a name")
                    body = self._body()
                    self._json(200, server.patch(
                        kind, name, body.get("spec"),
                        status_patch=body.get("status"),
                        finalizers=body.get("finalizers")))
            except Exception as e:
                self._error(e)

        def do_DELETE(self):
            try:
                with self._req_span("DELETE", urlparse(self.path).path):
                    url = urlparse(self.path)
                    kind, name, sub = _route(url.path)
                    if sub is not None:
                        # e.g. DELETE /apis/pods/p0/eviction — the wrong
                        # verb must NEVER fall through to deleting the
                        # parent
                        raise NotFoundError(f"no route {url.path}")
                    if name is None:
                        raise NotFoundError("DELETE needs a name")
                    q = parse_qs(url.query)
                    force = q.get("force", ["0"])[0] in ("1", "true")
                    server.delete(kind, name, force=force)
                    self._json(200, {"status": "ok"})
            except Exception as e:
                self._error(e)

        def log_message(self, *a):   # quiet by default
            pass

    httpd = make_http_server((host, port), Handler, certfile, keyfile)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd
