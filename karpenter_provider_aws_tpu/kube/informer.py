"""Reflector/informer: list+watch a kind into a local store with handlers.

The controller-runtime informer analog. A reflector does one initial
``list`` (seeding the store and the sync point), then consumes the watch
stream from the list's resourceVersion, applying ADDED/MODIFIED/DELETED
to the store and invoking the registered handler per event. When the
watch RV falls off the server's history (TooOldError — the 410 Gone), it
RELISTS and reconciles the store against the fresh list, synthesizing
add/update/delete handler calls for the delta — exactly the reflector
recovery path in client-go.

Handlers receive full ENVELOPES ({"metadata": ..., "spec": ...}) — state
appliers need metadata (deletionTimestamp, resourceVersion), not just the
spec.

Two drive modes:

- ``sync_once()`` — pump synchronously: deliver every pending event now.
  The deterministic test/simulation path (FakeClock strata), where the
  caller interleaves pumping and reconciling.
- ``start()/stop()`` — a daemon thread pumping continuously with a
  blocking get. The production path (threaded ControllerRuntime).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from .apiserver import FakeAPIServer, TooOldError, Watch, WatchEvent

# handler(event_type, name, envelope, old_envelope) — envelope is None for
# DELETED, old_envelope is None for ADDED
Handler = Callable[[str, str, Optional[dict], Optional[dict]], None]


class Informer:
    def __init__(self, server: FakeAPIServer, kind: str,
                 handler: Optional[Handler] = None):
        self.server = server
        self.kind = kind
        self.handler = handler
        self.store: Dict[str, dict] = {}    # name -> envelope (local cache)
        self._watch: Optional[Watch] = None
        self._rv = 0
        self._synced = False
        self._lock = threading.RLock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    @property
    def has_synced(self) -> bool:
        return self._synced

    def specs(self) -> Dict[str, dict]:
        """Snapshot of name -> spec from the local cache."""
        with self._lock:
            return {n: o["spec"] for n, o in self.store.items()}

    # ---- protocol ----------------------------------------------------------

    def _relist(self) -> None:
        """Initial list, or recovery from a 410: replace the store with
        the server's truth, synthesizing handler events for the delta.
        A LOOP, not recursion: under sustained churn (>ring events
        landing between each list and watch attempt) recursion would
        grow the Python stack and eventually kill the informer thread
        instead of retrying like a client-go reflector."""
        while True:
            items, rv = self.server.list(self.kind)
            fresh = {o["metadata"]["name"]: o for o in items}
            with self._lock:
                old = self.store
                self.store = fresh
                self._rv = rv
                self._synced = True
            if self.handler is not None:
                for name, obj in fresh.items():
                    prev = old.get(name)
                    if prev is None:
                        self.handler("ADDED", name, obj, None)
                    elif (prev["metadata"]["resourceVersion"]
                          != obj["metadata"]["resourceVersion"]):
                        self.handler("MODIFIED", name, obj, prev)
                for name, obj in old.items():
                    if name not in fresh:
                        self.handler("DELETED", name, None, obj)
            if self._watch is not None:
                self.server.stop_watch(self._watch)
                self._watch = None
            try:
                self._watch = self.server.watch(self.kind, self._rv)
                return
            except TooOldError:
                # events raced past the ring between our list and watch —
                # relist from the new high-water mark
                continue

    def _apply(self, ev: WatchEvent) -> None:
        if ev.type == "BOOKMARK":
            # no object change — just advance the resume point, so a
            # relist after a 410 starts from a fresh RV (the client-go
            # allowWatchBookmarks contract)
            with self._lock:
                self._rv = ev.resource_version
            return
        name = ev.object["metadata"]["name"]
        with self._lock:
            old = self.store.get(name)
            if ev.type == "DELETED":
                self.store.pop(name, None)
            else:
                self.store[name] = ev.object
            self._rv = ev.resource_version
        if self.handler is not None:
            if ev.type == "DELETED":
                self.handler("DELETED", name, None, old)
            else:
                self.handler(ev.type, name, ev.object, old)

    def sync_once(self) -> int:
        """Deterministic pump: list on first call, then drain every pending
        watch event. Returns the number of events applied. A watcher the
        server dropped for overrunning its bounded queue (TooOldError —
        the in-process 410) recovers by RELISTING, exactly like the
        ring-expiry path."""
        if not self._synced or self._watch is None:
            self._relist()
            return len(self.store)
        try:
            pending = self._watch.pop_pending()
        except TooOldError:
            self._synced = False
            self._relist()
            return len(self.store)
        n = 0
        for ev in pending:
            self._apply(ev)
            n += 1
        return n

    # ---- threaded mode -----------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            if not self._synced or self._watch is None:
                self._relist()
            try:
                ev = self._watch.get(timeout=0.2)
            except TooOldError:
                # overran the bounded per-watcher queue: relist on the
                # next loop turn (_relist unsubscribes the dead watch)
                self._synced = False
                continue
            if ev is not None:
                self._apply(ev)

    def start(self) -> "Informer":
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"informer-{self.kind}", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._watch is not None:
            self._watch.stop()
        if self._thread is not None:
            self._thread.join(timeout)


class InformerSet:
    """The shared-informer-factory analog: one informer per kind, pumped
    or started together, in a FIXED kind order for the deterministic path
    (config kinds before pods before nodes/claims, so appliers observe
    referents first on initial sync)."""

    def __init__(self, server: FakeAPIServer):
        self.server = server
        self.informers: Dict[str, Informer] = {}
        self._order: List[str] = []

    def add(self, kind: str, handler: Optional[Handler] = None) -> Informer:
        inf = Informer(self.server, kind, handler)
        self.informers[kind] = inf
        self._order.append(kind)
        return inf

    def sync_once(self) -> int:
        return sum(self.informers[k].sync_once() for k in self._order)

    def start(self) -> "InformerSet":
        for k in self._order:
            self.informers[k].start()
        return self

    def stop(self) -> None:
        for k in self._order:
            self.informers[k].stop()

    @property
    def has_synced(self) -> bool:
        return all(i.has_synced for i in self.informers.values())
