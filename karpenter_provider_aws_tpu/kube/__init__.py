"""Kubernetes-API-shaped ingest seam: fake apiserver, typed client,
informers (reference pkg/operator/operator.go manager + client wiring;
pkg/test/environment.go envtest stratum)."""

from .apiserver import (
    AlreadyExistsError, APIError, ConflictError, EvictionBlockedError,
    FakeAPIServer, InvalidObjectError, NotFoundError, TooOldError, Watch,
    WatchEvent,
)
from .client import (
    KubeClient, TERMINATION_FINALIZER, install_admission,
    install_default_indexes,
)
from .informer import Informer, InformerSet
from .httpserver import serve as serve_http

__all__ = [
    "APIError", "AlreadyExistsError", "ConflictError",
    "EvictionBlockedError", "FakeAPIServer", "Informer", "InformerSet",
    "InvalidObjectError", "KubeClient", "NotFoundError",
    "TERMINATION_FINALIZER", "TooOldError", "Watch", "WatchEvent",
    "install_admission", "install_default_indexes", "serve_http",
]
