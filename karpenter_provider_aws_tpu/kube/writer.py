"""The controllers' write seam: every Kubernetes-object mutation a
controller makes goes through this interface.

Two implementations, one contract:

- ``DirectWriter`` applies writes straight into the ClusterState mirror —
  the deterministic simulation stratum (FakeClock unit tests), where
  read-your-write is immediate.
- ``ApiWriter`` writes to the fake apiserver through the typed client;
  the mirror only changes when the operator's informers deliver the watch
  events (operator/sync.py). This is the reference's wiring: controllers
  own NO state — they act through the client and observe through caches
  (cmd/controller/main.go:47-53, operator.go:92-186).

The split keeps controller code identical across strata — the reference
achieves the same by running envtest (a real apiserver) under its unit
suites (pkg/test/environment.go:83-162).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

from .. import trace
from ..apis.objects import Lease, Node, NodeClaim, NodeClaimPhase, Pod
from ..state.cluster import ClusterState
from ..utils.clock import Clock
from .apiserver import (
    ConflictError, EvictionBlockedError, NotFoundError,
)
from .client import KubeClient


class FencedWriteError(RuntimeError):
    """A side-effectful write was attempted under a fencing token the
    lease store no longer carries — a demoted (zombie) leader's queued
    eviction/claim/bind. Raised AT THE VERB so the write never reaches
    the store; the controller runtime counts it like any reconcile error
    and the zombie's loop goes quiet instead of racing the new leader."""

    def __init__(self, verb: str, fence: int):
        # lazy: kube must stay importable without the solver package
        from ..solver.taxonomy import FENCED_WRITE_REJECTED, reason
        self.verb = verb
        self.fence = fence
        self.reason = reason(FENCED_WRITE_REJECTED,
                             f"{verb} under rotated fence (held {fence})")
        super().__init__(self.reason)


class WriterCounts:
    """Per-verb write-throughput counters shared by both writer
    implementations — the introspection registry's ``writer`` provider,
    and the input the round-5 verdict's write-path profiling item needs
    (API-stratum throughput DEGRADES 1k→15k; these counters put per-verb
    rates next to the apiserver's own watch/event stats)."""

    def _init_counts(self) -> None:
        self.counts: Dict[str, int] = {}
        # instrumented (introspect/contention.py): every write verb
        # passes through here — contention means the write path itself
        # is the serializer
        from ..introspect import contention
        self._counts_lock = contention.lock("writer")
        # handoff fencing (operator/leaderelection.py FenceGuard):
        # unarmed (None) in single-operator deployments — one attribute
        # read on the write path
        self._fence = None

    def set_fence(self, guard) -> None:
        """Arm handoff fencing: every side-effectful verb re-checks the
        lease store's fencing token first and raises
        :class:`FencedWriteError` (counted as ``fenced_reject``) when it
        rotated — the zombie-leader write barrier."""
        self._fence = guard

    def _check_fence(self, verb: str) -> None:
        g = self._fence
        if g is None or g.check():
            return
        self._count("fenced_reject")
        raise FencedWriteError(verb, g.fence)

    def _count(self, verb: str, n: int = 1) -> None:
        with self._counts_lock:
            self.counts[verb] = self.counts.get(verb, 0) + n

    def stats(self) -> Dict[str, int]:
        with self._counts_lock:
            return dict(self.counts)


class DirectWriter(WriterCounts):
    """Write-through to the ClusterState mirror (simulation stratum)."""

    def __init__(self, cluster: ClusterState, clock: Clock):
        self.cluster = cluster
        self.clock = clock
        self._init_counts()

    # ---- claims ------------------------------------------------------------

    def create_claim(self, claim: NodeClaim) -> None:
        self._check_fence("create_claim")
        self._count("create_claim")
        self.cluster.add_claim(claim)

    def update_claim_status(self, claim: NodeClaim) -> None:
        # in-place mutation is already visible through the mirror
        self._check_fence("update_claim_status")
        self._count("update_claim_status")

    def mark_claim_deleting(self, name: str) -> None:
        """The k8s delete that starts the finalizer/termination flow."""
        self._check_fence("mark_claim_deleting")
        self._count("mark_claim_deleting")
        claim = self.cluster.claims.get(name)
        if claim is None:
            return
        if not claim.deletion_timestamp:
            claim.deletion_timestamp = self.clock.now()
            claim.phase = NodeClaimPhase.TERMINATING
            # the claim leaves pool_usage() immediately: re-render gauges
            self.cluster.touch_capacity(name)

    def rollback_claim(self, name: str) -> None:
        """Hard delete of a claim whose instance never materialized (or is
        already gone) — no drain, no finalizer round."""
        self._check_fence("rollback_claim")
        self._count("rollback_claim")
        self.cluster.delete_claim(name)

    def finalize_claim(self, claim: NodeClaim) -> None:
        """Termination complete: remove the claim object."""
        self._check_fence("finalize_claim")
        self._count("finalize_claim")
        self.cluster.delete_claim(claim.name)

    # ---- nodes -------------------------------------------------------------

    def register_node(self, node: Node, lease: Optional[Lease] = None) -> None:
        self._check_fence("register_node")
        self._count("register_node")
        self.cluster.add_node(node)
        if lease is not None:
            self.cluster.add_lease(lease)

    def cordon(self, node: Node, taint) -> bool:
        self._check_fence("cordon")
        if all(t.key != taint.key for t in node.taints):
            self._count("cordon")
            node.taints.append(taint)
            return True
        return False

    def drain_node(self, node_name: str) -> Tuple[List[Pod], List[Pod]]:
        self._check_fence("drain_node")
        self._count("drain_node")
        return self.cluster.drain_node(node_name)

    def teardown_node(self, node_name: str) -> None:
        self._check_fence("teardown_node")
        self._count("teardown_node")
        self.cluster.evict_node(node_name)

    # ---- pods / volumes / leases ------------------------------------------

    def bind_pod(self, pod_name: str, node_name: str) -> bool:
        self._check_fence("bind_pod")
        self._count("bind_pod")
        self.cluster.bind_pod(pod_name, node_name)
        return True

    def bind_pods(self, pairs: Sequence[Tuple[str, str]]) -> List[bool]:
        """Batched bind: the mirror path has no lock to amortize, so it
        is the per-pod verb in a loop (same contract as ApiWriter's)."""
        return [self.bind_pod(p, n) for p, n in pairs]

    def bind_volumes(self, pod_name: str, zone: Optional[str]) -> None:
        self._check_fence("bind_volumes")
        self._count("bind_volumes")
        self.cluster.bind_volumes(pod_name, zone)

    def delete_lease(self, name: str) -> None:
        self._check_fence("delete_lease")
        self._count("delete_lease")
        self.cluster.delete_lease(name)


class ApiWriter(WriterCounts):
    """Write-through to the apiserver; the mirror follows via informers."""

    def __init__(self, kube: KubeClient, cluster: ClusterState, clock: Clock):
        self.kube = kube
        self.cluster = cluster
        self.clock = clock
        self._init_counts()

    # ---- claims ------------------------------------------------------------

    def create_claim(self, claim: NodeClaim) -> None:
        # the write seam's spans name the k8s-object mutations inside the
        # ambient trace (a provisioning pass shows claim-create / pod-bind
        # legs between solve and CreateFleet); contextvars carry the trace
        # across this in-process hop — the httpserver carries it when the
        # same seam is driven over the wire
        self._check_fence("create_claim")
        self._count("create_claim")
        with trace.span("kube.create_nodeclaim", claim=claim.name):
            self.kube.create_nodeclaim(claim)

    def update_claim_status(self, claim: NodeClaim) -> None:
        self._check_fence("update_claim_status")
        self._count("update_claim_status")
        try:
            self.kube.update_nodeclaim(claim)
        except NotFoundError:
            pass  # deleted out from under us; the next reconcile observes it

    def mark_claim_deleting(self, name: str) -> None:
        self._check_fence("mark_claim_deleting")
        self._count("mark_claim_deleting")
        try:
            self.kube.delete_nodeclaim(name, now=self.clock.now())
        except NotFoundError:
            pass
        # the mirror's claim leaves pool_usage() when the MODIFIED event
        # lands; gauges re-render then

    def rollback_claim(self, name: str) -> None:
        self._check_fence("rollback_claim")
        self._count("rollback_claim")
        try:
            self.kube.delete_nodeclaim_now(name)
        except NotFoundError:
            pass

    def finalize_claim(self, claim: NodeClaim) -> None:
        self._check_fence("finalize_claim")
        self._count("finalize_claim")
        self.kube.remove_nodeclaim_finalizer(claim.name)

    # ---- nodes -------------------------------------------------------------

    def register_node(self, node: Node, lease: Optional[Lease] = None) -> None:
        self._check_fence("register_node")
        self._count("register_node")
        self.kube.create_node(node)
        if lease is not None:
            self.kube.create_lease(lease)

    def cordon(self, node: Node, taint) -> bool:
        self._check_fence("cordon")
        try:
            if self.kube.taint_node(node.name, taint):
                self._count("cordon")
                return True
            return False
        except NotFoundError:
            return False

    def drain_node(self, node_name: str) -> Tuple[List[Pod], List[Pod]]:
        """PDB-respecting drain THROUGH the eviction subresource: the
        server enforces budgets (the real Eviction API contract); we
        report (evicted, blocked) from its verdicts. Pod set comes from
        the mirror — the same information a real drainer lists. The
        evictions go as ONE bulk batch (one lock acquisition, one watch
        flush); the server evaluates each pod's PDB allowance in order
        inside the batch, so verdicts match the per-call sequence
        exactly."""
        self._check_fence("drain_node")
        self._count("drain_node")
        pods = [p for p in self.cluster.pods_by_node().get(node_name, [])
                if not p.is_daemonset]
        if not pods:
            return [], []
        results = self.kube.bulk([("evict", p.name) for p in pods])
        evicted: List[Pod] = []
        blocked: List[Pod] = []
        for pod, r in zip(pods, results):
            if isinstance(r, EvictionBlockedError):
                blocked.append(pod)
            elif isinstance(r, NotFoundError):
                continue
            elif isinstance(r, Exception):
                raise r
            else:
                evicted.append(pod)
        return evicted, blocked

    def teardown_node(self, node_name: str) -> None:
        """Final teardown: force-evict stragglers (grace-zero delete
        analog), remove daemonset pods with the node, delete the node —
        all one bulk batch (NotFound slots are raced teardowns)."""
        self._check_fence("teardown_node")
        self._count("teardown_node")
        ops = []
        for pod in self.cluster.pods_by_node().get(node_name, []):
            if pod.is_daemonset:
                ops.append(("delete", "pods", pod.name))
            else:
                ops.append(("evict", pod.name, True))
        ops.append(("delete", "nodes", node_name))
        self.kube.bulk(ops)

    # ---- pods / volumes / leases ------------------------------------------

    def bind_pod(self, pod_name: str, node_name: str) -> bool:
        """Returns False when the bind raced an eviction/delete — the
        watch stream carries whatever the truth is, and callers must not
        count the pod as scheduled (karpenter_pods_scheduled_total would
        overcount)."""
        self._check_fence("bind_pod")
        try:
            with trace.span("kube.bind_pod", pod=pod_name, node=node_name):
                self.kube.bind_pod(pod_name, node_name)
            self._count("bind_pod")
            return True
        except (ConflictError, NotFoundError):
            return False

    def bind_pods(self, pairs: Sequence[Tuple[str, str]]) -> List[bool]:
        """A provisioning pass's binds as ONE coalesced write: the bulk
        verb pays one lock acquisition + one watch flush for the whole
        list — bind_pod was the profiled #1 write-path frame paying
        lock+copy+fan-out per pod. Per-pair verdicts keep the raced-bind
        contract (False = not scheduled)."""
        if not pairs:
            return []
        self._check_fence("bind_pods")
        with trace.span("kube.bind_pods", pods=len(pairs)):
            oks = self.kube.bind_pods(pairs)
        n = sum(oks)
        if n:
            self._count("bind_pod", n)
        self._count("bulk_binds")
        return oks

    def bind_volumes(self, pod_name: str, zone: Optional[str]) -> None:
        """Persist WaitForFirstConsumer zone pins server-side (the CSI
        controller analog); the mirror converges via the pvcs informer."""
        if not zone:
            return
        self._check_fence("bind_volumes")
        self._count("bind_volumes")
        pod = self.cluster.pods.get(pod_name)
        if pod is None:
            return
        for cname in pod.volume_claims:
            pvc = self.cluster.pvcs.get(cname)
            if pvc is not None and pvc.bound_zone is None:
                try:
                    self.kube.patch_pvc(cname, boundZone=zone)
                except NotFoundError:
                    pass

    def delete_lease(self, name: str) -> None:
        self._check_fence("delete_lease")
        self._count("delete_lease")
        self.kube.delete_lease(name)
