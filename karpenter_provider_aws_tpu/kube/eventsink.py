"""Mirror recorder events into the apiserver as ``events`` objects.

The reference's controllers publish Kubernetes Events through the
manager's recorder (pkg/controllers/interruption/events/events.go,
pkg/cloudprovider/events) and the documented debugging flow is
``kubectl get events``. In API mode this sink gives the same surface:
every `events.Recorder.publish` also creates an object of kind
``events`` in the apiserver, so ``kpctl get events`` (and the REST
``/apis/events`` route, including watches) see the stream a real
cluster would.

Retention is the sink's job, like an apiserver's event TTL: only the
newest EVENTS_RETAINED events are kept; older ones are deleted as new
ones arrive, so a chatty controller can never grow the store without
bound. The sink periodically re-lists the store (RELIST_EVERY) and
re-adopts every name it finds, so events written by OTHER actors age
out under the same ceiling instead of accumulating untracked. The
in-memory recorder ring (events.MAX_EVENTS) is unaffected — tests and
the direct stratum keep reading that.
"""

from __future__ import annotations

import itertools
from collections import deque

from .apiserver import AlreadyExistsError, FakeAPIServer, NotFoundError

EVENTS_RETAINED = 1000
# every this-many creates the sink re-lists the store and re-adopts ALL
# event names, so events written by OTHER actors (a second operator, a
# test harness, kpctl apply) age out too instead of growing the store
# unboundedly between restarts
RELIST_EVERY = 256


class ApiEventSink:
    """``Recorder.sink`` implementation writing through an apiserver.

    Called under the recorder's lock, so creates are ordered exactly as
    published. Event names are sequential (``ev-000001``); against a
    pre-populated server the counter skips forward past collisions so a
    restarted operator keeps appending rather than failing.
    """

    def __init__(self, api: FakeAPIServer, retained: int = EVENTS_RETAINED,
                 relist_every: int = RELIST_EVERY):
        self._api = api
        self._retained = retained
        self._relist_every = relist_every
        # adopt whatever a prior run left behind: retention must cover
        # the WHOLE store, not just this instance's writes, and the
        # counter resumes past the newest adopted name so appends rarely
        # collide (the create loop still handles races). Order and resume
        # NUMERICALLY — lexicographic order breaks past ev-999999 (a
        # 7-digit name sorts before 6-digit ones), which would age out
        # the newest events and re-issue taken names after a restart.
        self._since_relist = 0
        numbered = self._adopt()
        start = numbered[-1][0] + 1 if numbered else 1
        self._seq = itertools.count(max(start, 1))

    @staticmethod
    def _numbered(objs):
        numbered = []
        for o in objs:
            name = o["metadata"]["name"]
            tail = name.rsplit("-", 1)[-1]
            numbered.append((int(tail) if tail.isdigit() else -1, name))
        numbered.sort()
        return numbered

    def _adopt(self):
        """Re-list the store and track EVERY event name, oldest first, so
        retention covers externally-written events too. Returns the
        numerically-sorted (seq, name) list."""
        existing, _ = self._api.list("events")
        numbered = self._numbered(existing)
        self._names: deque = deque(n for _, n in numbered)
        return numbered

    def __call__(self, event) -> None:
        spec = {
            "name": "",   # filled per attempt below
            "time": event.time,
            "type": event.type,
            "reason": event.reason,
            "objectKind": event.object_kind,
            "objectName": event.object_name,
            "message": event.message,
        }
        while True:
            spec["name"] = f"ev-{next(self._seq):06d}"
            try:
                self._api.create("events", spec)
                break
            except AlreadyExistsError:
                continue
        self._names.append(spec["name"])
        # periodic re-adopt: names created by actors other than this sink
        # would otherwise stay untracked forever and grow the store past
        # EVENTS_RETAINED; the counter never rewinds (create collisions
        # keep skipping forward), only the tracked-name set refreshes
        self._since_relist += 1
        if self._since_relist >= self._relist_every:
            self._since_relist = 0
            self._adopt()
        while len(self._names) > self._retained:
            try:
                self._api.delete("events", self._names.popleft())
            except NotFoundError:
                pass   # someone else aged it out — retention still holds
