"""Typed client over the FakeAPIServer — the controller-runtime client
analog.

Controllers act on the cluster EXCLUSIVELY through this client (reference
cmd/controller/main.go:47-53 hands every core controller the manager's
client); nothing typed crosses the seam — every call serializes through
apis/serde to the wire dicts the apiserver stores, so the protocol
boundary is real (a non-Python agent could speak it).

Write verbs mirror the reference's usage:

- ``create_*`` / ``delete_*`` / ``update_*`` (optimistic concurrency on
  update — retry on ConflictError like controller-runtime does)
- ``patch_*`` merge-patches named spec fields (status updates)
- ``bind_pod`` (pods/binding) and ``evict_pod`` (pods/eviction, PDB
  enforced server-side)
- NodeClaims are created WITH the termination finalizer: a delete only
  stamps deletionTimestamp and the termination controller later clears
  the finalizer — the reference's NodeClaim lifecycle contract.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..apis import serde
from ..apis.objects import (
    Lease, Node, NodeClaim, NodePool, PersistentVolumeClaim, Pod,
    PodDisruptionBudget, StorageClass,
)
from .apiserver import BulkOp, FakeAPIServer, NotFoundError, Watch

TERMINATION_FINALIZER = "karpenter.tpu/termination"


class KubeClient:
    def __init__(self, server: FakeAPIServer):
        self.server = server

    # ---- pods --------------------------------------------------------------

    def create_pod(self, pod: Pod) -> None:
        self.server.create("pods", serde.pod_to_dict(pod))

    def create_pods(self, pods: Sequence[Pod]) -> List[Optional[Exception]]:
        """Batched create through the bulk verb: one lock acquisition,
        one admission sweep, per-pod events. Returns a per-pod slot —
        None on success, the APIError on a captured failure."""
        res = self.server.bulk([("create", "pods", serde.pod_to_dict(p))
                                for p in pods])
        return [r if isinstance(r, Exception) else None for r in res]

    def delete_pods(self, names: Sequence[str]) -> int:
        """Batched delete (bulk verb); NotFound slots (raced teardowns)
        are ignored. Returns how many deletes landed."""
        res = self.server.bulk([("delete", "pods", n) for n in names])
        return sum(1 for r in res if not isinstance(r, Exception))

    def bind_pods(self, pairs: Sequence[Tuple[str, str]]) -> List[bool]:
        """Batched pods/binding: one lock acquisition and one watch
        flush for the whole list. Per-pair verdicts — a bind that raced
        an eviction/delete (Conflict/NotFound) reports False instead of
        failing the batch."""
        res = self.server.bulk([("bind", p, n) for p, n in pairs])
        return [not isinstance(r, Exception) for r in res]

    def get_pod(self, name: str) -> Pod:
        return serde.pod_from_dict(self.server.get("pods", name)["spec"])

    def list_pods(self) -> List[Pod]:
        items, _ = self.server.list("pods")
        return [serde.pod_from_dict(o["spec"]) for o in items]

    def bind_pod(self, name: str, node_name: str) -> None:
        self.server.bind(name, node_name)

    def evict_pod(self, name: str, force: bool = False) -> None:
        self.server.evict(name, force=force)

    def delete_pod(self, name: str) -> None:
        self.server.delete("pods", name)

    # ---- nodes -------------------------------------------------------------

    def create_node(self, node: Node) -> None:
        self.server.create("nodes", serde.node_to_dict(node))

    def get_node(self, name: str) -> Node:
        return serde.node_from_dict(self.server.get("nodes", name)["spec"])

    def list_nodes(self) -> List[Node]:
        items, _ = self.server.list("nodes")
        return [serde.node_from_dict(o["spec"]) for o in items]

    def patch_node(self, name: str, **spec_fields) -> None:
        self.server.patch("nodes", name, spec_fields)

    def taint_node(self, name: str, taint) -> bool:
        """Add a taint if absent; returns True when it was added."""
        obj = self.server.get("nodes", name)
        taints = obj["spec"].get("taints", [])
        if any(t["key"] == taint.key for t in taints):
            return False
        taints = taints + [serde._taint_to_dict(taint)]
        self.server.patch("nodes", name, {"taints": taints})
        return True

    def delete_node(self, name: str) -> None:
        self.server.delete("nodes", name)

    # ---- nodeclaims --------------------------------------------------------

    def create_nodeclaim(self, claim: NodeClaim) -> None:
        self.server.create("nodeclaims", serde.nodeclaim_to_dict(claim),
                           finalizers=(TERMINATION_FINALIZER,))

    @staticmethod
    def claim_from_envelope(obj: dict) -> NodeClaim:
        """Typed claim from a wire envelope, with the API-level deletion
        stamp overlaid: the delete verb marks metadata.deletionTimestamp
        (the spec is untouched), and every consumer truth-tests
        claim.deletion_timestamp — so ALL read paths must overlay it."""
        c = serde.nodeclaim_from_dict(obj["spec"])
        meta_ts = obj["metadata"]["deletionTimestamp"]
        if meta_ts is not None and not c.deletion_timestamp:
            c.deletion_timestamp = meta_ts
        return c

    def get_nodeclaim(self, name: str) -> NodeClaim:
        return self.claim_from_envelope(self.server.get("nodeclaims", name))

    def list_nodeclaims(self) -> List[NodeClaim]:
        items, _ = self.server.list("nodeclaims")
        return [self.claim_from_envelope(o) for o in items]

    # the status-ish fields a controller OWNS when it writes launch
    # results / phase transitions back (the reference's status().Update
    # contract). Spec fields (requirements, nodePool, taints, ...) and
    # lifecycle metadata (deletionTimestamp, finalizers) are deliberately
    # NOT here: patching them from a stale typed claim would last-writer-
    # wins another controller's write (e.g. clear a concurrent delete's
    # deletionTimestamp). annotations/labels ARE here (launch stamps the
    # nodeclass drift hashes); distinct controllers own distinct KEYS,
    # and the server's RFC 7386 merge keeps per-key writes from
    # clobbering siblings.
    _CLAIM_STATUS_FIELDS = (
        "phase", "providerID", "internalIP", "instanceType", "zone",
        "capacityType", "imageID", "capacity", "allocatable", "labels",
        "annotations", "launchedAt", "registeredAt", "initializedAt",
    )

    def update_nodeclaim(self, claim: NodeClaim) -> None:
        """Status write-back (launch results, phase transitions): merge
        ONLY the caller-owned status fields over the stored object. Patch
        semantics — no RV precondition — because exactly one controller
        owns each status field; restricting the patch to those fields is
        what makes that contract safe under concurrency."""
        full = serde.nodeclaim_to_dict(claim)
        self.server.patch("nodeclaims", claim.name,
                          {k: full[k] for k in self._CLAIM_STATUS_FIELDS})

    def delete_nodeclaim(self, name: str, now: Optional[float] = None) -> None:
        """The k8s delete that STARTS the finalizer flow: stamps
        deletionTimestamp; the termination controller drains, deletes the
        instance, then clears the finalizer to remove the object."""
        self.server.delete("nodeclaims", name, now=now)

    def remove_nodeclaim_finalizer(self, name: str) -> None:
        """Termination complete: drop the finalizer (the object is removed
        if it was deleting)."""
        try:
            self.server.patch("nodeclaims", name, finalizers=())
        except NotFoundError:
            pass

    def delete_nodeclaim_now(self, name: str) -> None:
        """Hard delete bypassing the finalizer — rollback of a claim whose
        instance never launched."""
        self.server.delete("nodeclaims", name, force=True)

    def claims_by_provider_id(self, provider_id: str) -> List[NodeClaim]:
        return [self.claim_from_envelope(o)
                for o in self.server.get_by_index(
                    "nodeclaims", "providerID", provider_id)]

    # ---- nodepools / nodeclasses ------------------------------------------

    def create_nodepool(self, pool: NodePool) -> None:
        self.server.create("nodepools", serde.nodepool_to_dict(pool))

    def list_nodepools(self) -> List[NodePool]:
        items, _ = self.server.list("nodepools")
        # controller-owned live usage rides the envelope status sub-map
        return [serde.nodepool_apply_status(
                    serde.nodepool_from_dict(o["spec"]), o.get("status"))
                for o in items]

    def update_nodepool(self, pool: NodePool) -> None:
        self.server.patch("nodepools", pool.name, serde.nodepool_to_dict(pool))

    def delete_nodepool(self, name: str) -> None:
        self.server.delete("nodepools", name)

    def create_nodeclass(self, nc) -> None:
        self.server.create("nodeclasses", serde.nodeclass_to_dict(nc))

    def list_nodeclasses(self) -> List:
        items, _ = self.server.list("nodeclasses")
        return [serde.nodeclass_from_dict(o["spec"]) for o in items]

    def update_nodeclass(self, nc) -> None:
        self.server.patch("nodeclasses", nc.name, serde.nodeclass_to_dict(nc))

    # ---- volumes / pdbs / leases ------------------------------------------

    def create_pvc(self, pvc: PersistentVolumeClaim) -> None:
        self.server.create("pvcs", serde.pvc_to_dict(pvc))

    def patch_pvc(self, name: str, **spec_fields) -> None:
        self.server.patch("pvcs", name, spec_fields)

    def create_storage_class(self, sc: StorageClass) -> None:
        self.server.create("storageclasses", serde.storage_class_to_dict(sc))

    def create_pdb(self, pdb: PodDisruptionBudget) -> None:
        self.server.create("pdbs", serde.pdb_to_dict(pdb))

    def delete_pdb(self, name: str) -> None:
        self.server.delete("pdbs", name)

    def create_lease(self, lease: Lease) -> None:
        self.server.create("leases", serde.lease_to_dict(lease))

    def delete_lease(self, name: str) -> None:
        try:
            self.server.delete("leases", name)
        except NotFoundError:
            pass

    # ---- raw protocol ------------------------------------------------------

    def list_raw(self, kind: str) -> Tuple[List[dict], int]:
        return self.server.list(kind)

    def bulk(self, ops: Sequence[BulkOp]) -> List:
        """Raw batched apply (apiserver.bulk): many writes, one lock
        acquisition + admission sweep + watch flush per kind touched;
        per-op results/errors aligned with ``ops``."""
        return self.server.bulk(ops)

    def watch(self, kind: str, resource_version: int = 0) -> Watch:
        return self.server.watch(kind, resource_version)


def install_default_indexes(server: FakeAPIServer) -> None:
    """The manager's field indexes (reference operator.go:180-186 indexes
    NodeClaims on status.providerID for instance→claim lookups).
    Idempotent: double wiring (cli pre-serve + Operator) is a no-op."""
    if getattr(server, "_kpat_indexes_installed", False):
        return
    server._kpat_indexes_installed = True
    server.add_index("nodeclaims", "providerID",
                     lambda spec: spec.get("providerID"))
    server.add_index("pods", "nodeName", lambda spec: spec.get("nodeName"))


def install_admission(server: FakeAPIServer) -> None:
    """Wire the admission chain at the API boundary (reference
    pkg/webhooks/webhooks.go): defaults first, then SCHEMA validation
    (apis/schema.py — the machine-readable CRD contract, patterns/enums/
    cross-field rules), then the semantic webhooks. Nothing structurally
    or semantically invalid crosses the seam. Idempotent: double wiring
    (cli pre-serve + Operator) must not chain validators twice."""
    if getattr(server, "_kpat_admission_installed", False):
        return
    server._kpat_admission_installed = True
    from .. import webhooks
    from ..apis import schema

    def _np_default(spec: dict) -> dict:
        # schema-check BEFORE typed parsing: malformed input gets the
        # precise structural diagnostic, not a parse crash
        errs = schema.validate("nodepools", spec)
        if errs:
            from .apiserver import InvalidObjectError
            raise InvalidObjectError("nodepools",
                                     spec.get("name", "?"), errs)
        pool = serde.nodepool_from_dict(spec)
        webhooks.default_node_pool(pool)
        return serde.nodepool_to_dict(pool)

    def _np_validate(spec: dict) -> List[str]:
        # structural validation already ran in _np_default (before typed
        # parsing) and the spec only round-tripped serde since — running
        # the jsonschema pass again here would double the admission cost
        return webhooks.validate_node_pool(serde.nodepool_from_dict(spec))

    def _nc_validate(spec: dict) -> List[str]:
        errs = schema.validate("nodeclasses", spec)
        if errs:
            return errs
        return webhooks.validate_node_class(serde.nodeclass_from_dict(spec))

    def _claim_validate(spec: dict) -> List[str]:
        return schema.validate("nodeclaims", spec)

    def _pdb_validate(spec: dict) -> List[str]:
        return webhooks.validate_pdb(serde.pdb_from_dict(spec))

    server.register_admission("nodepools", validate=_np_validate,
                              default=_np_default)
    server.register_admission("nodeclasses", validate=_nc_validate)
    server.register_admission("nodeclaims", validate=_claim_validate)
    server.register_admission("pdbs", validate=_pdb_validate)
