"""In-memory Kubernetes-API-shaped object store: list/watch/create/update/
patch/delete over versioned wire objects.

This is the ingest boundary of the framework — the analog of the apiserver
the reference's controllers are wired against (reference
cmd/controller/main.go:47-53 builds core controllers over a client +
cluster state; pkg/operator/operator.go:92-186 builds the manager and its
field indexers; pkg/test/environment.go:83-162 drives the same protocol
from envtest in unit tests). Everything that crosses this seam is a plain
JSON-able dict in the apis/serde wire format wrapped in a k8s-style
envelope::

    {"kind": "Pod",
     "metadata": {"name", "uid", "resourceVersion", "creationTimestamp",
                  "deletionTimestamp", "finalizers"},
     "spec": <serde dict>}

Semantics mirrored from the real protocol:

- **resourceVersion**: one global monotonic counter; every write stamps
  the object and the emitted watch event. ``update`` requires the caller's
  metadata.resourceVersion to match the stored one (409 Conflict
  otherwise) — optimistic concurrency, exactly the reference's
  client-side retry contract.
- **watch**: per-kind subscriptions deliver ADDED/MODIFIED/DELETED events
  in RV order. Each kind keeps a bounded event history; a watch resuming
  from an RV older than the history raises ``TooOldError`` (the HTTP 410
  Gone that forces a reflector relist). Per-watcher queues are BOUNDED:
  a subscriber that overruns ``watch_queue_bound`` is dropped to the same
  410/relist path instead of growing an unbounded deque, and periodic
  BOOKMARK events carry the current RV so an idle watcher's resume point
  stays fresh (the real apiserver's allowWatchBookmarks contract).
- **finalizers**: ``delete`` on an object with finalizers only stamps
  deletionTimestamp (MODIFIED event); the object is removed when an
  update clears the last finalizer while deletionTimestamp is set — the
  reference's NodeClaim termination flow runs on exactly this contract.
- **subresources**: pods/binding (``bind``) and pods/eviction (``evict``,
  PDB-enforced server-side like the real Eviction API).
- **field indexers**: ``add_index``/``get_by_index`` mirror the manager's
  NodeClaim provider-id index (operator.go:180-186). Indexes are REAL
  inverted maps maintained on every write — a lookup touches only the
  matching names, never the whole store.
- **admission**: pluggable per-kind hooks run on create/update — the
  webhook seam (reference pkg/webhooks/webhooks.go) so invalid objects
  are rejected AT the boundary, not after ingestion.

Write-path scaling (the 100k-pod-churn design; docs/reference/watch.md):

- **Frozen envelopes, copy-on-read.** Every stored envelope is FROZEN at
  write time (``FrozenDict``/``FrozenList`` — dict/list subclasses whose
  mutators raise, so ``json.dumps`` still sees plain containers). Reads
  (``get``/``list``/``get_by_index``), watch delivery, and history replay
  all hand out the SAME shared object with zero copying; a consumer that
  needs a private mutable copy calls ``copy.deepcopy`` (deepcopy thaws).
  The isolation the old per-watcher deepcopy bought is now structural: a
  handler cannot corrupt siblings or history because it cannot mutate the
  envelope at all.
- **Per-kind store locks + lock-free RV allocation.** Each kind has its
  own re-entrant store lock (all registered under the ``api_server``
  contention name, so accounting aggregates); pods churn never convoys
  nodeclaim writes. RVs come from one atomic counter with per-kind
  high-water marks published under the kind lock — monotonic per kind
  without any cross-kind serialization. Nested cross-kind acquisition
  (evict's PDB read) always follows KINDS order.
- **Fan-out outside the lock.** ``_emit`` only appends the shared event
  to the history ring and a per-kind publish queue; the actual delivery
  to subscriber queues runs AFTER the store lock is released, under a
  per-kind combining flush — a slow watcher can never convoy writers,
  and per-kind RV delivery order is preserved (watcher queues dedup by
  RV, so a subscription replay racing the flusher stays exactly-once).
- **Batched writes.** ``bulk()`` applies many creates/patches/binds/
  evictions/deletes with ONE lock acquisition, one admission sweep, and
  one delivery flush per kind touched — per-object events and RVs, batch
  cost amortized (kube/writer.py ApiWriter routes a provisioning pass's
  pod binds and a drain's evictions through it).
"""

from __future__ import annotations

import copy
import gc as _gc
import itertools
import threading
from collections import deque
from dataclasses import dataclass
from typing import (Callable, Dict, List, Optional, Sequence, Set, Tuple,
                    Union)

from ..utils.clock import WALL

# kinds are plural lowercase, like REST resource paths
KINDS = ("pods", "nodes", "nodeclaims", "nodepools", "nodeclasses",
         "pvcs", "storageclasses", "pdbs", "leases", "events")

EVENT_HISTORY = 4096      # per-kind watch event ring; older RVs are "410 Gone"
WATCH_QUEUE_BOUND = 8192  # per-watcher queue bound; overrun -> 410/relist
BOOKMARK_EVERY = 256      # deliveries between per-watcher BOOKMARK events
BULK_CHUNK = 16           # max ops applied per bulk lock acquisition: a
                          # hold spans ~0.15 ms of interpreter time, so
                          # the window in which an OS-preempted holder
                          # can park waiters stays minimal — bulk wait
                          # tails then reflect handoff, not preemption
                          # luck (lock overhead per op is ~µs; the
                          # delivery flush still amortizes whole-batch)


class APIError(Exception):
    """Base of every apiserver error."""


class NotFoundError(APIError):
    pass


class AlreadyExistsError(APIError):
    pass


class ConflictError(APIError):
    """Stale resourceVersion on update (HTTP 409)."""


class TooOldError(APIError):
    """Watch RV fell off the event history, or a watcher overran its
    bounded queue (HTTP 410 Gone) — relist."""


class InvalidObjectError(APIError):
    """Admission rejected the object (HTTP 422); .causes lists reasons."""

    def __init__(self, kind: str, name: str, causes: Sequence[str]):
        super().__init__(f"{kind}/{name} rejected: " + "; ".join(causes))
        self.causes = list(causes)


class EvictionBlockedError(APIError):
    """A PodDisruptionBudget currently permits no eviction (HTTP 429)."""


# ---- frozen wire containers -------------------------------------------------


def _frozen_mutate(self, *a, **k):
    raise TypeError(
        "apiserver envelopes are frozen shared objects; copy.deepcopy() "
        "one to get a private mutable copy (deepcopy thaws)")


class FrozenDict(dict):
    """A read-only dict: every mutator raises. Still a ``dict`` subclass,
    so ``json.dumps`` and ``isinstance(..., dict)`` consumers see a plain
    mapping. ``copy.deepcopy`` THAWS — it returns an ordinary mutable
    deep copy — so the standard get→deepcopy→mutate→update flow works."""

    __slots__ = ()

    __setitem__ = _frozen_mutate
    __delitem__ = _frozen_mutate
    __ior__ = _frozen_mutate
    clear = _frozen_mutate
    pop = _frozen_mutate
    popitem = _frozen_mutate
    setdefault = _frozen_mutate
    update = _frozen_mutate

    def __deepcopy__(self, memo):
        return {k: copy.deepcopy(v, memo) for k, v in self.items()}

    def __reduce__(self):   # pickle as a plain dict
        return (dict, (dict(self),))


class FrozenList(list):
    """Read-only list counterpart of FrozenDict (same thaw-on-deepcopy
    contract). Concatenation with a plain list yields a plain list, so
    read-modify patterns like ``taints + [new]`` keep working."""

    __slots__ = ()

    __setitem__ = _frozen_mutate
    __delitem__ = _frozen_mutate
    __iadd__ = _frozen_mutate
    __imul__ = _frozen_mutate
    append = _frozen_mutate
    extend = _frozen_mutate
    insert = _frozen_mutate
    pop = _frozen_mutate
    remove = _frozen_mutate
    clear = _frozen_mutate
    sort = _frozen_mutate
    reverse = _frozen_mutate

    def __deepcopy__(self, memo):
        return [copy.deepcopy(v, memo) for v in self]

    def __reduce__(self):   # pickle as a plain list
        return (list, (list(self),))


def freeze(obj):
    """Recursively wrap dicts/lists in their frozen counterparts. The
    one canonical copy per RV every reader and watcher shares. Already-
    frozen subtrees SHORT-CIRCUIT: successive revisions of an object
    structurally share their unchanged immutable subtrees, so freezing
    a patched envelope walks only the changed spine, not the object."""
    t = type(obj)
    if t is FrozenDict or t is FrozenList:
        return obj   # canonical already — the whole subtree is immutable
    if isinstance(obj, dict):
        return FrozenDict((k, freeze(v)) for k, v in obj.items())
    if isinstance(obj, (list, tuple)):
        return FrozenList(freeze(v) for v in obj)
    return obj


def thaw(obj):
    """A private mutable deep copy of a (possibly frozen) envelope."""
    return copy.deepcopy(obj)


@dataclass
class WatchEvent:
    type: str          # ADDED | MODIFIED | DELETED | BOOKMARK
    kind: str
    object: dict       # the SHARED frozen envelope (immutable)
    resource_version: int


class Watch:
    """One watch subscription: a BOUNDED FIFO the server appends to.

    ``pop_pending()`` drains without blocking (the deterministic pump);
    ``get(timeout)`` blocks (the threaded reflector). ``stop()`` wakes
    blocked readers with a ``None`` sentinel. A subscriber that overruns
    ``bound`` queued events is dropped: its queue clears and every later
    read raises ``TooOldError`` — the informer relists, exactly like a
    410 on the wire. Duplicate deliveries (a subscription replay racing
    the fan-out flusher) are deduped by RV, which per-kind delivery
    order makes safe."""

    def __init__(self, kind: str, bound: int = WATCH_QUEUE_BOUND,
                 on_drop=None):
        self.kind = kind
        self.bound = bound
        self._on_drop = on_drop   # server-level cumulative drop counter
        self._events: deque = deque()
        # instrumented (introspect/contention.py): lock-wait on the
        # condition is fan-out contention; wait() time is accounted
        # separately as QUEUE wait (a parked watcher is not contention)
        from ..introspect import contention
        self._cond = contention.condition("watch_event")
        self._stopped = False
        self._overflowed = False
        self._last_rv = 0          # highest object RV pushed (dedup floor)
        self._since_bookmark = 0
        self.drops = 0             # events discarded at overflow
        self.bookmarks = 0
        self.max_depth = 0         # deepest this queue ever got (monotonic)

    def _push(self, ev: WatchEvent, replay: bool = False) -> bool:
        """Append the SHARED event object (no copy). Returns True when it
        was queued; False for duplicates, overflow, or a stopped watch.
        ``replay`` (subscription-time history hand-over) is exempt from
        the bound: the client asked for exactly that backlog and has not
        yet had a chance to consume — only live streaming can overrun."""
        with self._cond:
            if self._stopped or self._overflowed:
                return False
            if ev.type != "BOOKMARK" and ev.resource_version <= self._last_rv:
                return False   # replay/fan-out duplicate (dedup by RV)
            if not replay and len(self._events) >= self.bound:
                # overrun: drop this watcher to 410/relist instead of
                # growing without bound — thousands of slow watchers
                # must not amplify every MODIFIED into unbounded memory
                n = len(self._events) + 1
                self.drops += n
                self._events.clear()
                self._overflowed = True
                self._cond.notify_all()
                if self._on_drop is not None:
                    # the hub's cumulative counter: a dropped watcher
                    # unsubscribing must not erase the evidence
                    self._on_drop(n)
                return False
            self._events.append(ev)
            if len(self._events) > self.max_depth:
                self.max_depth = len(self._events)
            if ev.type != "BOOKMARK":
                self._last_rv = ev.resource_version
                self._since_bookmark += 1
            self._cond.notify_all()
            return True

    def _maybe_bookmark(self, every: int) -> bool:
        """Queue a BOOKMARK carrying the current RV once ``every`` real
        events have been delivered since the last one (fan-out flusher
        only). Keeps a resuming watcher's RV fresh without a relist."""
        with self._cond:
            if (self._stopped or self._overflowed or every <= 0
                    or self._since_bookmark < every):
                return False
            self._since_bookmark = 0
            self._events.append(WatchEvent(
                type="BOOKMARK", kind=self.kind,
                object=freeze({"kind": self.kind,
                               "metadata": {"resourceVersion": self._last_rv}}),
                resource_version=self._last_rv))
            if len(self._events) > self.max_depth:
                self.max_depth = len(self._events)
            self.bookmarks += 1
            self._cond.notify_all()
            return True

    def depth(self) -> int:
        """Queued (undelivered) events, read under the watch's own
        condition — the locked accessor stats() uses."""
        with self._cond:
            return len(self._events)

    def _check_overflow(self) -> None:
        if self._overflowed:
            raise TooOldError(
                f"{self.kind}: watcher overran its {self.bound}-event "
                f"queue bound; relist")

    def pop_pending(self) -> List[WatchEvent]:
        with self._cond:
            self._check_overflow()
            out = list(self._events)
            self._events.clear()
            return out

    def get(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        with self._cond:
            self._check_overflow()
            if not self._events and not self._stopped:
                self._cond.wait(timeout)
                self._check_overflow()
            if self._events:
                return self._events.popleft()
            return None

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()


class _DeferGC:
    """Defer automatic garbage collection across a critical section.

    A gen-2 collection landing while a store lock is held convoys every
    writer of that kind — and with JAX's gc callback installed a full
    collection costs hundreds of ms (the soak's owner-at-contention tags
    caught ``_xla_gc_callback`` holding the api_server lock for >1 s).
    Depth-counted and process-wide: collection is re-enabled (and runs,
    if due) at the outermost exit, so the pause lands OUTSIDE the lock.
    A no-op when the embedding process already disabled gc itself."""

    _lock = threading.Lock()
    _depth = 0
    _we_disabled = False

    def __enter__(self):
        cls = _DeferGC
        with cls._lock:
            if cls._depth == 0 and _gc.isenabled():
                _gc.disable()
                cls._we_disabled = True
            cls._depth += 1
        return self

    def __exit__(self, *exc):
        cls = _DeferGC
        with cls._lock:
            cls._depth -= 1
            if cls._depth == 0 and cls._we_disabled:
                cls._we_disabled = False
                _gc.enable()
        return False


# one bulk operation: ("create", kind, spec[, finalizers]) |
# ("update", kind, envelope) | ("patch", kind, name, spec_patch[, status,
# finalizers]) | ("bind", pod, node) | ("evict", pod[, force]) |
# ("delete", kind, name[, force])
BulkOp = Tuple


class FakeAPIServer:
    def __init__(self, clock=None, watch_queue_bound: int = WATCH_QUEUE_BOUND,
                 bookmark_every: int = BOOKMARK_EVERY):
        """``clock`` (utils.clock.Clock-like) stamps server-side times —
        deletionTimestamp on finalizer-gated deletes, like the real
        apiserver stamps deletion times itself. Defaults to wall clock."""
        self._clock = clock
        self.watch_queue_bound = watch_queue_bound
        self.bookmark_every = bookmark_every
        # per-kind store locks (introspect/contention.py): ALL registered
        # under the one "api_server" name so contention accounting
        # aggregates across the decomposition — `kpctl top` CONTENTION
        # still reports the hub as one lock, now without the old
        # every-verb convoy
        from ..introspect import contention
        self._locks = {k: contention.rlock("api_server") for k in KINDS}
        # lock-free RV allocator: next() on itertools.count is atomic
        # under the GIL; per-kind high-water marks publish under the
        # kind lock (monotonic per kind — the watch contract's unit)
        self._rv = itertools.count(1)
        self._kind_rv: Dict[str, int] = {k: 0 for k in KINDS}
        self._store: Dict[str, Dict[str, dict]] = {k: {} for k in KINDS}
        self._history: Dict[str, deque] = {
            k: deque(maxlen=EVENT_HISTORY) for k in KINDS}
        self._watches: Dict[str, List[Watch]] = {k: [] for k in KINDS}
        # fan-out outside the store lock: writers append events here
        # (under the kind lock), then one combining flusher per kind
        # delivers to subscriber queues with no store lock held
        self._pub: Dict[str, deque] = {k: deque() for k in KINDS}
        self._pub_mutex: Dict[str, threading.Lock] = {
            k: threading.Lock() for k in KINDS}
        self._deliver = {k: contention.lock("api_fanout") for k in KINDS}
        # field indexes: key_fn registry + REAL inverted maps
        # ((kind, index) -> value -> {names}; name -> value for removal)
        self._indexes: Dict[Tuple[str, str], Callable[[dict], Optional[str]]] = {}
        self._index_maps: Dict[Tuple[str, str], Dict[str, Set[str]]] = {}
        self._index_keys: Dict[Tuple[str, str], Dict[str, str]] = {}
        self._kind_indexes: Dict[str, List[str]] = {}
        self._admission: Dict[str, List[Callable[[dict], List[str]]]] = {}
        self._defaulters: Dict[str, List[Callable[[dict], dict]]] = {}
        self._uid = itertools.count(1)
        # per-kind fan-out counters, each written ONLY by that kind's
        # (single, combining) flusher — cross-kind flushes never race a
        # shared "+=" (a lost increment would silently undercount the
        # karpenter_api_* series); the totals are summed properties
        self._kind_delivered: Dict[str, int] = {k: 0 for k in KINDS}
        self._kind_bookmarks: Dict[str, int] = {k: 0 for k in KINDS}
        self._kind_drops: Dict[str, int] = {k: 0 for k in KINDS}
        self._bulk_count_lock = threading.Lock()
        self.bulk_calls = 0
        self.bulk_ops = 0
        # per-watcher envelope copies made on the fan-out path. The new
        # delivery design shares ONE frozen object, so this stays 0 by
        # construction — the bench writepath row records it as the
        # no-copy pin (a reintroduced copy must increment it)
        self.fanout_envelope_copies = 0
        # process-monotonic watch-queue high water: folded from each
        # watch's own max_depth when it unsubscribes, so stats() never
        # regresses when a deep (or dropped) watcher goes away — the
        # headroom registry's monotonic-high-water contract
        self._watch_hw = 0
        # the PDB math's namespace index (policy/v1 allowance is computed
        # over one namespace's pods, never a full-store scan)
        self.add_index("pods", "namespace",
                       lambda spec: spec.get("namespace", "default"))

    @property
    def events_emitted(self) -> int:
        """Watch fan-out deliveries pushed, total (sum of the per-kind
        flusher counters)."""
        return sum(self._kind_delivered.values())

    @property
    def bookmarks_sent(self) -> int:
        return sum(self._kind_bookmarks.values())

    @property
    def watch_drops(self) -> int:
        """Cumulative events discarded dropping overrun watchers —
        survives the dropped watcher's unsubscribe/relist."""
        return sum(self._kind_drops.values())

    @property
    def last_rv(self) -> int:
        """Global high-water RV: max over the per-kind marks (each is
        only advanced under its kind's lock, so this never regresses)."""
        return max(self._kind_rv.values())

    def stats(self) -> Dict[str, int]:
        """Introspection snapshot of the watch hub: subscriber fan-out,
        queued (undelivered) events via the LOCKED per-watch depth
        accessor, store occupancy, write sequence, bulk/bookmark/drop
        counters. Takes no store lock — a stats poll can never convoy a
        writer."""
        watchers = 0
        queued = 0
        # seeded from the unsubscribe fold: watch_max_depth is monotonic
        # per process, not "max over watchers that happen to be alive"
        max_depth = self._watch_hw
        deepest = 0
        for ws in self._watches.values():
            for w in tuple(ws):
                watchers += 1
                d = w.depth()
                queued += d
                if d > deepest:
                    deepest = d
                if w.max_depth > max_depth:
                    max_depth = w.max_depth
        objects = sum(len(s) for s in self._store.values())
        return {"watchers": watchers, "watch_queue_depth": queued,
                "watch_deepest": deepest,
                "watch_max_depth": max_depth,
                "watch_drops": self.watch_drops,
                "bookmarks": self.bookmarks_sent,
                "objects": objects, "events_emitted": self.events_emitted,
                "bulk_calls": self.bulk_calls, "bulk_ops": self.bulk_ops,
                "fanout_envelope_copies": self.fanout_envelope_copies,
                "last_rv": self.last_rv}

    # ---- headroom probes (introspect/headroom.py) --------------------------

    def headroom_probe(self) -> Dict[str, float]:
        """Per-watcher queue saturation: depth = the DEEPEST live queue
        (first watcher to hit the bound 410s regardless of the others),
        capacity = the shared bound, drops/high-water = the cumulative
        hub counters that survive a dropped watcher's unsubscribe."""
        deepest = 0
        hw = self._watch_hw
        for ws in self._watches.values():
            for w in tuple(ws):
                d = w.depth()
                if d > deepest:
                    deepest = d
                if w.max_depth > hw:
                    hw = w.max_depth
        return {"depth": float(deepest),
                "capacity": float(self.watch_queue_bound),
                "highwater": float(hw),
                "drops": float(self.watch_drops)}

    def headroom_probe_publish(self) -> Dict[str, float]:
        """Fan-out publish backlog: events appended by writers but not
        yet delivered by the combining flushers. Unbounded (capacity 0)
        — the forecast watches the fill rate, not an occupancy."""
        return {"depth": float(sum(len(q) for q in self._pub.values())),
                "capacity": 0.0}

    # ---- admission (webhook seam) -----------------------------------------

    def register_admission(self, kind: str,
                           validate: Optional[Callable[[dict], List[str]]] = None,
                           default: Optional[Callable[[dict], dict]] = None) -> None:
        """Install a validating and/or defaulting hook for a kind. The
        validator sees the SPEC wire dict and returns error strings
        (empty = admitted); the defaulter returns the (possibly mutated)
        spec. Mirrors the reference's knative-style admission chain."""
        if validate is not None:
            self._admission.setdefault(kind, []).append(validate)
        if default is not None:
            self._defaulters.setdefault(kind, []).append(default)

    def _admit(self, kind: str, name: str, spec: dict) -> dict:
        for d in self._defaulters.get(kind, ()):
            try:
                spec = d(spec)
            except InvalidObjectError:
                raise   # a defaulter's own precise rejection passes through
            except Exception as e:
                # a defaulter crashing on input the schema would have
                # rejected must still surface as an admission rejection
                # (callers only handle InvalidObjectError); the message
                # class distinguishes defaulter bugs from bad input
                raise InvalidObjectError(
                    kind, name, [f"defaulting failed: {e}"])
        causes: List[str] = []
        for v in self._admission.get(kind, ()):
            causes.extend(v(spec))
        if causes:
            raise InvalidObjectError(kind, name, causes)
        return spec

    # ---- store + index maintenance (caller holds the kind lock) -----------

    def _check_kind(self, kind: str) -> None:
        if kind not in self._store:
            raise APIError(f"unknown kind {kind!r}")

    def _index_put(self, kind: str, name: str, spec: dict) -> None:
        for idx in self._kind_indexes.get(kind, ()):
            key_fn = self._indexes[(kind, idx)]
            keys = self._index_keys[(kind, idx)]
            fwd = self._index_maps[(kind, idx)]
            try:
                new = key_fn(spec)
            except Exception:
                new = None   # a broken key_fn must not fail the write
            old = keys.get(name)
            if old == new:
                continue
            if old is not None:
                bucket = fwd.get(old)
                if bucket is not None:
                    bucket.discard(name)
                    if not bucket:
                        del fwd[old]
            if new is not None:
                fwd.setdefault(new, set()).add(name)
                keys[name] = new
            else:
                keys.pop(name, None)

    def _index_del(self, kind: str, name: str) -> None:
        for idx in self._kind_indexes.get(kind, ()):
            keys = self._index_keys[(kind, idx)]
            old = keys.pop(name, None)
            if old is not None:
                fwd = self._index_maps[(kind, idx)]
                bucket = fwd.get(old)
                if bucket is not None:
                    bucket.discard(name)
                    if not bucket:
                        del fwd[old]

    def _store_put(self, kind: str, name: str, obj: dict) -> None:
        self._store[kind][name] = obj
        self._index_put(kind, name, obj["spec"])

    def _store_del(self, kind: str, name: str) -> None:
        del self._store[kind][name]
        self._index_del(kind, name)

    @staticmethod
    def _spine(cur: dict) -> dict:
        """Mutable SHALLOW working copy of a frozen envelope: plain
        top-level/metadata/spec dicts whose values still reference the
        shared immutable subtrees. Because nothing frozen is ever
        mutated in place, revisions may structurally share unchanged
        children — a patch pays O(changed spine), not O(object), inside
        the store lock (thaw() stays for callers that need a fully
        private copy)."""
        return {"kind": cur["kind"],
                "metadata": dict(cur["metadata"]),
                "spec": dict(cur["spec"]),
                "status": cur.get("status") or {}}

    # ---- watch fan-out (publish queue + combining flusher) ----------------

    def _emit(self, type_: str, kind: str, obj: dict) -> None:
        """Record the event (caller holds the kind lock): ONE shared
        frozen event object goes to the history ring and the publish
        queue. No subscriber work happens here — delivery runs in
        ``_flush`` after the store lock is released."""
        ev = WatchEvent(type=type_, kind=kind, object=obj,
                        resource_version=obj["metadata"]["resourceVersion"])
        self._history[kind].append(ev)
        self._pub[kind].append(ev)

    def _flush(self, kind: str) -> None:
        """Deliver queued events to every subscriber, OUTSIDE the store
        lock. A combining flush: one thread drains at a time (per-kind
        delivery stays in RV order); a writer that loses the non-blocking
        acquire returns immediately — the active flusher re-checks the
        queue after releasing, so no event is stranded."""
        pub = self._pub[kind]
        mtx = self._pub_mutex[kind]
        dlv = self._deliver[kind]
        while True:
            if not pub:
                return
            if not dlv.acquire(blocking=False):
                return   # active flusher will observe our events
            try:
                while True:
                    with mtx:
                        if not pub:
                            break
                        # drain by popleft, NEVER list()+clear(): writers
                        # append under the STORE lock (not this mutex),
                        # so an append landing between a snapshot and a
                        # clear would be discarded undelivered — a lost
                        # DELETE the mirror never heals from (the
                        # SOAK_r08 agreement check caught exactly this)
                        batch = []
                        while pub:
                            batch.append(pub.popleft())
                        watchers = tuple(self._watches[kind])
                    delivered = 0
                    for ev in batch:
                        for w in watchers:
                            if w._push(ev):
                                delivered += 1
                    self._kind_delivered[kind] += delivered
                    if self.bookmark_every > 0:
                        for w in watchers:
                            if w._maybe_bookmark(self.bookmark_every):
                                self._kind_bookmarks[kind] += 1
            finally:
                dlv.release()
            # closing the missed-wakeup window: an append that raced our
            # release is drained by looping (its own flush attempt may
            # have lost the non-blocking acquire to us)
            with mtx:
                if not pub:
                    return

    def _next_rv(self, kind: str) -> int:
        rv = next(self._rv)         # lock-free allocation
        self._kind_rv[kind] = rv    # published under the kind lock
        return rv

    # ---- core verbs --------------------------------------------------------
    # Every public verb is: kind lock -> _<verb>_locked -> flush. The
    # _locked internals are shared with bulk(), which holds each kind's
    # lock ONCE for a whole batch.

    def create(self, kind: str, spec: dict, *,
               finalizers: Sequence[str] = ()) -> dict:
        """Create an object from its serde spec; returns the (frozen)
        envelope — deepcopy it for a mutable private copy. Admission and
        the envelope build run BEFORE the store lock (_prebuild): a slow
        validator (jsonschema on nodeclaims/nodepools) must never hold
        the kind's writers up."""
        self._check_kind(kind)
        env = self._prebuild(kind, spec, finalizers)
        with _DeferGC(), self._locks[kind]:
            obj = self._create_locked(kind, env)
        self._flush(kind)
        return obj

    def _prebuild(self, kind: str, spec: dict,
                  finalizers: Sequence[str] = ()) -> dict:
        """Admission + the whole envelope build, OUTSIDE any store lock:
        returns a plain-spine envelope (frozen leaves) with a
        placeholder RV. ``_create_locked`` stamps the real RV and
        installs it — the locked phase of a create is dup-check + RV +
        store/index put + emit, nothing O(object)."""
        name = spec.get("name")
        if not name:
            raise APIError(f"{kind}: spec has no name")
        spec = freeze(self._admit(kind, name, thaw(spec)))
        return {
            "kind": kind,
            "metadata": {
                "name": name,
                "uid": f"uid-{next(self._uid):06d}",
                "resourceVersion": 0,   # stamped under the kind lock
                # stamped when a clock is wired (live mode); None in
                # clock-free tests, where RV orders events
                "creationTimestamp": (self._clock.now()
                                      if self._clock else None),
                "deletionTimestamp": None,
                "finalizers": list(finalizers),
            },
            "spec": spec,
            # controller-owned status sub-map (the k8s spec/status
            # split): written only via patch(status_patch=...), and
            # PRESERVED across user spec updates — `kpctl get -o yaml
            # | kpctl apply` can never re-submit stale status
            "status": {},
        }

    def _create_locked(self, kind: str, env: dict) -> dict:
        """Install a ``_prebuild`` envelope (caller holds the kind
        lock): dup-check, stamp the RV, store, emit."""
        name = env["metadata"]["name"]
        if name in self._store[kind]:
            raise AlreadyExistsError(f"{kind}/{name} already exists")
        env["metadata"]["resourceVersion"] = self._next_rv(kind)
        obj = freeze(env)   # spine walk only: the leaves froze outside
        self._store_put(kind, name, obj)
        self._emit("ADDED", kind, obj)
        return obj

    def get(self, kind: str, name: str) -> dict:
        """Returns the FROZEN stored envelope (zero-copy shared read);
        ``copy.deepcopy`` it before mutating (deepcopy thaws)."""
        self._check_kind(kind)
        with self._locks[kind]:
            obj = self._store[kind].get(name)
            if obj is None:
                raise NotFoundError(f"{kind}/{name} not found")
            return obj

    def now(self) -> float:
        """The server's clock reading — the timebase every timestamp the
        server stamps (creationTimestamp, deletionTimestamp, event times)
        lives on. Clients rendering ages must anchor to THIS, not their
        own wall clock: under a FakeClock (or plain clock skew) the two
        can differ arbitrarily."""
        return (self._clock.now() if self._clock is not None
                else WALL.now())

    def list(self, kind: str) -> Tuple[List[dict], int]:
        """Returns (items, listResourceVersion) — watch from the returned
        RV to observe every later change exactly once. Items are the
        frozen stored envelopes (no per-item copies: the old O(store)
        deepcopy per list is gone)."""
        self._check_kind(kind)
        with self._locks[kind]:
            return list(self._store[kind].values()), self.last_rv

    def update(self, kind: str, obj: dict) -> dict:
        """Full-object update with optimistic concurrency: the caller's
        metadata.resourceVersion must match the stored object's. The
        envelope's ``status`` sub-map is controller-owned and EXCLUDED
        from the write — the stored status survives a user apply
        verbatim (spec/status split; write status via
        ``patch(status_patch=...)``). Admission runs BEFORE the store
        lock — the caller's spec does not depend on stored state."""
        self._check_kind(kind)
        name = obj["metadata"]["name"]
        spec = freeze(self._admit(kind, name, thaw(obj["spec"])))
        with _DeferGC(), self._locks[kind]:
            new = self._update_locked(kind, obj, pre_spec=spec)
        self._flush(kind)
        return new

    def _update_locked(self, kind: str, obj: dict,
                       pre_spec: Optional[dict] = None) -> dict:
        name = obj["metadata"]["name"]
        cur = self._store[kind].get(name)
        if cur is None:
            raise NotFoundError(f"{kind}/{name} not found")
        if obj["metadata"]["resourceVersion"] != cur["metadata"]["resourceVersion"]:
            raise ConflictError(
                f"{kind}/{name}: stale resourceVersion "
                f"{obj['metadata']['resourceVersion']} "
                f"(current {cur['metadata']['resourceVersion']})")
        spec = (pre_spec if pre_spec is not None
                else self._admit(kind, name, thaw(obj["spec"])))
        new = self._spine(cur)
        new["spec"] = spec
        new["metadata"]["finalizers"] = list(obj["metadata"].get("finalizers", ()))
        new["metadata"]["resourceVersion"] = self._next_rv(kind)
        new = freeze(new)
        # clearing the last finalizer of a deleting object removes it
        if (new["metadata"]["deletionTimestamp"] is not None
                and not new["metadata"]["finalizers"]):
            self._store_del(kind, name)
            self._emit("DELETED", kind, new)
        else:
            self._store_put(kind, name, new)
            self._emit("MODIFIED", kind, new)
        return new

    @staticmethod
    def _merge_value(target: dict, k: str, v) -> None:
        """RFC 7386 JSON merge patch for one key: ``None`` deletes, maps
        merge RECURSIVELY (so writers of disjoint annotation/label keys
        never clobber each other's entries), everything else replaces."""
        if v is None:
            target.pop(k, None)
        elif isinstance(v, dict):
            # RFC 7386 §2: a non-object (or missing) target counts as {},
            # so deletion markers inside the patch vanish instead of
            # being stored verbatim as None values — status patches skip
            # admission and would otherwise persist them
            base = target.get(k)
            sub = dict(base) if isinstance(base, dict) else {}
            for sk, sv in v.items():
                FakeAPIServer._merge_value(sub, sk, sv)
            target[k] = sub
        else:
            target[k] = copy.deepcopy(v)

    def patch(self, kind: str, name: str, spec_patch: Optional[dict] = None, *,
              status_patch: Optional[dict] = None,
              finalizers: Optional[Sequence[str]] = None) -> dict:
        """JSON-merge-patch on the spec (RFC 7386: ``None`` values delete
        keys, nested maps merge per-key), the controller-owned envelope
        ``status`` sub-map, and/or replace the finalizer list. No RV
        precondition — a patch applies to whatever is current, like a
        server-side strategic merge. Status patches skip spec admission:
        they never contain user intent.

        For kinds WITH admission hooks (nodeclaims, nodepools, ...), the
        merged spec is validated OPTIMISTICALLY outside the store lock:
        snapshot the current spec+RV, merge+admit unlocked, then apply
        under the lock only if the RV is still current — a racing writer
        re-runs the merge (bounded retries, falling back to the locked
        path). A nodeclaim status write's jsonschema pass must never
        hold up the kind's other writers."""
        self._check_kind(kind)
        if spec_patch and (self._admission.get(kind)
                           or self._defaulters.get(kind)):
            for _ in range(4):
                with self._locks[kind]:
                    cur = self._store[kind].get(name)
                    if cur is None:
                        raise NotFoundError(f"{kind}/{name} not found")
                    base_rv = cur["metadata"]["resourceVersion"]
                    base_spec = cur["spec"]
                merged = dict(base_spec)
                for k, v in spec_patch.items():
                    self._merge_value(merged, k, v)
                admitted = freeze(self._admit(kind, name, merged))
                with _DeferGC(), self._locks[kind]:
                    cur = self._store[kind].get(name)
                    if cur is None:
                        raise NotFoundError(f"{kind}/{name} not found")
                    if cur["metadata"]["resourceVersion"] != base_rv:
                        continue   # racing writer landed: re-merge
                    new = self._patch_locked(
                        kind, name, None, status_patch=status_patch,
                        finalizers=finalizers, pre_spec=admitted)
                self._flush(kind)
                return new
            # contended object: give up optimism, admit under the lock
        with _DeferGC(), self._locks[kind]:
            new = self._patch_locked(kind, name, spec_patch,
                                     status_patch=status_patch,
                                     finalizers=finalizers)
        self._flush(kind)
        return new

    def _patch_locked(self, kind: str, name: str,
                      spec_patch: Optional[dict] = None, *,
                      status_patch: Optional[dict] = None,
                      finalizers: Optional[Sequence[str]] = None,
                      pre_spec: Optional[dict] = None) -> dict:
        cur = self._store[kind].get(name)
        if cur is None:
            raise NotFoundError(f"{kind}/{name} not found")
        # structural sharing: only the changed spine is copied inside
        # the lock (_merge_value's recursion already builds fresh
        # sub-dicts for the keys it touches; untouched subtrees stay
        # the shared frozen objects)
        new = self._spine(cur)
        if pre_spec is not None:
            # merged + admitted outside the lock (the public patch
            # verb's optimistic path); the caller proved the base RV is
            # still current before handing it in
            new["spec"] = pre_spec
        elif spec_patch:
            for k, v in spec_patch.items():
                self._merge_value(new["spec"], k, v)
            new["spec"] = self._admit(kind, name, new["spec"])
        if status_patch:
            status = dict(new["status"])
            for k, v in status_patch.items():
                self._merge_value(status, k, v)
            new["status"] = status
        if finalizers is not None:
            new["metadata"]["finalizers"] = list(finalizers)
        new["metadata"]["resourceVersion"] = self._next_rv(kind)
        new = freeze(new)
        if (new["metadata"]["deletionTimestamp"] is not None
                and not new["metadata"]["finalizers"]):
            self._store_del(kind, name)
            self._emit("DELETED", kind, new)
        else:
            self._store_put(kind, name, new)
            self._emit("MODIFIED", kind, new)
        return new

    def delete(self, kind: str, name: str, *, now: Optional[float] = None,
               force: bool = False) -> None:
        """Delete an object. With finalizers present (and not ``force``),
        only stamps deletionTimestamp — the finalizing controller removes
        the object later by clearing the finalizer list."""
        self._check_kind(kind)
        with _DeferGC(), self._locks[kind]:
            self._delete_locked(kind, name, now=now, force=force)
        self._flush(kind)

    def _delete_locked(self, kind: str, name: str, *,
                       now: Optional[float] = None,
                       force: bool = False) -> None:
        cur = self._store[kind].get(name)
        if cur is None:
            raise NotFoundError(f"{kind}/{name} not found")
        if cur["metadata"]["finalizers"] and not force:
            if cur["metadata"]["deletionTimestamp"] is None:
                new = self._spine(cur)
                # the server stamps deletion time itself when the
                # caller didn't; never 0.0/falsy — every downstream
                # consumer truth-tests deletion_timestamp
                if now is None:
                    now = (self._clock.now() if self._clock is not None
                           else WALL.now())
                new["metadata"]["deletionTimestamp"] = now or 1e-9
                new["metadata"]["resourceVersion"] = self._next_rv(kind)
                new = freeze(new)
                self._store_put(kind, name, new)
                self._emit("MODIFIED", kind, new)
            return
        gone = self._spine(cur)
        gone["metadata"]["resourceVersion"] = self._next_rv(kind)
        gone = freeze(gone)
        self._store_del(kind, name)
        self._emit("DELETED", kind, gone)

    # ---- batched apply -----------------------------------------------------

    def bulk(self, ops: Sequence[BulkOp]) -> List[Union[dict, None, APIError]]:
        """Apply many write operations with one out-of-lock admission
        sweep (creates and updates — a patch's merged spec depends on
        stored state, so hook-bearing kinds admit patches under the
        lock here; use the single ``patch`` verb for its optimistic
        out-of-lock validation when that matters), bounded amortized
        lock holds (≤ ``BULK_CHUNK`` ops per acquisition — a
        thousand-pod wave never pins a kind's other writers for the
        whole batch), and one delivery flush per kind touched — the
        write-coalescing verb (kube/writer.py ApiWriter batches a
        provisioning pass's binds and a drain's evictions through it).

        Op shapes (tuples)::

            ("create", kind, spec[, finalizers])
            ("update", kind, envelope)
            ("patch",  kind, name, spec_patch[, status_patch, finalizers])
            ("bind",   pod_name, node_name)
            ("evict",  pod_name[, force])
            ("delete", kind, name[, force])

        Ops GROUP BY KIND (bind/evict are pods): relative order within a
        kind is preserved — the per-kind linearizability unit — while
        cross-kind order inside one bulk is unspecified. Per-op failures
        are CAPTURED: the result list aligns with ``ops`` and holds the
        envelope (None for delete) or the APIError instance, so one
        conflict never aborts the rest of the batch."""
        results: List[Union[dict, None, APIError]] = [None] * len(ops)
        by_kind: Dict[str, List[int]] = {}
        prepared: Dict[int, dict] = {}
        for i, op in enumerate(ops):
            verb = op[0]
            kind = "pods" if verb in ("bind", "evict") else op[1]
            self._check_kind(kind)
            if verb == "create":
                # the admission sweep + whole envelope build run HERE,
                # outside any store lock — the locked phase of a bulk
                # create is dup-check + RV stamp + store put + emit
                try:
                    prepared[i] = self._prebuild(
                        kind, op[2], op[3] if len(op) > 3 else ())
                except APIError as e:
                    results[i] = e
                    continue
            elif verb == "update":
                # an update's spec does not depend on stored state:
                # admit it out of the lock like the single verb does
                try:
                    prepared[i] = freeze(self._admit(
                        kind, op[2]["metadata"]["name"],
                        thaw(op[2]["spec"])))
                except APIError as e:
                    results[i] = e
                    continue
            by_kind.setdefault(kind, []).append(i)
        with self._bulk_count_lock:
            self.bulk_calls += 1
            self.bulk_ops += len(ops)
        for kind, idxs in by_kind.items():
            # bounded lock holds: at most BULK_CHUNK ops per acquisition
            # (a thousand-pod wave must not pin the kind's other writers
            # for the whole batch), gc deferred for each held span so a
            # due collection runs after release instead of inside it.
            # Per-kind op order is preserved across chunks; ONE delivery
            # flush still covers the whole batch.
            for lo in range(0, len(idxs), BULK_CHUNK):
                chunk = idxs[lo:lo + BULK_CHUNK]
                with _DeferGC(), self._locks[kind]:
                    self._bulk_apply_locked(ops, chunk, prepared, results)
            self._flush(kind)
        return results

    def _bulk_apply_locked(self, ops, idxs, prepared, results) -> None:
        for i in idxs:
            op = ops[i]
            verb = op[0]
            try:
                if verb == "create":
                    results[i] = self._create_locked(op[1], prepared[i])
                elif verb == "update":
                    results[i] = self._update_locked(
                        op[1], op[2], pre_spec=prepared[i])
                elif verb == "patch":
                    results[i] = self._patch_locked(
                        op[1], op[2], op[3],
                        status_patch=op[4] if len(op) > 4 else None,
                        finalizers=op[5] if len(op) > 5 else None)
                elif verb == "bind":
                    results[i] = self._bind_locked(op[1], op[2])
                elif verb == "evict":
                    results[i] = self._evict_locked(
                        op[1], force=bool(op[2]) if len(op) > 2
                        else False)
                elif verb == "delete":
                    self._delete_locked(
                        op[1], op[2],
                        force=bool(op[3]) if len(op) > 3 else False)
                    results[i] = None
                else:
                    raise APIError(f"unknown bulk verb {verb!r}")
            except APIError as e:
                results[i] = e

    # ---- watch -------------------------------------------------------------

    def watch(self, kind: str, resource_version: int = 0) -> Watch:
        """Subscribe from ``resource_version`` (exclusive). Events already
        past that RV replay from the history ring (the SAME shared event
        objects — replay copies nothing); an RV older than the ring
        raises TooOldError (relist, like a 410 Gone)."""
        self._check_kind(kind)
        with self._locks[kind]:
            hist = self._history[kind]
            # a full ring has dropped events (all with RV < hist[0]'s);
            # resuming below that horizon can't replay them — 410 Gone.
            # A non-full ring still holds the kind's entire lifetime, so
            # any RV (including 0) is safe.
            if (len(hist) == hist.maxlen
                    and resource_version < hist[0].resource_version - 1):
                raise TooOldError(
                    f"{kind}: watch from rv={resource_version} too old "
                    f"(history starts at {hist[0].resource_version})")
            def _note_drop(n: int, _k: str = kind) -> None:
                # called from the kind's single flusher thread only
                self._kind_drops[_k] += n

            w = Watch(kind, bound=self.watch_queue_bound,
                      on_drop=_note_drop)
            for ev in hist:
                if ev.resource_version > resource_version:
                    # shared frozen event — zero-copy replay, exempt from
                    # the bound (the caller asked for this backlog)
                    w._push(ev, replay=True)
            with self._pub_mutex[kind]:
                self._watches[kind].append(w)
            return w

    def stop_watch(self, w: Watch) -> None:
        with self._pub_mutex[w.kind]:
            if w in self._watches[w.kind]:
                self._watches[w.kind].remove(w)
            if w.max_depth > self._watch_hw:
                self._watch_hw = w.max_depth
        w.stop()

    # ---- subresources ------------------------------------------------------

    def bind(self, pod_name: str, node_name: str) -> dict:
        """pods/binding: set spec.nodeName on an unbound pod."""
        with _DeferGC(), self._locks["pods"]:
            out = self._bind_locked(pod_name, node_name)
        self._flush("pods")
        return out

    def _bind_locked(self, pod_name: str, node_name: str) -> dict:
        cur = self._store["pods"].get(pod_name)
        if cur is None:
            raise NotFoundError(f"pods/{pod_name} not found")
        if cur["spec"].get("nodeName"):
            raise ConflictError(
                f"pod {pod_name} already bound to {cur['spec']['nodeName']}")
        return self._patch_locked("pods", pod_name, {"nodeName": node_name})

    def _pdb_allowance(self, pdb_spec: dict) -> int:
        """Server-side disruptions-allowed math (policy/v1): healthy =
        bound matching pods without deletionTimestamp. Caller holds the
        pods lock. Matching pods come from the NAMESPACE inverted index
        — allowance is O(pods in the namespace), so an ApiWriter drain
        is no longer O(total pods) per eviction."""
        sel = pdb_spec.get("labelSelector", {})
        ns = pdb_spec.get("namespace", "default")
        ns_names = self._index_maps[("pods", "namespace")].get(ns, ())
        store = self._store["pods"]
        matching = []
        for name in ns_names:
            obj = store[name]
            s = obj["spec"]
            if s.get("isDaemonset"):
                continue
            if all(s.get("labels", {}).get(k) == v for k, v in sel.items()):
                matching.append(obj)
        healthy = sum(1 for o in matching
                      if o["spec"].get("nodeName")
                      and o["metadata"]["deletionTimestamp"] is None
                      # pods carry deletion state in SPEC too (our pods
                      # have no finalizers, so a draining pod is marked
                      # at the spec level — state/cluster.py:204 uses the
                      # same representation for healthy math)
                      and o["spec"].get("deletionTimestamp") is None)
        allowed = len(matching)
        if pdb_spec.get("minAvailable") is not None:
            allowed = min(allowed, healthy - int(pdb_spec["minAvailable"]))
        if pdb_spec.get("maxUnavailable") is not None:
            unavailable = len(matching) - healthy
            allowed = min(allowed, int(pdb_spec["maxUnavailable"]) - unavailable)
        return max(allowed, 0)

    def evict(self, pod_name: str, *, force: bool = False) -> dict:
        """pods/eviction: unbind the pod (the workload controller instantly
        re-creates it pending in this simulation, so eviction == unbind).
        PDBs are enforced HERE, server-side, exactly like the real
        Eviction API; ``force`` models a grace-zero pod delete that
        bypasses budgets (the reference's force-drain backstop)."""
        with _DeferGC(), self._locks["pods"]:
            out = self._evict_locked(pod_name, force=force)
        self._flush("pods")
        return out

    def _evict_locked(self, pod_name: str, *, force: bool = False) -> dict:
        cur = self._store["pods"].get(pod_name)
        if cur is None:
            raise NotFoundError(f"pods/{pod_name} not found")
        spec = cur["spec"]
        if not force and not spec.get("isDaemonset"):
            # nested cross-kind read follows KINDS order (pods < pdbs),
            # so it can never deadlock against bulk (one kind at a time)
            with self._locks["pdbs"]:
                pdbs = list(self._store["pdbs"].values())
            for pdb in pdbs:
                ps = pdb["spec"]
                sel = ps.get("labelSelector", {})
                if ps.get("namespace", "default") != spec.get("namespace", "default"):
                    continue
                if not all(spec.get("labels", {}).get(k) == v
                           for k, v in sel.items()):
                    continue
                if self._pdb_allowance(ps) <= 0:
                    raise EvictionBlockedError(
                        f"pod {pod_name}: PDB {pdb['metadata']['name']} "
                        f"permits no eviction now")
        return self._patch_locked("pods", pod_name, {"nodeName": None})

    # ---- field indexers ----------------------------------------------------

    def add_index(self, kind: str, index: str,
                  key_fn: Callable[[dict], Optional[str]]) -> None:
        """Register a field index over SPEC dicts (the manager's
        FieldIndexer analog, operator.go:180-186). Builds a REAL inverted
        map, maintained on every create/update/patch/delete — lookups
        never scan the store."""
        self._check_kind(kind)
        with self._locks[kind]:
            fresh = (kind, index) not in self._indexes
            self._indexes[(kind, index)] = key_fn
            if fresh:
                self._kind_indexes.setdefault(kind, []).append(index)
            fwd: Dict[str, Set[str]] = {}
            keys: Dict[str, str] = {}
            self._index_maps[(kind, index)] = fwd
            self._index_keys[(kind, index)] = keys
            for name, obj in self._store[kind].items():
                try:
                    key = key_fn(obj["spec"])
                except Exception:
                    key = None
                if key is not None:
                    fwd.setdefault(key, set()).add(name)
                    keys[name] = key

    def get_by_index(self, kind: str, index: str, value: str) -> List[dict]:
        """Indexed lookup via the inverted map: touches ONLY matching
        objects. Returns frozen stored envelopes (the copy-on-read
        discipline every read verb follows)."""
        key_fn = self._indexes.get((kind, index))
        if key_fn is None:
            raise APIError(f"no index {index!r} on {kind}")
        with self._locks[kind]:
            names = self._index_maps[(kind, index)].get(value, ())
            store = self._store[kind]
            return [store[n] for n in sorted(names)]
