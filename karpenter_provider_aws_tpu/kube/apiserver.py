"""In-memory Kubernetes-API-shaped object store: list/watch/create/update/
patch/delete over versioned wire objects.

This is the ingest boundary of the framework — the analog of the apiserver
the reference's controllers are wired against (reference
cmd/controller/main.go:47-53 builds core controllers over a client +
cluster state; pkg/operator/operator.go:92-186 builds the manager and its
field indexers; pkg/test/environment.go:83-162 drives the same protocol
from envtest in unit tests). Everything that crosses this seam is a plain
JSON-able dict in the apis/serde wire format wrapped in a k8s-style
envelope::

    {"kind": "Pod",
     "metadata": {"name", "uid", "resourceVersion", "creationTimestamp",
                  "deletionTimestamp", "finalizers"},
     "spec": <serde dict>}

Semantics mirrored from the real protocol:

- **resourceVersion**: one global monotonic counter; every write stamps
  the object and the emitted watch event. ``update`` requires the caller's
  metadata.resourceVersion to match the stored one (409 Conflict
  otherwise) — optimistic concurrency, exactly the reference's
  client-side retry contract.
- **watch**: per-kind subscriptions deliver ADDED/MODIFIED/DELETED events
  in RV order. Each kind keeps a bounded event history; a watch resuming
  from an RV older than the history raises ``TooOldError`` (the HTTP 410
  Gone that forces a reflector relist).
- **finalizers**: ``delete`` on an object with finalizers only stamps
  deletionTimestamp (MODIFIED event); the object is removed when an
  update clears the last finalizer while deletionTimestamp is set — the
  reference's NodeClaim termination flow runs on exactly this contract.
- **subresources**: pods/binding (``bind``) and pods/eviction (``evict``,
  PDB-enforced server-side like the real Eviction API).
- **field indexers**: ``add_index``/``get_by_index`` mirror the manager's
  NodeClaim provider-id index (operator.go:180-186).
- **admission**: pluggable per-kind hooks run on create/update — the
  webhook seam (reference pkg/webhooks/webhooks.go) so invalid objects
  are rejected AT the boundary, not after ingestion.
"""

from __future__ import annotations

import copy
import itertools
import threading
import time as _time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# kinds are plural lowercase, like REST resource paths
KINDS = ("pods", "nodes", "nodeclaims", "nodepools", "nodeclasses",
         "pvcs", "storageclasses", "pdbs", "leases", "events")

EVENT_HISTORY = 4096   # per-kind watch event ring; older RVs are "410 Gone"


class APIError(Exception):
    """Base of every apiserver error."""


class NotFoundError(APIError):
    pass


class AlreadyExistsError(APIError):
    pass


class ConflictError(APIError):
    """Stale resourceVersion on update (HTTP 409)."""


class TooOldError(APIError):
    """Watch RV fell off the event history (HTTP 410 Gone) — relist."""


class InvalidObjectError(APIError):
    """Admission rejected the object (HTTP 422); .causes lists reasons."""

    def __init__(self, kind: str, name: str, causes: Sequence[str]):
        super().__init__(f"{kind}/{name} rejected: " + "; ".join(causes))
        self.causes = list(causes)


class EvictionBlockedError(APIError):
    """A PodDisruptionBudget currently permits no eviction (HTTP 429)."""


@dataclass
class WatchEvent:
    type: str          # ADDED | MODIFIED | DELETED
    kind: str
    object: dict       # full envelope (deep copy)
    resource_version: int


class Watch:
    """One watch subscription: an unbounded FIFO the server appends to.

    ``pop_pending()`` drains without blocking (the deterministic pump);
    ``get(timeout)`` blocks (the threaded reflector). ``stop()`` wakes
    blocked readers with a ``None`` sentinel."""

    def __init__(self, kind: str):
        self.kind = kind
        self._events: deque = deque()
        # instrumented (introspect/contention.py): lock-wait on the
        # condition is fan-out contention; wait() time is accounted
        # separately as QUEUE wait (a parked watcher is not contention)
        from ..introspect import contention
        self._cond = contention.condition("watch_event")
        self._stopped = False

    def _push(self, ev: WatchEvent) -> None:
        with self._cond:
            self._events.append(ev)
            self._cond.notify_all()

    def pop_pending(self) -> List[WatchEvent]:
        with self._cond:
            out = list(self._events)
            self._events.clear()
            return out

    def get(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        with self._cond:
            if not self._events and not self._stopped:
                self._cond.wait(timeout)
            if self._events:
                return self._events.popleft()
            return None

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()


class FakeAPIServer:
    def __init__(self, clock=None):
        """``clock`` (utils.clock.Clock-like) stamps server-side times —
        deletionTimestamp on finalizer-gated deletes, like the real
        apiserver stamps deletion times itself. Defaults to wall clock."""
        self._clock = clock
        # instrumented (introspect/contention.py): EVERY verb and every
        # watch push serializes here — the watch fan-out's convoy lock
        from ..introspect import contention
        self._lock = contention.rlock("api_server")
        self._rv = itertools.count(1)
        self._store: Dict[str, Dict[str, dict]] = {k: {} for k in KINDS}
        self._history: Dict[str, deque] = {
            k: deque(maxlen=EVENT_HISTORY) for k in KINDS}
        self._watches: Dict[str, List[Watch]] = {k: [] for k in KINDS}
        self._indexes: Dict[Tuple[str, str], Callable[[dict], Optional[str]]] = {}
        self._admission: Dict[str, List[Callable[[dict], List[str]]]] = {}
        self._defaulters: Dict[str, List[Callable[[dict], dict]]] = {}
        self._uid = itertools.count(1)
        self.last_rv = 0
        self.events_emitted = 0   # watch fan-out: deliveries pushed, total

    def stats(self) -> Dict[str, int]:
        """Introspection snapshot of the watch hub: subscriber fan-out,
        queued (undelivered) events, store occupancy, write sequence."""
        with self._lock:
            watchers = sum(len(ws) for ws in self._watches.values())
            queued = sum(len(w._events) for ws in self._watches.values()
                         for w in ws)
            objects = sum(len(s) for s in self._store.values())
            return {"watchers": watchers, "watch_queue_depth": queued,
                    "objects": objects, "events_emitted": self.events_emitted,
                    "last_rv": self.last_rv}

    # ---- admission (webhook seam) -----------------------------------------

    def register_admission(self, kind: str,
                           validate: Optional[Callable[[dict], List[str]]] = None,
                           default: Optional[Callable[[dict], dict]] = None) -> None:
        """Install a validating and/or defaulting hook for a kind. The
        validator sees the SPEC wire dict and returns error strings
        (empty = admitted); the defaulter returns the (possibly mutated)
        spec. Mirrors the reference's knative-style admission chain."""
        if validate is not None:
            self._admission.setdefault(kind, []).append(validate)
        if default is not None:
            self._defaulters.setdefault(kind, []).append(default)

    def _admit(self, kind: str, name: str, spec: dict) -> dict:
        for d in self._defaulters.get(kind, ()):
            try:
                spec = d(spec)
            except InvalidObjectError:
                raise   # a defaulter's own precise rejection passes through
            except Exception as e:
                # a defaulter crashing on input the schema would have
                # rejected must still surface as an admission rejection
                # (callers only handle InvalidObjectError); the message
                # class distinguishes defaulter bugs from bad input
                raise InvalidObjectError(
                    kind, name, [f"defaulting failed: {e}"])
        causes: List[str] = []
        for v in self._admission.get(kind, ()):
            causes.extend(v(spec))
        if causes:
            raise InvalidObjectError(kind, name, causes)
        return spec

    # ---- core verbs --------------------------------------------------------

    def _check_kind(self, kind: str) -> None:
        if kind not in self._store:
            raise APIError(f"unknown kind {kind!r}")

    def _emit(self, type_: str, kind: str, obj: dict) -> None:
        rv = obj["metadata"]["resourceVersion"]
        # each subscriber AND the history ring get their OWN copy: a
        # handler mutating a delivered envelope must corrupt neither the
        # replay history nor its sibling watchers (the same isolation
        # list()/get() give via their defensive copies)
        self._history[kind].append(WatchEvent(
            type=type_, kind=kind, object=copy.deepcopy(obj),
            resource_version=rv))
        for w in self._watches[kind]:
            w._push(WatchEvent(type=type_, kind=kind,
                               object=copy.deepcopy(obj),
                               resource_version=rv))
            self.events_emitted += 1

    def _next_rv(self) -> int:
        self.last_rv = next(self._rv)
        return self.last_rv

    def create(self, kind: str, spec: dict, *,
               finalizers: Sequence[str] = ()) -> dict:
        """Create an object from its serde spec; returns the envelope."""
        self._check_kind(kind)
        name = spec.get("name")
        if not name:
            raise APIError(f"{kind}: spec has no name")
        with self._lock:
            if name in self._store[kind]:
                raise AlreadyExistsError(f"{kind}/{name} already exists")
            spec = self._admit(kind, name, copy.deepcopy(spec))
            rv = self._next_rv()
            obj = {
                "kind": kind,
                "metadata": {
                    "name": name,
                    "uid": f"uid-{next(self._uid):06d}",
                    "resourceVersion": rv,
                    # stamped when a clock is wired (live mode); None in
                    # clock-free tests, where RV orders events
                    "creationTimestamp": (self._clock.now()
                                          if self._clock else None),
                    "deletionTimestamp": None,
                    "finalizers": list(finalizers),
                },
                "spec": spec,
                # controller-owned status sub-map (the k8s spec/status
                # split): written only via patch(status_patch=...), and
                # PRESERVED across user spec updates — `kpctl get -o yaml
                # | kpctl apply` can never re-submit stale status
                "status": {},
            }
            self._store[kind][name] = obj
            self._emit("ADDED", kind, obj)
            return copy.deepcopy(obj)

    def get(self, kind: str, name: str) -> dict:
        self._check_kind(kind)
        with self._lock:
            obj = self._store[kind].get(name)
            if obj is None:
                raise NotFoundError(f"{kind}/{name} not found")
            return copy.deepcopy(obj)

    def now(self) -> float:
        """The server's clock reading — the timebase every timestamp the
        server stamps (creationTimestamp, deletionTimestamp, event times)
        lives on. Clients rendering ages must anchor to THIS, not their
        own wall clock: under a FakeClock (or plain clock skew) the two
        can differ arbitrarily."""
        return self._clock.now() if self._clock is not None else _time.time()

    def list(self, kind: str) -> Tuple[List[dict], int]:
        """Returns (items, listResourceVersion) — watch from the returned
        RV to observe every later change exactly once."""
        self._check_kind(kind)
        with self._lock:
            items = [copy.deepcopy(o) for o in self._store[kind].values()]
            return items, self.last_rv

    def update(self, kind: str, obj: dict) -> dict:
        """Full-object update with optimistic concurrency: the caller's
        metadata.resourceVersion must match the stored object's. The
        envelope's ``status`` sub-map is controller-owned and EXCLUDED
        from the write — the stored status survives a user apply
        verbatim (spec/status split; write status via
        ``patch(status_patch=...)``)."""
        self._check_kind(kind)
        name = obj["metadata"]["name"]
        with self._lock:
            cur = self._store[kind].get(name)
            if cur is None:
                raise NotFoundError(f"{kind}/{name} not found")
            if obj["metadata"]["resourceVersion"] != cur["metadata"]["resourceVersion"]:
                raise ConflictError(
                    f"{kind}/{name}: stale resourceVersion "
                    f"{obj['metadata']['resourceVersion']} "
                    f"(current {cur['metadata']['resourceVersion']})")
            spec = self._admit(kind, name, copy.deepcopy(obj["spec"]))
            new = copy.deepcopy(cur)
            new["spec"] = spec
            new["metadata"]["finalizers"] = list(obj["metadata"].get("finalizers", ()))
            new["metadata"]["resourceVersion"] = self._next_rv()
            # clearing the last finalizer of a deleting object removes it
            if (new["metadata"]["deletionTimestamp"] is not None
                    and not new["metadata"]["finalizers"]):
                del self._store[kind][name]
                self._emit("DELETED", kind, new)
            else:
                self._store[kind][name] = new
                self._emit("MODIFIED", kind, new)
            return copy.deepcopy(new)

    @staticmethod
    def _merge_value(target: dict, k: str, v) -> None:
        """RFC 7386 JSON merge patch for one key: ``None`` deletes, maps
        merge RECURSIVELY (so writers of disjoint annotation/label keys
        never clobber each other's entries), everything else replaces."""
        if v is None:
            target.pop(k, None)
        elif isinstance(v, dict):
            # RFC 7386 §2: a non-object (or missing) target counts as {},
            # so deletion markers inside the patch vanish instead of
            # being stored verbatim as None values — status patches skip
            # admission and would otherwise persist them
            base = target.get(k)
            sub = dict(base) if isinstance(base, dict) else {}
            for sk, sv in v.items():
                FakeAPIServer._merge_value(sub, sk, sv)
            target[k] = sub
        else:
            target[k] = copy.deepcopy(v)

    def patch(self, kind: str, name: str, spec_patch: Optional[dict] = None, *,
              status_patch: Optional[dict] = None,
              finalizers: Optional[Sequence[str]] = None) -> dict:
        """JSON-merge-patch on the spec (RFC 7386: ``None`` values delete
        keys, nested maps merge per-key), the controller-owned envelope
        ``status`` sub-map, and/or replace the finalizer list. No RV
        precondition — a patch applies to whatever is current, like a
        server-side strategic merge. Status patches skip spec admission:
        they never contain user intent."""
        self._check_kind(kind)
        with self._lock:
            cur = self._store[kind].get(name)
            if cur is None:
                raise NotFoundError(f"{kind}/{name} not found")
            new = copy.deepcopy(cur)
            if spec_patch:
                for k, v in spec_patch.items():
                    self._merge_value(new["spec"], k, v)
                new["spec"] = self._admit(kind, name, new["spec"])
            if status_patch:
                status = new.setdefault("status", {})
                for k, v in status_patch.items():
                    self._merge_value(status, k, v)
            if finalizers is not None:
                new["metadata"]["finalizers"] = list(finalizers)
            new["metadata"]["resourceVersion"] = self._next_rv()
            if (new["metadata"]["deletionTimestamp"] is not None
                    and not new["metadata"]["finalizers"]):
                del self._store[kind][name]
                self._emit("DELETED", kind, new)
            else:
                self._store[kind][name] = new
                self._emit("MODIFIED", kind, new)
            return copy.deepcopy(new)

    def delete(self, kind: str, name: str, *, now: Optional[float] = None,
               force: bool = False) -> None:
        """Delete an object. With finalizers present (and not ``force``),
        only stamps deletionTimestamp — the finalizing controller removes
        the object later by clearing the finalizer list."""
        self._check_kind(kind)
        with self._lock:
            cur = self._store[kind].get(name)
            if cur is None:
                raise NotFoundError(f"{kind}/{name} not found")
            if cur["metadata"]["finalizers"] and not force:
                if cur["metadata"]["deletionTimestamp"] is None:
                    new = copy.deepcopy(cur)
                    # the server stamps deletion time itself when the
                    # caller didn't; never 0.0/falsy — every downstream
                    # consumer truth-tests deletion_timestamp
                    if now is None:
                        now = (self._clock.now() if self._clock is not None
                               else _time.time())
                    new["metadata"]["deletionTimestamp"] = now or 1e-9
                    new["metadata"]["resourceVersion"] = self._next_rv()
                    self._store[kind][name] = new
                    self._emit("MODIFIED", kind, new)
                return
            gone = copy.deepcopy(cur)
            gone["metadata"]["resourceVersion"] = self._next_rv()
            del self._store[kind][name]
            self._emit("DELETED", kind, gone)

    # ---- watch -------------------------------------------------------------

    def watch(self, kind: str, resource_version: int = 0) -> Watch:
        """Subscribe from ``resource_version`` (exclusive). Events already
        past that RV replay from the history ring; an RV older than the
        ring raises TooOldError (relist, like a 410 Gone)."""
        self._check_kind(kind)
        with self._lock:
            hist = self._history[kind]
            # a full ring has dropped events (all with RV < hist[0]'s);
            # resuming below that horizon can't replay them — 410 Gone.
            # A non-full ring still holds the kind's entire lifetime, so
            # any RV (including 0) is safe.
            if (len(hist) == hist.maxlen
                    and resource_version < hist[0].resource_version - 1):
                raise TooOldError(
                    f"{kind}: watch from rv={resource_version} too old "
                    f"(history starts at {hist[0].resource_version})")
            w = Watch(kind)
            for ev in hist:
                if ev.resource_version > resource_version:
                    # replayed events are copies too — the ring must stay
                    # pristine for the next resuming watcher
                    w._push(WatchEvent(type=ev.type, kind=ev.kind,
                                       object=copy.deepcopy(ev.object),
                                       resource_version=ev.resource_version))
            self._watches[kind].append(w)
            return w

    def stop_watch(self, w: Watch) -> None:
        with self._lock:
            if w in self._watches[w.kind]:
                self._watches[w.kind].remove(w)
        w.stop()

    # ---- subresources ------------------------------------------------------

    def bind(self, pod_name: str, node_name: str) -> dict:
        """pods/binding: set spec.nodeName on an unbound pod."""
        with self._lock:
            cur = self._store["pods"].get(pod_name)
            if cur is None:
                raise NotFoundError(f"pods/{pod_name} not found")
            if cur["spec"].get("nodeName"):
                raise ConflictError(
                    f"pod {pod_name} already bound to {cur['spec']['nodeName']}")
            return self.patch("pods", pod_name, {"nodeName": node_name})

    def _pdb_allowance(self, pdb_spec: dict) -> int:
        """Server-side disruptions-allowed math (policy/v1): healthy =
        bound matching pods without deletionTimestamp. Caller holds lock."""
        sel = pdb_spec.get("labelSelector", {})
        ns = pdb_spec.get("namespace", "default")
        matching = []
        for obj in self._store["pods"].values():
            s = obj["spec"]
            if s.get("isDaemonset"):
                continue
            if s.get("namespace", "default") != ns:
                continue
            if all(s.get("labels", {}).get(k) == v for k, v in sel.items()):
                matching.append(obj)
        healthy = sum(1 for o in matching
                      if o["spec"].get("nodeName")
                      and o["metadata"]["deletionTimestamp"] is None
                      # pods carry deletion state in SPEC too (our pods
                      # have no finalizers, so a draining pod is marked
                      # at the spec level — state/cluster.py:204 uses the
                      # same representation for healthy math)
                      and o["spec"].get("deletionTimestamp") is None)
        allowed = len(matching)
        if pdb_spec.get("minAvailable") is not None:
            allowed = min(allowed, healthy - int(pdb_spec["minAvailable"]))
        if pdb_spec.get("maxUnavailable") is not None:
            unavailable = len(matching) - healthy
            allowed = min(allowed, int(pdb_spec["maxUnavailable"]) - unavailable)
        return max(allowed, 0)

    def evict(self, pod_name: str, *, force: bool = False) -> dict:
        """pods/eviction: unbind the pod (the workload controller instantly
        re-creates it pending in this simulation, so eviction == unbind).
        PDBs are enforced HERE, server-side, exactly like the real
        Eviction API; ``force`` models a grace-zero pod delete that
        bypasses budgets (the reference's force-drain backstop)."""
        with self._lock:
            cur = self._store["pods"].get(pod_name)
            if cur is None:
                raise NotFoundError(f"pods/{pod_name} not found")
            spec = cur["spec"]
            if not force and not spec.get("isDaemonset"):
                for pdb in self._store["pdbs"].values():
                    ps = pdb["spec"]
                    sel = ps.get("labelSelector", {})
                    if ps.get("namespace", "default") != spec.get("namespace", "default"):
                        continue
                    if not all(spec.get("labels", {}).get(k) == v
                               for k, v in sel.items()):
                        continue
                    if self._pdb_allowance(ps) <= 0:
                        raise EvictionBlockedError(
                            f"pod {pod_name}: PDB {pdb['metadata']['name']} "
                            f"permits no eviction now")
            return self.patch("pods", pod_name, {"nodeName": None})

    # ---- field indexers ----------------------------------------------------

    def add_index(self, kind: str, index: str,
                  key_fn: Callable[[dict], Optional[str]]) -> None:
        """Register a field index over SPEC dicts (the manager's
        FieldIndexer analog, operator.go:180-186)."""
        self._check_kind(kind)
        self._indexes[(kind, index)] = key_fn

    def get_by_index(self, kind: str, index: str, value: str) -> List[dict]:
        key_fn = self._indexes.get((kind, index))
        if key_fn is None:
            raise APIError(f"no index {index!r} on {kind}")
        with self._lock:
            return [copy.deepcopy(o) for o in self._store[kind].values()
                    if key_fn(o["spec"]) == value]
