"""karpenter_provider_aws_tpu — a TPU-native node-provisioning framework.

A from-scratch reimplementation of the capabilities of Karpenter's AWS
provider (reference: /root/reference, Go), redesigned TPU-first:

- The provisioning scheduler (reference: sequential Go First-Fit-Decreasing,
  designs/bin-packing.md) and the consolidation search (designs/consolidation.md)
  are reformulated as a batched pod x instance-type constraint-satisfaction
  problem solved by a single jit-compiled grouped-FFD kernel on device
  (`karpenter_provider_aws_tpu.ops.binpack`).
- The control plane (operator, controllers, cloud lattice providers, caching,
  batching, fault feedback, metrics) is rebuilt idiomatically around that
  solver with a fake cloud backend for tests.

Package map (reference analog in parens):

- ``apis``        CRD-equivalent object model: NodePool / NodeClaim / NodeClass,
                  requirements algebra (pkg/apis).
- ``lattice``     instance-type catalog, offerings, pricing, allocatable math
                  (pkg/providers/instancetype, pkg/providers/pricing).
- ``ops``         device kernels: requirement->mask compiler, grouped-FFD
                  bin-packing scan, offering finalization (the core scheduler
                  hot loop, moved on device).
- ``solver``      host-facing Solve() API: pod dedup/grouping, bucketed
                  padding, NodePlan decode, FFD oracle referee.
- ``parallel``    jax.sharding Mesh plumbing, pod-axis sharded solve
                  (shard_map), cross-device reductions.
- ``cloud``       CloudProvider boundary + fake cloud backend
                  (pkg/cloudprovider, pkg/fake).
- ``controllers`` reconcile loops: provisioning, disruption, interruption,
                  nodeclass, gc, tagging, pricing (pkg/controllers + core).
- ``state``       in-memory cluster state mirror (core state.Cluster).
- ``cache``       TTL caches incl. unavailable-offerings ICE cache (pkg/cache).
- ``batcher``     request coalescer (pkg/batcher).
- ``utils``       unit parsing, hashing, misc (pkg/utils).
"""

__version__ = "0.4.0"
