"""Weather scenarios: the declarative half of the adversarial suite.

A scenario is a small, fully-serializable schedule of market and chaos
phases (docs/reference/weather.md): a mean-reverting spot-price walk
with regime shifts, ICE (insufficient-capacity) spells, correlated
interruption storms, and device weather. Everything the simulator does
is a pure function of ``(scenario, seed, tick)`` — two runs with the
same scenario JSON and seed produce byte-identical weather timelines,
which is what makes a chaos soak REPLAYABLE instead of anecdotal.

Named scenarios (``calm``, ``squall``, ``spot-crash``, ``ice-age``,
``storm-front``) are constructed here; ``tools/soak.py --weather`` and
the CI squall smoke accept either a name or a path to a scenario JSON
file produced by :meth:`WeatherScenario.to_json`.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass
from typing import Dict, Tuple

# the storm/ICE zone palette used by the named scenarios — the standard
# availability zones of the synthetic catalog (lattice/catalog.py ZONES
# minus the local zone, which has no spot market to storm on)
_STD_ZONES = ("us-west-2a", "us-west-2b", "us-west-2c")


@dataclass(frozen=True)
class Regime:
    """A spot-market regime shift: from ``at`` onward, matching
    (family, zone) walks revert toward ``mu`` (log-space; 0.0 = the base
    market, ``ln 2`` = prices doubling). Later regimes override earlier
    ones for the keys they match."""

    at: float                           # seconds from scenario start
    mu: float                           # log-multiplier reversion target
    families: Tuple[str, ...] = ()      # () = every family
    zones: Tuple[str, ...] = ()         # () = every zone


@dataclass(frozen=True)
class Storm:
    """A correlated interruption storm over ``zones`` × ``families``:
    every tick in [at, at+duration) bursts EventBridge messages at
    matching live spot instances (all four schemas), optionally mixed
    with junk bodies and device weather."""

    at: float
    duration: float
    zones: Tuple[str, ...] = ()
    families: Tuple[str, ...] = ()
    intensity: float = 0.25             # P(message for a matching instance)/tick
    junk_rate: float = 0.0              # expected malformed/unknown bodies/tick
    device_error_rate: float = 0.0      # P(device-error burst)/tick
    device_errors: int = 3              # injected per burst (3 ⇒ retry exhausts
                                        # and the host-FFD rung engages)


@dataclass(frozen=True)
class IceSpell:
    """An insufficient-capacity spell: while active, ~``rate`` matching
    offerings per tick are pulled from the market (FakeCloud capacity 0
    + an UnavailableOfferings mark) and held for a deterministic number
    of ticks before thawing."""

    at: float
    duration: float
    rate: float = 1.0                   # expected newly-ICE'd offerings/tick
    zones: Tuple[str, ...] = ()
    families: Tuple[str, ...] = ()
    capacity_types: Tuple[str, ...] = ("spot",)
    hold_seconds: float = 60.0          # mean hold before a pool thaws


@dataclass(frozen=True)
class SidecarOutage:
    """CONTROL-PLANE weather (docs/reference/solver-pool.md): while
    active, one solver-pool endpoint misbehaves. Modes:

    - ``kill``: the endpoint goes dark (connection refused); with
      ``restart_after`` (default) it restarts when the window closes —
      the breaker's half-open probe must then re-close it;
    - ``hang``: the endpoint ACCEPTS the RPC and stalls past every
      deadline — the failure a connect error never exercises;
    - ``junk``: the endpoint answers bytes that are not a NodePlan.

    Purely deterministic (no RNG): the timeline records outage/restore
    on the ticks the window edges cross, exactly like storms."""

    at: float
    duration: float
    endpoint: int = 0                   # index into the pool's endpoint list
    mode: str = "kill"                  # kill | hang | junk
    restart_after: bool = True          # kill mode: restart at window end


@dataclass(frozen=True)
class OperatorKill:
    """OPERATOR weather (docs/reference/handoff.md): at ``at`` seconds
    the targeted operator runtime dies mid-storm. Modes:

    - ``kill``: crash semantics — the runtime crash-stops WITHOUT
      releasing its lease (a kill -9 never runs the shutdown path), so
      the standby must wait out the lease duration before promoting;
    - ``hang``: the runtime's threads freeze in place — renewal stops,
      the lease expires, a standby promotes, and when the window closes
      (``restart_after``) the zombie resumes straight into the write
      fence, where its queued side effects are rejected.

    Deterministic like :class:`SidecarOutage`: the timeline records
    kill/restore on the ticks the window edges cross. ``restart_after``
    defaults to False — a killed leader staying dead is the handoff
    acceptance shape (the standby must carry the rest of the run)."""

    at: float
    duration: float
    target: int = 0                     # index into the operator-handle list
    mode: str = "kill"                  # kill | hang
    restart_after: bool = False


@dataclass
class WeatherScenario:
    name: str = "custom"
    seed: int = 0
    tick_seconds: float = 2.0
    duration_seconds: float = 240.0     # advisory run length (harnesses may
                                        # run longer; the schedule just ends)
    # the market walk: per-(family, zone) log-multiplier x evolving as
    # x += theta * (mu - x) + sigma * N(0, 1) each tick
    market_theta: float = 0.15
    market_sigma: float = 0.04
    market_mu: float = 0.0
    reprice_every: int = 1              # ticks between pushes to the lattice
    regimes: Tuple[Regime, ...] = ()
    storms: Tuple[Storm, ...] = ()
    ice: Tuple[IceSpell, ...] = ()
    sidecar_outages: Tuple[SidecarOutage, ...] = ()
    operator_kills: Tuple[OperatorKill, ...] = ()

    # ---- serialization (replayable byte-for-byte from a seed) -----------

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    def to_json(self, indent: int = 1) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)

    @classmethod
    def from_dict(cls, d: Dict) -> "WeatherScenario":
        def tup(xs, typ):
            return tuple(typ(**{k: (tuple(v) if isinstance(v, list) else v)
                                for k, v in x.items()}) for x in xs or ())
        kw = dict(d)
        kw["regimes"] = tup(kw.get("regimes"), Regime)
        kw["storms"] = tup(kw.get("storms"), Storm)
        kw["ice"] = tup(kw.get("ice"), IceSpell)
        if "sidecar_outages" in kw:   # absent in pre-PR-13 scenario JSON
            kw["sidecar_outages"] = tup(kw.get("sidecar_outages"),
                                        SidecarOutage)
        if "operator_kills" in kw:    # absent in pre-PR-17 scenario JSON
            kw["operator_kills"] = tup(kw.get("operator_kills"),
                                       OperatorKill)
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(kw) - known
        if unknown:
            raise ValueError(f"unknown scenario fields: {sorted(unknown)}")
        return cls(**kw)

    @classmethod
    def from_json(cls, text: str) -> "WeatherScenario":
        return cls.from_dict(json.loads(text))


def named(name: str) -> WeatherScenario:
    """The built-in scenario library (docs/reference/weather.md)."""
    if name == "calm":
        # fair weather: a barely-drifting market, no chaos — the control
        # run the stormy artifacts are compared against
        return WeatherScenario(name="calm", market_sigma=0.01)
    if name == "squall":
        # one short, violent storm mid-run, then recovery — the CI gate
        # (tools/smoke_weather.py): 60 s on FakeClock, ladder must engage
        # and the burn must recover after the front passes
        return WeatherScenario(
            name="squall", tick_seconds=1.0, duration_seconds=60.0,
            market_sigma=0.03,
            storms=(Storm(at=20.0, duration=15.0,
                          zones=_STD_ZONES[:2], intensity=0.5,
                          junk_rate=0.5, device_error_rate=0.6),),
            ice=(IceSpell(at=20.0, duration=15.0, rate=1.0,
                          zones=_STD_ZONES[:2], hold_seconds=20.0),))
    if name == "spot-crash":
        # the spot market for the workhorse families triples, then
        # mean-reverts: consolidation must chase the moving price field
        # without burning the 2% cost budget
        crash = 1.1     # ln-multiplier ≈ 3.0x
        return WeatherScenario(
            name="spot-crash", market_sigma=0.06,
            regimes=(Regime(at=50.0, mu=crash,
                            families=("m5", "c5", "r5")),
                     Regime(at=170.0, mu=0.0)))
    if name == "ice-age":
        # sustained capacity scarcity: a long, broad ICE spell — the
        # solver keeps placing around a shrinking offering set
        return WeatherScenario(
            name="ice-age", market_sigma=0.03,
            ice=(IceSpell(at=30.0, duration=170.0, rate=2.0,
                          capacity_types=("spot", "on-demand"),
                          hold_seconds=90.0),))
    if name == "storm-front":
        # the acceptance scenario: a front marching zone by zone —
        # correlated interruption storms with junk and device weather,
        # ICE trailing each storm, and a price spike while capacity is
        # being reclaimed. Every rung of the ladder fires.
        storms = tuple(
            Storm(at=30.0 + 50.0 * i, duration=40.0, zones=(z,),
                  intensity=0.35, junk_rate=0.3,
                  device_error_rate=0.4, device_errors=3)
            for i, z in enumerate(_STD_ZONES))
        spells = tuple(
            IceSpell(at=30.0 + 50.0 * i, duration=40.0, rate=2.0,
                     zones=(z,), hold_seconds=45.0)
            for i, z in enumerate(_STD_ZONES))
        return WeatherScenario(
            name="storm-front", market_sigma=0.05,
            regimes=(Regime(at=30.0, mu=0.6),   # ≈1.8x while the front rages
                     Regime(at=185.0, mu=0.0)),
            storms=storms, ice=spells)
    if name == "blackout":
        # control-plane weather against a 2-sidecar solver pool
        # (docs/reference/solver-pool.md): endpoint 0 dies outright and
        # endpoint 1 HANGS while 0 is still dark — a full-pool blackout
        # window (30-45 s) where the local solve is the only rung —
        # then 1 recovers, 0 restarts (breaker must re-close via the
        # half-open probe), and a late junk-response spell on 1 forces
        # failovers onto the recovered 0. Market stays mild: the
        # artifact isolates the control plane's own failure ladder.
        return WeatherScenario(
            name="blackout", tick_seconds=1.0, duration_seconds=120.0,
            market_sigma=0.02,
            sidecar_outages=(
                SidecarOutage(at=15.0, duration=40.0, endpoint=0,
                              mode="kill"),
                SidecarOutage(at=30.0, duration=15.0, endpoint=1,
                              mode="hang"),
                SidecarOutage(at=75.0, duration=15.0, endpoint=1,
                              mode="junk"),
            ))
    if name == "handoff":
        # the operator-handoff acceptance scenario (docs/reference/
        # handoff.md): a violent squall-class storm is raging when the
        # ACTIVE OPERATOR is killed outright mid-storm (no restart — a
        # dead leader stays dead). The warm standby must wait out the
        # lease, pass the bounded-staleness gate, promote behind the
        # write fence, sweep the blackout window's orphaned leases, and
        # carry the rest of the storm within the SLO budget. Market
        # stays mild: the artifact isolates the handoff itself.
        return WeatherScenario(
            name="handoff", tick_seconds=1.0, duration_seconds=120.0,
            market_sigma=0.02,
            storms=(Storm(at=25.0, duration=40.0, zones=_STD_ZONES[:2],
                          intensity=0.35, junk_rate=0.2),),
            ice=(IceSpell(at=25.0, duration=30.0, rate=1.0,
                          zones=_STD_ZONES[:1], hold_seconds=20.0),),
            operator_kills=(OperatorKill(at=45.0, duration=60.0,
                                         target=0, mode="kill"),))
    raise ValueError(f"unknown weather scenario {name!r} "
                     f"(named: {', '.join(NAMED_SCENARIOS)})")


NAMED_SCENARIOS = ("calm", "squall", "spot-crash", "ice-age",
                   "storm-front", "blackout", "handoff")


def load_scenario(spec: str) -> WeatherScenario:
    """A named scenario, or a path to a scenario JSON file."""
    if spec in NAMED_SCENARIOS:
        return named(spec)
    from pathlib import Path
    p = Path(spec)
    if p.exists():
        return WeatherScenario.from_json(p.read_text())
    raise ValueError(f"--weather {spec!r}: not a named scenario "
                     f"({', '.join(NAMED_SCENARIOS)}) and no such file")
