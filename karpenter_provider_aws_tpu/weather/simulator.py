"""The weather simulator: deterministic chaos over the control plane.

One object composes every adversarial seam the repo already has into a
single clock-driven system (docs/reference/weather.md):

- **spot market**: the :class:`~.fields.SpotMarketField` walk pushes
  re-priced spot surfaces through ``PricingProvider.update_spot_pricing``
  — the lattice's price tensor is rewritten in place and
  ``price_version`` bumps, so the solver's masked-view memo, the
  incremental builder's gate ladder, and the device-resident problem
  state all re-tensorize exactly as they would for a live pricing feed;
- **ICE field**: chosen offerings get ``FakeCloud`` capacity 0 (ground
  truth — launches into them fail and feed the provider's own ICE
  handling) AND an ``UnavailableOfferings`` mark (the learned state the
  next solve masks on);
- **interruption storms**: bursts of all four EventBridge schemas
  (``interruption/messages.py``) at live spot instances correlated by
  zone/family, plus junk bodies that must be counted-and-dropped;
- **device weather**: retryable XLA failures via the solver's
  ``FaultInjector`` (merged, never replacing an operator-applied one —
  ``--fault-schedule`` and ``--weather`` compose).

Everything the simulator DECIDES is a pure function of ``(scenario,
seed, tick)``: per-tick RNGs are derived as ``Random(f"{seed}:{tick}")``
(plus a separate ``:live`` stream for draws whose COUNT depends on live
control-plane state, so instance-targeted sampling can never desync the
deterministic stream). The recorded ``timeline`` contains only the
deterministic decisions — :meth:`WeatherSimulator.replay` re-derives it
with no control plane attached, which is how a soak proves its weather
was reproducible.

Driven off the shared ``Clock``: ``advance()`` converts elapsed clock
time into tick numbers and steps any missed ticks sequentially, so a
``FakeClock`` CI smoke and a wall-clock soak run ONE code path.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional, Tuple

from ..utils.clock import Clock
from .fields import IceField, Offering, SpotMarketField
from .scenario import WeatherScenario

_ICE_REASON = "WeatherIce"

# consolidation_advisory's spot-crash detector: a regime target μ (log
# price multiplier) at or past this reads as distressed spot capacity
# (e^0.5 ≈ a sustained 1.65× surge) — voluntary consolidation holds
CONSOL_HOLD_MU = 0.5


class WeatherSimulator:
    def __init__(self, scenario: WeatherScenario, lattice,
                 seed: Optional[int] = None, clock: Optional[Clock] = None,
                 pricing=None, cloud=None, unavailable=None, queue=None,
                 solver=None, metrics=None, sidecars=None, operators=None):
        """Every control-plane seam is optional: with all of them None
        the simulator is a pure replay engine (timeline only).

        ``sidecars`` is the control-plane-weather seam (PR 13): a
        sequence of handles with ``kill()/restart()/set_hang()/
        set_junk()`` (parallel/sidecar.py ChaosSidecar) that scenario
        ``SidecarOutage`` elements drive — one handle per solver-pool
        endpoint index. An outage naming an endpoint beyond the list is
        recorded in the timeline but applies to nothing (the timeline
        stays a pure function of the scenario either way).

        ``operators`` is the operator-weather seam (handoff chaos): a
        sequence of handles with ``kill()/restart()/set_hang()/
        restore()`` (tools/soak.py OperatorHandle over a
        ControllerRuntime) that scenario ``OperatorKill`` elements
        drive — one handle per operator index. Same out-of-range /
        pure-replay semantics as ``sidecars``."""
        self.scenario = scenario
        self.seed = scenario.seed if seed is None else int(seed)
        self.lattice = lattice
        self.clock = clock or Clock()
        self.pricing = pricing
        self.cloud = cloud
        self.unavailable = unavailable
        self.queue = queue
        self.solver = solver
        self.sidecars = list(sidecars) if sidecars else []
        self.operators = list(operators) if operators else []
        self.market = SpotMarketField(lattice, scenario)
        self.ice = IceField(lattice, scenario)
        self._fam_of = {s.name: s.family for s in lattice.specs}
        self.timeline: List[Dict] = []
        self.counters: Dict[str, int] = {
            "reprices": 0, "regime_shifts": 0, "storm_ticks": 0,
            "messages_sent": 0, "spot_interruptions": 0, "rebalances": 0,
            "scheduled_changes": 0, "state_changes": 0, "junk_sent": 0,
            "ice_marks": 0, "ice_thaws": 0, "device_errors": 0,
            "sidecar_outages": 0, "sidecar_restores": 0,
            "operator_kills": 0, "operator_restores": 0,
        }
        self.ticks = 0
        self._t0: Optional[float] = None
        self._stopped = False
        self._lock = threading.Lock()
        # active regime targets per (family, zone); later shifts override
        self._mu: Dict[Tuple[str, str], float] = {}
        self._held: Dict[Offering, int] = {}     # offering -> thaw tick
        self._gauges = None
        if metrics is not None:
            from ..metrics import wire_core_metrics
            m = wire_core_metrics(metrics)
            self._gauges = {
                "storm": m["weather_storm_active"],
                "ice": m["weather_ice_pools"],
                "mult_mean": m["weather_spot_mult_mean"],
                "mult_max": m["weather_spot_mult_max"],
                "ticks": m["weather_ticks"],
                "events": m["weather_events"],
            }

    # ---- drive ----------------------------------------------------------

    def start(self) -> "WeatherSimulator":
        self._t0 = self.clock.monotonic()
        return self

    def advance(self) -> int:
        """Step every tick the clock has reached since the last call
        (0 or more). The soak churn loop and the FakeClock smoke both
        call this once per iteration — one code path."""
        with self._lock:
            if self._t0 is None:
                self._t0 = self.clock.monotonic()
            want = int((self.clock.monotonic() - self._t0)
                       / self.scenario.tick_seconds)
            stepped = 0
            while self.ticks < want:
                self._step_tick()
                stepped += 1
            return stepped

    def step(self, n: int = 1) -> None:
        """Step exactly ``n`` ticks regardless of the clock (replay and
        deterministic tests)."""
        with self._lock:
            for _ in range(n):
                self._step_tick()

    # ---- one tick -------------------------------------------------------

    def _event(self, kind: str, **payload) -> None:
        e = {"tick": self.ticks, "kind": kind}
        e.update(payload)
        self.timeline.append(e)
        if self._gauges is not None:
            self._gauges["events"].inc(kind=kind)

    def _step_tick(self) -> None:
        sc = self.scenario
        t = self.ticks
        now_s = t * sc.tick_seconds
        prev_s = now_s - sc.tick_seconds
        rng = random.Random(f"{self.seed}:{t}")

        # 1. regime shifts crossing into this tick
        for r in sc.regimes:
            if prev_s < r.at <= now_s or (t == 0 and r.at <= 0):
                matched = 0
                for fam, zone in self.market.keys:
                    if ((not r.families or fam in r.families)
                            and (not r.zones or zone in r.zones)):
                        self._mu[(fam, zone)] = r.mu
                        matched += 1
                if matched == 0:
                    # a regime whose filter matches no market walk never
                    # activated: don't count or record it — the soak's
                    # regime non-vacuity gate must not be satisfiable by
                    # a filter that named families/zones the lattice
                    # doesn't carry
                    continue
                self.counters["regime_shifts"] += 1
                self._event("regime", at=r.at, mu=r.mu,
                            families=list(r.families), zones=list(r.zones))

        # 2. market walk + reprice
        self.market.step(rng, self._mu)
        if sc.reprice_every and t % sc.reprice_every == 0:
            self.counters["reprices"] += 1
            self._event("reprice", digest=self.market.digest())
            if self.pricing is not None:
                self.pricing.update_spot_pricing(self.market.prices())

        # 3. ICE field: thaw expired holds, then sample active spells
        thawed = sorted(o for o, thaw in self._held.items() if thaw <= t)
        if thawed:
            for o in thawed:
                del self._held[o]
            self.counters["ice_thaws"] += len(thawed)
            self._event("ice-thaw", pools=[list(o) for o in thawed])
            if self.cloud is not None:
                for ct, it, z in thawed:
                    self.cloud.clear_capacity(ct, it, z)
            if self.unavailable is not None:
                for ct, it, z in thawed:
                    self.unavailable.delete(ct, it, z)
        for i, spell in enumerate(sc.ice):
            if not (spell.at <= now_s < spell.at + spell.duration):
                continue
            new = self.ice.sample(rng, i, spell, self._held, t,
                                  sc.tick_seconds)
            if not new:
                continue
            self._held.update(new)
            self.counters["ice_marks"] += len(new)
            self._event("ice", pools=[list(o) for o, _ in new])
            if self.unavailable is not None:
                for (ct, it, z), _ in new:
                    self.unavailable.mark_unavailable(_ICE_REASON, ct, it, z)
        if self.cloud is not None:
            # re-assert the hold every tick: instance terminations hand
            # capacity back to pools they came from (cloud/fake.py), and a
            # weather-held pool must stay dry until its thaw tick
            for ct, it, z in self._held:
                self.cloud.set_capacity(ct, it, z, 0)

        # 4. storms. Events always pair: begin fires on the tick the
        # window opens, end on the tick its close crosses — a storm
        # whose whole window falls between two ticks (shorter than
        # tick_seconds) still runs begin → one burst → end on the tick
        # it slips past, never an unpaired end.
        storms_active = 0
        for i, storm in enumerate(sc.storms):
            end_s = storm.at + storm.duration
            started = (prev_s < storm.at <= now_s
                       or (t == 0 and storm.at <= 0))
            active = storm.at <= now_s < end_s
            if started:
                self._event("storm-begin", storm=i,
                            zones=list(storm.zones),
                            families=list(storm.families),
                            intensity=storm.intensity)
            if active or (started and now_s >= end_s):
                storms_active += 1
                self.counters["storm_ticks"] += 1
                self._burst(rng, i, storm)
            if storm.at <= now_s and prev_s < end_s <= now_s:
                self._event("storm-end", storm=i)

        # 4b. sidecar outages (control-plane weather; parallel/pool.py).
        # Purely deterministic — no RNG draw, so the timeline events are
        # a function of (scenario, tick) alone and replay with no
        # sidecar handles attached. Same edge pairing as storms: an
        # outage shorter than tick_seconds still runs
        # outage → restore on the tick it slips past.
        for i, o in enumerate(sc.sidecar_outages):
            end_s = o.at + o.duration
            started = (prev_s < o.at <= now_s or (t == 0 and o.at <= 0))
            if started:
                self.counters["sidecar_outages"] += 1
                self._event("sidecar-outage", outage=i,
                            endpoint=o.endpoint, mode=o.mode)
                self._apply_outage(o)
            if o.at <= now_s and prev_s < end_s <= now_s:
                self.counters["sidecar_restores"] += 1
                self._event("sidecar-restore", outage=i,
                            endpoint=o.endpoint, mode=o.mode)
                self._restore_outage(o)

        # 4c. operator kills (handoff chaos; operator/runtime.py +
        # state/replication.py). Deterministic like 4b: the timeline is
        # a function of (scenario, tick) with or without operator
        # handles attached.
        for i, k in enumerate(sc.operator_kills):
            end_s = k.at + k.duration
            started = (prev_s < k.at <= now_s or (t == 0 and k.at <= 0))
            if started:
                self.counters["operator_kills"] += 1
                self._event("operator-kill", kill=i,
                            target=k.target, mode=k.mode)
                self._apply_opkill(k)
            if k.at <= now_s and prev_s < end_s <= now_s:
                self.counters["operator_restores"] += 1
                self._event("operator-restore", kill=i,
                            target=k.target, mode=k.mode)
                self._restore_opkill(k)

        # 5. device weather (independent draws per active storm, fixed
        # order — deterministic)
        for i, storm in enumerate(sc.storms):
            if not (storm.at <= now_s < storm.at + storm.duration):
                continue
            if storm.device_error_rate and \
                    rng.random() < storm.device_error_rate:
                self.counters["device_errors"] += storm.device_errors
                self._event("device", errors=storm.device_errors)
                if self.solver is not None:
                    inject_device_errors(self.solver, storm.device_errors)

        self.ticks += 1
        if self._gauges is not None:
            mean, mx = self.market.multiplier_stats()
            self._gauges["storm"].set(float(storms_active))
            self._gauges["ice"].set(float(len(self._held)))
            self._gauges["mult_mean"].set(round(mean, 4))
            self._gauges["mult_max"].set(round(mx, 4))
            self._gauges["ticks"].set(float(self.ticks))

    def _apply_outage(self, o) -> None:
        """Drive one SidecarOutage onto its endpoint handle (no-op when
        no handle is attached at that index — pure replay)."""
        if not (0 <= o.endpoint < len(self.sidecars)):
            return
        h = self.sidecars[o.endpoint]
        if o.mode == "kill":
            h.kill()
        elif o.mode == "hang":
            h.set_hang(True)
        elif o.mode == "junk":
            h.set_junk(True)

    def _restore_outage(self, o) -> None:
        if not (0 <= o.endpoint < len(self.sidecars)):
            return
        h = self.sidecars[o.endpoint]
        if o.mode == "kill":
            if o.restart_after:
                h.restart()
        elif o.mode == "hang":
            h.set_hang(False)
        elif o.mode == "junk":
            h.set_junk(False)

    def _apply_opkill(self, k) -> None:
        """Drive one OperatorKill onto its operator handle (no-op when
        no handle is attached at that index — pure replay)."""
        if not (0 <= k.target < len(self.operators)):
            return
        h = self.operators[k.target]
        if k.mode == "kill":
            h.kill()
        elif k.mode == "hang":
            h.set_hang(True)

    def _restore_opkill(self, k) -> None:
        if not (0 <= k.target < len(self.operators)):
            return
        h = self.operators[k.target]
        if k.mode == "kill":
            if k.restart_after:
                h.restart()
        elif k.mode == "hang":
            h.set_hang(False)

    def _burst(self, rng, idx: int, storm) -> None:
        """One storm tick: the deterministic part (junk count, timeline
        entry) draws from ``rng``; instance-targeted sampling draws from
        a per-tick ``:live`` stream so its draw COUNT (a function of how
        many instances happen to exist) can never desync the
        deterministic stream."""
        n_junk = 0
        if storm.junk_rate:
            whole = int(storm.junk_rate)
            n_junk = whole + (1 if rng.random() < storm.junk_rate - whole
                              else 0)
        self._event("storm-burst", storm=idx, junk=n_junk)
        if self.queue is None:
            return
        # storm index in the seed: two storms active on one tick must
        # draw INDEPENDENT sequences, not hit the same instances twice
        live = random.Random(f"{self.seed}:{self.ticks}:{idx}:live")
        from ..interruption.messages import (rebalance_recommendation,
                                             scheduled_change,
                                             spot_interruption, state_change)
        for j in range(n_junk):
            self.counters["junk_sent"] += 1
            self.counters["messages_sent"] += 1
            if (self.ticks + j) % 2 == 0:   # tick-phased: bursts of one
                # junk body still alternate the two junk classes
                # malformed: not even a dict
                self.queue.send(["weather", "junk", self.ticks])
            else:
                # well-formed but unknown (source, detail-type)
                self.queue.send({"version": "0", "source": "chaos.weather",
                                 "detail-type": "Cosmic Ray Notification",
                                 "detail": {"tick": self.ticks}})
        if self.cloud is None:
            return
        fam_of = self._fam_of
        targets = [
            inst for inst in self.cloud.peek_instances()
            if inst.capacity_type == "spot"
            and (not storm.zones or inst.zone in storm.zones)
            and (not storm.families
                 or (fam_of.get(inst.instance_type)
                     or inst.instance_type.split(".")[0])
                 in storm.families)]
        scheduled_batch: List[str] = []
        for inst in targets:
            if live.random() >= storm.intensity:
                continue
            roll = live.random()
            self.counters["messages_sent"] += 1
            if roll < 0.70:
                self.counters["spot_interruptions"] += 1
                self.queue.send(spot_interruption(inst.id))
            elif roll < 0.85:
                self.counters["rebalances"] += 1
                self.queue.send(rebalance_recommendation(inst.id))
            elif roll < 0.95:
                self.counters["state_changes"] += 1
                self.queue.send(state_change(inst.id, "stopping"))
            else:
                scheduled_batch.append(inst.id)
        if scheduled_batch:
            # health events arrive batched over affected entities
            self.counters["scheduled_changes"] += 1
            self.queue.send(scheduled_change(*scheduled_batch))

    # ---- teardown / restore --------------------------------------------

    def stop(self) -> None:
        """Restore fair weather: thaw every held pool, return the spot
        surface to its base prices (one more ``price_version`` bump so
        downstream memos re-key), and return the live gauges to their
        fair-weather readings (storms/ICE 0, multipliers 1.0 — the
        scrape must agree with the restored lattice; ``ticks`` keeps its
        final value, it is the timeline index). Injected device faults
        are NOT cleared here — the fault injector may be shared with
        ``--fault-schedule``; harnesses clear it explicitly at
        convergence."""
        with self._lock:
            self._stopped = True
            held = sorted(self._held)
            self._held.clear()
            if self.cloud is not None:
                for ct, it, z in held:
                    self.cloud.clear_capacity(ct, it, z)
            if self.unavailable is not None:
                for ct, it, z in held:
                    self.unavailable.delete(ct, it, z)
            if self.pricing is not None and self.market.base:
                self.pricing.update_spot_pricing(dict(self.market.base))
            # control-plane weather clears with the rest: every sidecar
            # handle returns to fair weather (alive, no hang/junk) so
            # the convergence tail runs against a healthy pool
            for h in self.sidecars:
                h.restore()
            # operator handles are deliberately NOT restored: a killed
            # leader staying dead is the handoff acceptance shape — the
            # promoted standby carries the convergence tail (a hung
            # runtime still stops cleanly; pause never blocks stop)
            if self._gauges is not None:
                self._gauges["storm"].set(0.0)
                self._gauges["ice"].set(0.0)
                self._gauges["mult_mean"].set(1.0)
                self._gauges["mult_max"].set(1.0)

    # ---- introspection --------------------------------------------------

    def stats(self) -> Dict:
        """The ``weather`` provider for the introspection registry (and
        the WEATHER row in ``kpctl top``)."""
        sc = self.scenario
        if self._stopped:
            # every live surface must agree after stop(): the lattice is
            # restored, the gauges read fair weather — so does this
            # provider (the recorded counters/timeline stay as evidence)
            mean = mx = 1.0
            active = 0
        else:
            mean, mx = self.market.multiplier_stats()
            now_s = self.ticks * sc.tick_seconds
            active = sum(1 for s in sc.storms
                         if s.at <= now_s < s.at + s.duration)
        out: Dict = {
            "scenario": sc.name,
            "seed": self.seed,
            "ticks": self.ticks,
            "storms_active": active,
            "ice_pools": len(self._held),
            "spot_mult_mean": round(mean, 4),
            "spot_mult_max": round(mx, 4),
            "timeline_events": len(self.timeline),
        }
        out.update(self.counters)
        return out

    def consolidation_advisory(self) -> Dict[str, object]:
        """Should voluntary consolidation HOLD right now? The engine's
        weather gate (solver/consolidate.py): consolidating INTO an
        active storm window or a spot-crash regime trades a standing
        node for capacity about to be reclaimed or repriced. Returns
        ``{"hold": bool, "reason": "storm" | "spot-crash" | ""}``.

        ICE spells deliberately never hold — an ice-age holds capacity
        OUT of the market, which makes consolidating onto what remains
        MORE valuable, not less. A crash regime is detected from the
        live regime targets (``_mu``): any family/zone pushed past
        :data:`CONSOL_HOLD_MU` (≈ a sustained 1.6× price surge) reads
        as distressed spot capacity."""
        if self._stopped:
            return {"hold": False, "reason": ""}
        sc = self.scenario
        now_s = self.ticks * sc.tick_seconds
        if any(s.at <= now_s < s.at + s.duration for s in sc.storms):
            return {"hold": True, "reason": "storm"}
        if any(mu >= CONSOL_HOLD_MU for mu in self._mu.values()):
            return {"hold": True, "reason": "spot-crash"}
        return {"hold": False, "reason": ""}

    def artifact(self, **extra) -> Dict:
        """The WEATHER artifact body (docs/reference/weather.md): the
        scenario, the deterministic timeline, the runtime counters, and
        whatever verdict fields the harness adds."""
        doc = {
            "scenario": self.scenario.to_dict(),
            "seed": self.seed,
            "ticks": self.ticks,
            "timeline": list(self.timeline),
            "counters": dict(self.counters),
        }
        doc.update(extra)
        return doc

    # ---- replay ---------------------------------------------------------

    @classmethod
    def replay(cls, scenario: WeatherScenario, lattice, ticks: int,
               seed: Optional[int] = None) -> List[Dict]:
        """Re-derive the deterministic weather timeline with no control
        plane attached: same scenario + seed + tick count ⇒ identical
        timeline, byte for byte. A soak's replay check compares this
        against the timeline its live run recorded."""
        sim = cls(scenario, lattice, seed=seed)
        sim.step(ticks)
        return sim.timeline


def inject_device_errors(solver, n: int) -> None:
    """Merge ``n`` device-error injections into the solver's (possibly
    operator-owned) FaultInjector — shared with tools/soak.py's
    ``--fault-schedule`` so the two compose instead of clobbering each
    other. Mutation takes the injector's own lock: the operator thread
    consumes device_errors concurrently via take_device_error."""
    from ..solver import FaultInjector
    inj = solver.faults or FaultInjector()
    with inj._lock:
        inj.device_errors += n
    solver.inject_faults(inj)
