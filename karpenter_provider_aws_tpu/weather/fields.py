"""The stochastic weather fields: spot market walk + ICE probability.

Both fields are pure state machines advanced one tick at a time by the
simulator; every random draw comes from the per-tick RNG the simulator
hands in, in a FIXED iteration order (sorted key lists), so the field
trajectory is a deterministic function of ``(scenario, seed)``. Nothing
here touches the control plane — the simulator applies the field state
through the pricing provider / cloud / ICE cache seams.
"""

from __future__ import annotations

import hashlib
import math
from typing import Dict, List, Tuple

from ..lattice import catalog as cat
from .scenario import IceSpell, WeatherScenario

Offering = Tuple[str, str, str]          # (capacity_type, type, zone)


class SpotMarketField:
    """Per-(family, zone) log-multiplier over the base spot market,
    evolving as a mean-reverting (Ornstein-Uhlenbeck) walk with regime
    shifts. Families share one walk per zone — real spot markets move
    capacity-pool-wise, and it keeps the field at ~hundreds of states
    instead of per-type thousands while ``prices()`` still re-prices
    every (type, zone) offering from its own base."""

    def __init__(self, lattice, scenario: WeatherScenario):
        self.scenario = scenario
        self._theta = scenario.market_theta
        self._sigma = scenario.market_sigma
        try:
            ci = lattice.capacity_types.index("spot")
        except ValueError:
            ci = None
        # base spot price per (type, zone), availability-filtered; reads
        # the spec's data-carried per-AZ price (spot_price_in — the
        # weather-repricing hot path its zone-map memo exists for) with
        # the synthetic discount model as fallback, exactly like the
        # lattice build
        self.base: Dict[Tuple[str, str], float] = {}
        self._fam_of: Dict[str, str] = {}
        if ci is not None:
            for ti, spec in enumerate(lattice.specs):
                self._fam_of[spec.name] = spec.family
                for zi, zone in enumerate(lattice.zones):
                    if not lattice.available[ti, zi, ci]:
                        continue
                    sp = spec.spot_price_in(zone)
                    self.base[(spec.name, zone)] = (
                        sp if sp is not None else cat.spot_price(spec, zone))
        # one walk per (family, zone) that has any spot offering
        self.keys: List[Tuple[str, str]] = sorted(
            {(self._fam_of[t], z) for (t, z) in self.base})
        self.x: Dict[Tuple[str, str], float] = {k: 0.0 for k in self.keys}

    def step(self, rng, mu_by_key: Dict[Tuple[str, str], float]) -> None:
        """One tick of the walk. ``mu_by_key`` carries the active regime
        targets; keys absent fall back to the scenario's base mu."""
        base_mu = self.scenario.market_mu
        for k in self.keys:
            mu = mu_by_key.get(k, base_mu)
            self.x[k] += (self._theta * (mu - self.x[k])
                          + self._sigma * rng.gauss(0.0, 1.0))

    def prices(self) -> Dict[Tuple[str, str], float]:
        """The full re-priced spot surface: {(type, zone): $/hr}."""
        fam = self._fam_of
        x = self.x
        return {(t, z): round(p * math.exp(x[(fam[t], z)]), 6)
                for (t, z), p in self.base.items()}

    def multiplier_stats(self) -> Tuple[float, float]:
        """(mean, max) price multiplier across the walks."""
        if not self.keys:
            return 1.0, 1.0
        mults = [math.exp(v) for v in self.x.values()]
        return sum(mults) / len(mults), max(mults)

    def digest(self) -> str:
        """Deterministic fingerprint of the walk state — what the
        timeline records per reprice so same-seed replays can be
        compared byte-for-byte without carrying thousands of prices."""
        h = hashlib.sha256()
        for k in self.keys:
            h.update(f"{k[0]}|{k[1]}|{self.x[k]:.9f};".encode())
        return h.hexdigest()[:16]


class IceField:
    """The insufficient-capacity field: while a spell is active, ~rate
    matching offerings per tick are chosen (deterministically, from the
    lattice's static offering list) and held out of the market for a
    deterministic number of ticks."""

    def __init__(self, lattice, scenario: WeatherScenario):
        self.scenario = scenario
        # static offering universe, sorted for deterministic sampling
        self._fam_of = {s.name: s.family for s in lattice.specs}
        self._universe: List[Offering] = []
        for ci, ct in enumerate(lattice.capacity_types):
            for ti, name in enumerate(lattice.names):
                for zi, zone in enumerate(lattice.zones):
                    if lattice.available[ti, zi, ci]:
                        self._universe.append((ct, name, zone))
        self._universe.sort()
        self._eligible: Dict[int, List[Offering]] = {}   # per spell index

    def _spell_pool(self, idx: int, spell: IceSpell) -> List[Offering]:
        pool = self._eligible.get(idx)
        if pool is None:
            pool = [o for o in self._universe
                    if o[0] in spell.capacity_types
                    and (not spell.zones or o[2] in spell.zones)
                    and (not spell.families
                         or self._fam_of[o[1]] in spell.families)]
            self._eligible[idx] = pool
        return pool

    def sample(self, rng, idx: int, spell: IceSpell,
               held: Dict[Offering, int], tick: int,
               tick_seconds: float) -> List[Tuple[Offering, int]]:
        """Choose this tick's newly-ICE'd offerings for one active spell:
        [(offering, thaw_tick)]. Consumes a FIXED number of rng draws per
        chosen offering, independent of control-plane state."""
        pool = self._spell_pool(idx, spell)
        if not pool:
            return []
        whole = int(spell.rate)
        k = whole + (1 if rng.random() < spell.rate - whole else 0)
        out: List[Tuple[Offering, int]] = []
        chosen = set()
        for _ in range(k):
            o = pool[rng.randrange(len(pool))]
            hold_s = spell.hold_seconds * (0.5 + rng.random())
            if o in held or o in chosen:
                # already iced (or drawn twice this tick): the draws
                # still happened (determinism), but the offering must
                # not double-count ice_marks / the timeline entry
                continue
            chosen.add(o)
            thaw = tick + max(1, int(hold_s / tick_seconds))
            out.append((o, thaw))
        return out
