"""Adversarial scenario weather (docs/reference/weather.md): replayable
spot-market + interruption-storm chaos driving the degradation ladder."""

from .fields import IceField, SpotMarketField
from .scenario import (IceSpell, NAMED_SCENARIOS, Regime, SidecarOutage,
                       Storm, WeatherScenario, load_scenario, named)
from .simulator import WeatherSimulator, inject_device_errors

__all__ = ["WeatherScenario", "Regime", "Storm", "IceSpell",
           "SidecarOutage", "NAMED_SCENARIOS", "named", "load_scenario",
           "SpotMarketField", "IceField",
           "WeatherSimulator", "inject_device_errors"]
