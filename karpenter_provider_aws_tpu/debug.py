"""Live-debug dumpers + time-series monitor for soak/scale harnesses.

The analog of the reference's scale-stratum debug tooling:
test/pkg/debug/{events,node,nodeclaim,pod,monitor}.go (watch dumpers that
print state deltas while a long test runs) and
test/pkg/environment/aws/metrics.go:66-119 (the duration-metric pipeline
that records provisioning/deprovisioning time series for later analysis).

- ``snapshot(op)`` — one structured sample of the control plane.
- ``Monitor`` — samples on an interval (or on demand) into a list and
  writes a JSON time-series artifact; tools/soak.py records one per run.
- ``dump_state(op)`` — a full human-readable dump (nodes with their pods,
  claims with phases, recent events) for failure diagnosis; the soak
  harness and tests print it when an invariant breaks.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional


def _committed_cost_per_hour(op) -> float:
    """$/hr of live capacity: registered nodes + unregistered launched
    claims (the bill the cluster is running up right now)."""
    lat = op.lattice
    total = 0.0
    # index maps built once per snapshot: the per-node list.index()
    # linear scans this replaces ran inside EVERY Monitor sample — at
    # soak scale that is O(nodes x zones) per second for a lookup the
    # lattice answers in O(1)
    z_idx = {z: i for i, z in enumerate(lat.zones)}
    c_idx = {c: i for i, c in enumerate(lat.capacity_types)}

    def price(itype, zone, cap):
        ti = lat.name_to_idx.get(itype)
        zi = z_idx.get(zone)
        if ti is None or zi is None:
            return 0.0
        ci = c_idx.get(cap, 0)
        p = float(lat.price[ti, zi, ci])
        return p if p == p and p != float("inf") else 0.0

    from .apis import wellknown as wk
    counted = set()
    for node in op.cluster.snapshot_nodes():
        total += price(node.labels.get(wk.LABEL_INSTANCE_TYPE, ""),
                       node.labels.get(wk.LABEL_ZONE, ""),
                       node.labels.get(wk.LABEL_CAPACITY_TYPE, "on-demand"))
        if node.node_claim:
            counted.add(node.node_claim)
    for claim in op.cluster.snapshot_claims():
        if claim.name in counted or claim.deletion_timestamp:
            continue
        if claim.instance_type:
            total += price(claim.instance_type, claim.zone or "",
                           claim.capacity_type or "on-demand")
    return round(total, 4)


def snapshot(op) -> Dict:
    """One structured control-plane sample (cheap: locked snapshots).

    ``subsystems`` rides the introspection registry (introspect/): the
    same per-subsystem stats /debug/vars serves, so soak artifacts carry
    batcher occupancy, cache residency, writer throughput, watch
    fan-out, and SLO burn as first-class series instead of the handful
    of ad-hoc counters this module used to hand-pick."""
    cluster = op.cluster
    claims = cluster.snapshot_claims()
    s = {
        "t": round(time.time(), 3),
        "sim_t": round(op.clock.now(), 3),
        "pending_pods": len(cluster.pending_pods()),
        "bound_pods": sum(1 for p in cluster.snapshot_pods()
                          if p.node_name is not None),
        "nodes": len(cluster.nodes),
        "claims": len(claims),
        "claims_deleting": sum(1 for c in claims if c.deletion_timestamp),
        "cost_per_hour": _committed_cost_per_hour(op),
        "ice_entries": sum(1 for _ in op.unavailable.entries()),
    }
    try:
        from . import introspect
        s["subsystems"] = introspect.registry().collect()
    except Exception:
        pass   # observability must never kill the monitor
    return s


class Monitor:
    """Time-series sampler over an Operator (the monitor.go analog).

    ``sample()`` on demand (deterministic loops), or ``start(interval)``
    for a daemon thread (the threaded soak). ``write(path)`` emits the
    JSON artifact: {"samples": [...], "summary": {...}}.
    """

    def __init__(self, op):
        self.op = op
        self.samples: List[Dict] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample(self) -> Dict:
        s = snapshot(self.op)
        with self._lock:
            self.samples.append(s)
        return s

    def start(self, interval: float = 1.0) -> "Monitor":
        def run():
            while not self._stop.is_set():
                try:
                    self.sample()
                except Exception:
                    pass   # the monitor must never kill the soak
                self._stop.wait(interval)
        self._stop.clear()
        self._thread = threading.Thread(target=run, name="debug-monitor",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)

    def summary(self) -> Dict:
        with self._lock:
            if not self.samples:
                return {}
            peak_nodes = max(s["nodes"] for s in self.samples)
            peak_pending = max(s["pending_pods"] for s in self.samples)
            peak_cost = max(s["cost_per_hour"] for s in self.samples)
            out = {
                "samples": len(self.samples),
                "wall_seconds": round(self.samples[-1]["t"]
                                      - self.samples[0]["t"], 3),
                "peak_nodes": peak_nodes,
                "peak_pending_pods": peak_pending,
                "peak_cost_per_hour": peak_cost,
                "final": self.samples[-1],
            }
            # the SLO burn envelope over the run (introspect/slo.py):
            # peak burn is what a soak asserts the paper's bars against
            burns = [s["subsystems"]["slo"] for s in self.samples
                     if "slo" in s.get("subsystems", {})]
            if burns:
                out["peak_latency_burn"] = max(
                    b.get("latency_burn", 0.0) for b in burns)
                out["peak_cost_burn"] = max(
                    b.get("cost_burn", 0.0) for b in burns)
            # the contention envelope (introspect/contention.py): the
            # worst lock wait any sample saw, and which lock —
            # `kpctl soak` prints it next to the burn peaks
            peak_lock, peak_wait = None, 0.0
            for s in self.samples:
                cont = s.get("subsystems", {}).get("contention", {})
                for k, v in cont.items():
                    if k.endswith("_max_wait_ms") and isinstance(
                            v, (int, float)) and v > peak_wait:
                        peak_wait = v
                        peak_lock = k[: -len("_max_wait_ms")]
            if peak_lock is not None:
                out["peak_lock_wait_ms"] = round(peak_wait, 3)
                out["peak_lock_wait_lock"] = peak_lock
            return out

    def write(self, path: str) -> None:
        """Write the artifact; a ``.gz`` suffix gzips it. Soak series
        grew to hundreds of KB per run (SOAK_r06 is ~18k lines each) —
        compressed artifacts keep the repo and CI uploads sane, and
        every reader goes through load_timeseries, which takes both."""
        with self._lock:
            doc = {"samples": list(self.samples)}
        doc["summary"] = self.summary()
        if path.endswith(".gz"):
            import gzip
            with gzip.open(path, "wt") as f:
                json.dump(doc, f, separators=(",", ":"))
        else:
            with open(path, "w") as f:
                json.dump(doc, f, indent=1)


def load_timeseries(path: str) -> Dict:
    """Read a Monitor artifact, gzipped or plain. Sniffs the gzip magic
    rather than trusting the suffix, so renamed/downloaded artifacts
    still load; kpctl and the analysis tooling route through here."""
    with open(path, "rb") as f:
        head = f.read(2)
    if head == b"\x1f\x8b":
        import gzip
        with gzip.open(path, "rt") as f:
            return json.load(f)
    with open(path, "r") as f:
        return json.load(f)


def dump_state(op, max_events: int = 40) -> str:
    """Full human-readable control-plane dump for failure diagnosis (the
    debug-watcher analog: nodes with their pods, claims with phases, ICE
    entries, the recent event tail)."""
    cluster = op.cluster
    lines: List[str] = ["=== control-plane dump ==="]
    lines.append(f"clock: {op.clock.now():.1f}")
    pods_by_node = cluster.pods_by_node()
    lines.append(f"-- nodes ({len(cluster.nodes)}):")
    for node in cluster.snapshot_nodes():
        from .apis import wellknown as wk
        pods = pods_by_node.get(node.name, [])
        taints = ",".join(t.key for t in node.taints) or "-"
        lines.append(
            f"  {node.name} {node.labels.get(wk.LABEL_INSTANCE_TYPE)}"
            f"/{node.labels.get(wk.LABEL_ZONE)}"
            f"/{node.labels.get(wk.LABEL_CAPACITY_TYPE)} "
            f"ready={node.ready} taints={taints} pods={len(pods)}")
        for p in pods[:10]:
            lines.append(f"      {p.name}"
                         + (" [ds]" if p.is_daemonset else ""))
    lines.append(f"-- claims ({len(cluster.claims)}):")
    for c in cluster.snapshot_claims():
        lines.append(
            f"  {c.name} phase={c.phase.value} type={c.instance_type} "
            f"zone={c.zone} deleting={bool(c.deletion_timestamp)}")
    pending = cluster.pending_pods()
    lines.append(f"-- pending pods ({len(pending)}):")
    for p in pending[:20]:
        lines.append(f"  {p.name} requests={dict(p.requests)}")
    ice = list(op.unavailable.entries())
    lines.append(f"-- ICE entries ({len(ice)}):")
    for e in ice[:10]:
        lines.append(f"  {e}")
    try:
        events = op.recorder.events()[-max_events:]
        lines.append(f"-- recent events ({len(events)}):")
        for ev in events:
            lines.append(f"  [{ev.type}] {ev.object_kind}/{ev.object_name} "
                         f"{ev.reason}: {ev.message}")
    except Exception:
        pass
    return "\n".join(lines)
