from .ttl import TTLCache
from .unavailable import UnavailableOfferings

# Cache TTLs (reference pkg/cache/cache.go:19-43)
DEFAULT_TTL = 60.0                    # 1 min
UNAVAILABLE_OFFERINGS_TTL = 180.0     # 3 min (ICE memory)
INSTANCE_TYPES_TTL = 300.0            # 5 min
INSTANCE_PROFILE_TTL = 900.0          # 15 min

__all__ = ["TTLCache", "UnavailableOfferings", "DEFAULT_TTL",
           "UNAVAILABLE_OFFERINGS_TTL", "INSTANCE_TYPES_TTL", "INSTANCE_PROFILE_TTL"]
