"""Unavailable-offerings (ICE) cache → device availability mask.

Mirror of the reference's ICE feedback loop (reference
pkg/cache/unavailableofferings.go:31-84): CreateFleet insufficient-capacity
errors mark (capacityType, instanceType, zone) unavailable for 3 minutes;
a monotonically increasing sequence number invalidates downstream caches
keyed on the offering set. The TPU-native addition is ``mask(lattice)``:
the cache compiles directly to a boolean [T,Z,C] tensor that is ANDed with
the lattice's market availability before each solve, so ICE'd offerings
vanish from the device kernel's reachability einsum instead of being
re-filtered per pod in a host loop.
"""

from __future__ import annotations

import threading
from typing import Iterable, Optional, Tuple

import numpy as np

from ..errors import Offering, UnfulfillableCapacityError
from ..utils.clock import Clock
from .ttl import TTLCache

UNAVAILABLE_OFFERINGS_TTL = 180.0  # 3 min (reference pkg/cache/cache.go:27-29)


class UnavailableOfferings:
    def __init__(self, clock: Optional[Clock] = None, ttl: float = UNAVAILABLE_OFFERINGS_TTL):
        # expiry bumps seq through the evict hook, whichever path drops
        # the entry — the periodic cleanup() sweep or a lazy delete
        # inside TTLCache.get/__contains__ (is_unavailable between
        # expiry and the next sweep). Version-keyed consumers
        # (masked_view_versioned's memo, the disruption controller's
        # failed-search fingerprints) would otherwise keep a recovered
        # offering off-market until an unrelated mark happened to bump.
        self._cache = TTLCache(ttl, clock, on_evict=lambda _k, _v: self._bump())
        self._seq = 0
        self._lock = threading.Lock()

    def _bump(self) -> None:
        with self._lock:
            self._seq += 1

    @staticmethod
    def _key(capacity_type: str, instance_type: str, zone: str) -> str:
        return f"{capacity_type}:{instance_type}:{zone}"

    @property
    def seq_num(self) -> int:
        with self._lock:
            return self._seq

    def is_unavailable(self, capacity_type: str, instance_type: str, zone: str) -> bool:
        return self._key(capacity_type, instance_type, zone) in self._cache

    def mark_unavailable(self, reason: str, capacity_type: str,
                         instance_type: str, zone: str) -> None:
        self._cache.set(self._key(capacity_type, instance_type, zone), reason)
        with self._lock:
            self._seq += 1

    def mark_unavailable_for_error(self, err: UnfulfillableCapacityError,
                                   reason: str = "InsufficientInstanceCapacity") -> None:
        """Mirror of MarkUnavailableForFleetErr (unavailableofferings.go:55-65)."""
        for capacity_type, instance_type, zone in err.offerings:
            self.mark_unavailable(reason, capacity_type, instance_type, zone)

    def delete(self, capacity_type: str, instance_type: str, zone: str) -> None:
        self._cache.delete(self._key(capacity_type, instance_type, zone))
        with self._lock:
            self._seq += 1

    def flush(self) -> None:
        self._cache.flush()
        with self._lock:
            self._seq += 1

    def cleanup(self) -> int:
        """Expire stale entries. Expiry CHANGES the offering set (capacity is
        back on the market), so it bumps seq_num like marking does —
        downstream fingerprints (e.g. the disruption controller's failed-
        search cache) must invalidate when offerings return. The bump
        itself rides the evict hook (see __init__), once per entry."""
        return self._cache.cleanup()

    def stats(self) -> dict:
        """Introspection snapshot: ICE'd offering count + the sequence
        number downstream version-keyed caches invalidate on."""
        out = self._cache.stats()
        out["seq"] = self.seq_num
        return out

    def entries(self) -> Iterable[Offering]:
        for key, _ in self._cache.items():
            ct, it, z = key.split(":", 2)
            yield (ct, it, z)

    def mask(self, lattice) -> np.ndarray:
        """[T,Z,C] bool: True where the offering is NOT ICE'd. AND with
        ``lattice.available`` before building/solving a problem."""
        return mask_from_entries(lattice, self.entries())


def mask_from_entries(lattice, entries) -> np.ndarray:
    """[T,Z,C] bool mask from (capacity_type, instance_type, zone)
    triples: True where the offering is NOT named. Shared by the ICE
    cache above and the solver sidecar, which receives the operator's
    triples over the Solve RPC and rebuilds the SAME mask against its
    resident lattice (parallel/sidecar.py) — one implementation, so the
    two processes can never disagree on skip-unknown semantics."""
    m = np.ones((lattice.T, lattice.Z, lattice.C), dtype=bool)
    t_idx = lattice.name_to_idx
    z_idx = {z: i for i, z in enumerate(lattice.zones)}
    c_idx = {c: i for i, c in enumerate(lattice.capacity_types)}
    for ct, it, z in entries:
        ti, zi, ci = t_idx.get(it), z_idx.get(z), c_idx.get(ct)
        if ti is not None and zi is not None and ci is not None:
            m[ti, zi, ci] = False
    return m
