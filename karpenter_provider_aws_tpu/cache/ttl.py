"""TTL cache with eviction callbacks.

Mirror of the reference's patrickmn/go-cache usage (reference
pkg/cache/cache.go): per-entry expiry, periodic cleanup, and an on-evict
hook (the launch-template provider GCs stale cloud templates from its
eviction callback, reference pkg/providers/launchtemplate/launchtemplate.go:372-389).
Thread-safe; time injected via Clock for deterministic tests.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

from ..utils.clock import Clock


class TTLCache:
    def __init__(self, ttl: float, clock: Optional[Clock] = None,
                 on_evict: Optional[Callable[[str, Any], None]] = None):
        self.ttl = ttl
        self._clock = clock or Clock()
        self._on_evict = on_evict
        self._data: Dict[str, Tuple[Any, float]] = {}
        self._lock = threading.RLock()

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            entry = self._data.get(key)
            if entry is None:
                return default
            value, expires = entry
            if expires <= self._clock.now():
                del self._data[key]
                evict = self._on_evict
            else:
                return value
        if evict is not None:
            evict(key, value)
        return default

    def __contains__(self, key: str) -> bool:
        sentinel = object()
        return self.get(key, sentinel) is not sentinel

    def set(self, key: str, value: Any, ttl: Optional[float] = None) -> None:
        with self._lock:
            self._data[key] = (value, self._clock.now() + (ttl if ttl is not None else self.ttl))

    def get_or_compute(self, key: str, compute: Callable[[], Any],
                       ttl: Optional[float] = None) -> Any:
        sentinel = object()
        v = self.get(key, sentinel)
        if v is not sentinel:
            return v
        v = compute()
        self.set(key, v, ttl)
        return v

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)

    def flush(self) -> None:
        with self._lock:
            self._data.clear()

    def cleanup(self) -> int:
        """Drop expired entries (reference runs this on a 10s interval for the
        ICE cache, cache.go:39-42). Returns number evicted."""
        now = self._clock.now()
        evicted = []
        with self._lock:
            for k in list(self._data):
                v, exp = self._data[k]
                if exp <= now:
                    del self._data[k]
                    evicted.append((k, v))
        if self._on_evict is not None:
            for k, v in evicted:
                self._on_evict(k, v)
        return len(evicted)

    def stats(self) -> Dict[str, float]:
        """Introspection snapshot: stored entries (including not-yet-swept
        expired ones — the ``live`` count pays the expiry scan) and the
        configured TTL."""
        now = self._clock.now()
        with self._lock:
            stored = len(self._data)
            live = sum(1 for _, exp in self._data.values() if exp > now)
        return {"entries": stored, "live": live, "ttl_seconds": self.ttl}

    def items(self) -> Iterator[Tuple[str, Any]]:
        now = self._clock.now()
        with self._lock:
            return iter([(k, v) for k, (v, exp) in self._data.items() if exp > now])

    def __len__(self) -> int:
        return sum(1 for _ in self.items())
