from .provisioning import Provisioner
from .lifecycle import LifecycleController
from .garbagecollection import GarbageCollectionController
from .termination import TerminationController

__all__ = ["Provisioner", "LifecycleController", "GarbageCollectionController",
           "TerminationController"]
