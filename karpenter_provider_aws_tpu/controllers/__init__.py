from .provisioning import Provisioner
from .lifecycle import LifecycleController
from .garbagecollection import GarbageCollectionController
from .termination import TerminationController
from .disruption import DisruptionController
from .tagging import TaggingController

__all__ = ["Provisioner", "LifecycleController", "GarbageCollectionController",
           "TerminationController", "DisruptionController", "TaggingController"]
