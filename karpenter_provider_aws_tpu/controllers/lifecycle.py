"""NodeClaim lifecycle: launch → register → initialize, with liveness GC.

Mirror of the core nodeclaim lifecycle state machine (reference: NodeClaim
CRD status conditions, metrics karpenter_nodeclaims_{launched,registered,
initialized} per website reference/metrics.md:76-97). The simulated kubelet
registers a Node a configurable delay after launch (stratum-2 "no real
cluster" testing, like the reference's envtest + fake EC2); claims that
never register within the liveness TTL are deleted and relaunched by the
next provisioning pass (core's 15-minute registration liveness).
"""

from __future__ import annotations

import itertools
from typing import List, Optional

from .. import trace
from ..apis import wellknown as wk
from ..apis.objects import Lease, Node, NodeClaim, NodeClaimPhase
from ..cloudprovider.cloudprovider import CloudProvider
from ..errors import NotFoundError
from ..events import Recorder
from ..metrics import Registry, wire_core_metrics
from ..state.cluster import ClusterState
from ..utils.clock import Clock

REGISTRATION_TTL = 15 * 60.0   # core liveness: claims must register in 15 min


class LifecycleController:
    def __init__(self, cluster: ClusterState, cloud_provider: CloudProvider,
                 recorder: Optional[Recorder] = None, clock: Optional[Clock] = None,
                 registration_delay: float = 5.0,
                 metrics: Optional[Registry] = None,
                 writer=None):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock or Clock()
        from ..kube.writer import DirectWriter
        self.writer = writer or DirectWriter(cluster, self.clock)
        self.recorder = recorder or Recorder(self.clock)
        self.registration_delay = registration_delay
        m = wire_core_metrics(metrics or Registry())
        self._m_registered = m["nodeclaims_registered"]
        self._m_initialized = m["nodeclaims_initialized"]

    def reconcile(self) -> None:
        now = self.clock.now()
        for claim in list(self.cluster.claims.values()):
            if claim.deletion_timestamp:
                continue
            if claim.phase == NodeClaimPhase.LAUNCHED:
                if claim.launched_at is not None and now - claim.launched_at >= self.registration_delay:
                    node = self._register(claim)
                    # sim nodes are born Ready; pass the node we just
                    # registered — in API mode the mirror only learns of
                    # it at the next informer pump
                    self._initialize(claim, node=node)
                elif now - claim.created_at > REGISTRATION_TTL:
                    self._liveness_delete(claim, "registration deadline exceeded")
            elif claim.phase == NodeClaimPhase.PENDING:
                if now - claim.created_at > REGISTRATION_TTL:
                    self._liveness_delete(claim, "launch deadline exceeded")
            elif claim.phase == NodeClaimPhase.REGISTERED:
                self._initialize(claim)

    def _register(self, claim: NodeClaim) -> "Node":
        """Simulated kubelet joins the node and binds nominated pods.
        The registration span re-joins the provisioning pass's trace via
        the claim's traceparent annotation — the LAST hop of the causal
        chain (REST write → batch → solve → CreateFleet → registration),
        crossing the launch delay the claim spent in the cloud."""
        tp = claim.annotations.get(wk.ANNOTATION_TRACEPARENT)
        if tp is None:
            # no originating trace: registering under a fresh root would
            # only churn the recorder ring with single-span noise
            return self._register_traced(claim)
        with trace.span("nodeclaim.register", parent=tp,
                        nodeclaim=claim.name, nodepool=claim.node_pool):
            return self._register_traced(claim)

    def _register_traced(self, claim: NodeClaim) -> "Node":
        node = Node(
            name=claim.name, provider_id=claim.provider_id or "",
            internal_ip=claim.internal_ip,
            labels=dict(claim.labels), taints=list(claim.taints),
            capacity=dict(claim.capacity), allocatable=dict(claim.allocatable),
            ready=True, created_at=self.clock.now(),
            node_pool=claim.node_pool, node_claim=claim.name)
        # the (fake) kubelet joins the node and creates its coordination
        # lease — through the writer seam, like every k8s-object write
        self.writer.register_node(node, Lease(
            name=node.name, owner_node=node.name,
            created_at=self.clock.now()))
        # all of the claim's nominated pods bind as ONE coalesced write
        # (the apiserver bulk verb in API mode): registration of a
        # full node used to pay lock + watch fan-out per pod
        self.writer.bind_pods([(pod.name, node.name)
                               for pod in self.cluster.nominated_pods(claim.name)])
        claim.phase = NodeClaimPhase.REGISTERED
        claim.registered_at = self.clock.now()
        self.writer.update_claim_status(claim)
        self._m_registered.inc(nodepool=claim.node_pool)
        self.recorder.publish("Normal", "Registered", "NodeClaim", claim.name,
                              f"node {node.name} joined")
        return node

    def _initialize(self, claim: NodeClaim, node=None) -> None:
        """Registered → Initialized once the node is Ready and startup
        taints cleared (the sim node is born ready)."""
        if node is None:
            node = self.cluster.node_for_claim(claim.name)
        if node is None or not node.ready:
            return
        claim.phase = NodeClaimPhase.INITIALIZED
        claim.initialized_at = self.clock.now()
        self.writer.update_claim_status(claim)
        self._m_initialized.inc(nodepool=claim.node_pool)
        self.recorder.publish("Normal", "Initialized", "NodeClaim", claim.name, "")

    def _liveness_delete(self, claim: NodeClaim, reason: str) -> None:
        self.recorder.publish("Warning", "LivenessFailure", "NodeClaim", claim.name, reason)
        if claim.provider_id is not None:
            try:
                self.cloud_provider.delete(claim)
            except NotFoundError:
                pass
        # the instance (if any) is gone and no node ever registered: a
        # hard delete, no drain/finalizer round needed
        self.writer.rollback_claim(claim.name)
