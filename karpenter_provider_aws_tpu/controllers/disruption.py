"""Disruption controller: expiration → drift → emptiness → consolidation.

Mirror of the core disruption orchestration (reference website
concepts/disruption.md:16-27 method order; designs/consolidation.md
deletion-vs-replacement and cost rules; budgets math disruption.md:193-222
+ CRD karpenter.sh_nodepools.yaml:55-100). The consolidation simulation —
"remove candidate set S: do its pods fit on the remaining nodes plus at
most one new, cheaper node?" — is exactly a what-if Solve() on the device:
candidate bins drop out of the existing-bin table, their pods re-enter as
pending, and the same grouped-FFD kernel answers feasibility and the
replacement's price in one pass (SURVEY.md §2.2: the second workload the
north star moves on-device).

Method semantics:
- expiration: claims older than the pool's expire_after are replaced.
- drift: CloudProvider.IsDrifted or a NodePool template-hash mismatch
  (feature-gated, settings.md:40-47).
- emptiness: nodes with no non-daemonset pods after consolidate_after are
  deleted in parallel (disruption.md:93 "empty nodes first").
- consolidation (WhenUnderutilized): multi-node first — the largest
  candidate prefix (sorted by disruption cost) whose pods repack onto the
  remaining capacity + ≤1 cheaper node — then single-node scan
  (disruption.md:93-98). Spot→spot replacement requires ≥15-type
  flexibility and its feature gate (disruption.md:129).

Replacement safety: replacements launch FIRST; originals are drained only
after every replacement's node registers (disruption.md:23-25).

The consolidation method's what-if dispatch, zero-leg probe cache, host
fallback, savings referee, weather gate, and "why NOT consolidated" skip
ledger live in solver/consolidate.ConsolidationEngine (constructed here as
``self.engine``; docs/reference/consolidation.md). This controller keeps
the policy: method order, budgets, candidate ranking, the prefix ladder +
single-node scan, and launch-before-drain.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import trace
from ..apis import wellknown as wk
from ..apis.objects import NodeClaim, NodeClaimPhase, NodePool, Pod
from ..cache.unavailable import UnavailableOfferings
from ..cloudprovider.cloudprovider import CloudProvider
from ..errors import UnfulfillableCapacityError
from ..events import Recorder
from ..lattice.tensors import masked_view_versioned
from ..metrics import Registry, wire_core_metrics
from ..solver import taxonomy
from ..solver.consolidate import ConsolidationEngine
from ..solver.solve import NodePlan, ProbeResult, Solver
from ..state.cluster import ClusterState
from ..utils.clock import Clock
from .provisioning import Provisioner, nodepool_hash
from .termination import TerminationController

SPOT_TO_SPOT_MIN_TYPES = 15   # disruption.md:129
CONSOLIDATION_SAVINGS_EPS = 1e-4


@dataclass
class DisruptionAction:
    reason: str                       # Expired | Drifted | Empty | Underutilized
    claims: List[str]                 # originals to remove
    replacements: List[str] = field(default_factory=list)  # claim names launched
    def __post_init__(self):
        self.claims = list(self.claims)


class DisruptionController:
    def __init__(self, cluster: ClusterState, solver: Solver,
                 node_pools: Dict[str, NodePool],
                 cloud_provider: CloudProvider,
                 provisioner: Provisioner,
                 termination: TerminationController,
                 unavailable: UnavailableOfferings,
                 recorder: Optional[Recorder] = None,
                 clock: Optional[Clock] = None,
                 drift_enabled: bool = True,
                 spot_to_spot_consolidation: bool = False,
                 metrics: Optional[Registry] = None,
                 writer=None):
        self.cluster = cluster
        self.solver = solver
        self.node_pools = node_pools
        self.cloud_provider = cloud_provider
        self.provisioner = provisioner
        self.termination = termination
        self.unavailable = unavailable
        self.clock = clock or Clock()
        from ..kube.writer import DirectWriter
        self.writer = writer or DirectWriter(cluster, self.clock)
        self.recorder = recorder or Recorder(self.clock)
        self.drift_enabled = drift_enabled
        self.spot_to_spot_consolidation = spot_to_spot_consolidation
        m = wire_core_metrics(metrics or Registry())
        self._m_disrupted = m["nodeclaims_disrupted"]
        self._in_flight: List[DisruptionAction] = []
        # per-pass what-if budget (the reference bounds each disruption loop
        # with a timeout; we bound by solve count) + a state fingerprint so
        # an unchanged cluster never re-runs a failed consolidation search
        self.max_whatif_per_pass = 16
        self._whatif_used = 0
        self._last_failed_fingerprint = None
        # where the next pass's single-node scan resumes after a
        # budget-truncated pass (so repeat passes verify NEW candidates
        # instead of deterministically repeating the same window)
        self._scan_cursor = 0
        # coverage accounting for the negative cache: a failed pass may
        # only be cached once every candidate in the frontier has been
        # probed as a single under the CURRENT fingerprint — a pass whose
        # probe window or what-if budget covered part of the frontier
        # proved nothing about the rest (see _reconcile_once)
        self._covered: set = set()
        self._last_search_fp = None
        self._last_frontier: set = set()
        self._search_truncated = False
        # the vmapped what-if engine: batched candidate dispatch, zero-leg
        # probe cache, host fallback, savings referee, weather gate, and
        # the per-node skip-reason ledger (kpctl explain node)
        self.engine = ConsolidationEngine(
            cluster, solver, node_pools, unavailable, clock=self.clock,
            metrics=metrics, audit=getattr(provisioner, "explain", None))
        # (node, pdb) pairs whose Unconsolidatable event already published
        # for the current blockage episode (see _candidates)
        self._pdb_blocked_logged: set = set()
        # parsed budget schedules (False = invalid), per controller
        self._cron_cache: Dict[str, object] = {}
        # (schedule, duration) -> (valid_until, active): windows open only
        # at minute marks, so a closed verdict holds to the next minute;
        # an open one re-verifies each minute (it may linger <=60s past a
        # mid-minute close — the conservative, MORE-constrained direction)
        self._window_cache: Dict[Tuple[str, float], Tuple[float, bool]] = {}

    # one batched probe covers the prefix ladder + single-node scan; caps
    # bound the padded K bucket (solver.Solver._K_BUCKETS)
    MAX_PREFIX_PROBES = 16
    MAX_SINGLE_PROBES = 16

    # ---- budgets (disruption.md:193-222) ---------------------------------

    def _allowed_disruptions(self, pool: NodePool, reason: str) -> int:
        total = sum(1 for c in self.cluster.snapshot_claims()
                    if c.node_pool == pool.name and not c.deletion_timestamp)
        disrupting = sum(1 for a in self._in_flight for n in a.claims
                         if n in self.cluster.claims
                         and self.cluster.claims[n].node_pool == pool.name)
        allowed = total
        for budget in pool.disruption.budgets:
            if budget.reasons and reason not in budget.reasons:
                continue
            if budget.schedule is not None and not self._budget_active(budget):
                # a scheduled budget constrains only inside its window
                # (disruption.md:193-222; CRD requires schedule+duration
                # together — webhooks.validate_node_pool enforces that)
                continue
            spec = str(budget.nodes)
            if spec.endswith("%"):
                # percentages round UP (disruption.md: "4 disruptions ...
                # rounding up from 19 * .2 = 3.8")
                val = int(np.ceil(total * float(spec[:-1]) / 100.0))
            else:
                val = int(spec)
            allowed = min(allowed, max(val, 0))
        return max(allowed - disrupting, 0)

    def _budget_active(self, budget) -> bool:
        """Is the budget's scheduled window open right now? (An invalid
        schedule — rejected by admission anyway — never constrains.)

        Results memoize per (schedule, duration): an open window stays
        open until its close; a closed one cannot open before the next
        whole minute — so the lookback scan runs at most once a minute
        per budget instead of on every reconcile and fingerprint."""
        from ..utils.cron import Cron
        cron = self._cron_cache.get(budget.schedule)
        if cron is None:
            try:
                cron = Cron(budget.schedule)
            except ValueError:
                cron = False
            self._cron_cache[budget.schedule] = cron
        if cron is False:
            return False
        now = self.clock.now()
        duration = budget.duration or 0.0
        key = (budget.schedule, duration)
        cached = self._window_cache.get(key)
        if cached is not None and now < cached[0]:
            return cached[1]
        active = cron.in_window(now, duration)
        valid_until = (now // 60 + 1) * 60 if not active else now + 60.0
        self._window_cache[key] = (valid_until, active)
        return active

    def _budget_window_state(self) -> Tuple:
        """(pool, budget index, active) for every scheduled budget — part
        of the consolidation fingerprint: a window opening or closing is
        pure time passage that changes what disruption may do, so it must
        re-arm a negative-cached search."""
        out = []
        for pool in self.node_pools.values():
            for i, b in enumerate(pool.disruption.budgets):
                if b.schedule is not None:
                    out.append((pool.name, i, self._budget_active(b)))
        return tuple(out)

    # ---- candidate discovery --------------------------------------------

    def _candidates(self) -> List[NodeClaim]:
        """Initialized, healthy, not-already-disrupting claims with a
        registered node. Voluntary-disruption opt-outs are respected here:
        a `karpenter.sh/do-not-disrupt` annotation on the claim (NodePool
        template annotations land there), on the node, or on any of its
        pods removes the node from candidacy (reference
        disruption.md:253,282,294), and so does a pod whose
        PodDisruptionBudgets currently allow zero evictions (the
        `pdb ... prevents pod evictions` Unconsolidatable condition,
        disruption.md:112)."""
        in_flight = {n for a in self._in_flight for n in a.claims}
        node_by_claim = self.cluster.nodes_by_claim()
        # unfiltered: a do-not-disrupt DAEMONSET pod pins its node too;
        # pdb_blockers applies its own daemonset exemption
        pods_by_node = self.cluster.pods_by_node()
        # allowance is node-independent: one sweep for the whole pass
        zero_pdbs = self.cluster.zero_allowance_pdbs()
        blocked_now: set = set()
        out = []
        for claim in self.cluster.snapshot_claims():
            if claim.deletion_timestamp or claim.name in in_flight:
                continue
            if claim.phase != NodeClaimPhase.INITIALIZED:
                continue
            if claim.name not in node_by_claim:
                continue
            if claim.node_pool not in self.node_pools:
                continue
            node = node_by_claim[claim.name]
            if (claim.annotations.get(wk.ANNOTATION_DO_NOT_DISRUPT) == "true"
                    or node.annotations.get(wk.ANNOTATION_DO_NOT_DISRUPT) == "true"):
                continue
            pods = pods_by_node.get(node.name, [])
            if any(p.annotations.get(wk.ANNOTATION_DO_NOT_DISRUPT) == "true"
                   for p in pods):
                continue
            blocked = self.cluster.pdb_blockers(pods, zero_pdbs=zero_pdbs)
            if blocked:
                pod, pdb = next(iter(blocked.items()))
                # publish once per (node, pdb) blockage episode, not per
                # pass — _candidates runs from every disruption method
                # every reconcile and the recorder must not flood
                key = (node.name, pdb)
                blocked_now.add(key)
                if key not in self._pdb_blocked_logged:
                    self._pdb_blocked_logged.add(key)
                    self.recorder.publish(
                        "Normal", "Unconsolidatable", "Node", node.name,
                        f"pdb {pdb} prevents pod evictions (pod {pod})")
                    # same episode dedup keeps the event, the skip metric
                    # label, and the explain ledger in lockstep
                    self.engine.note_skip(
                        node.name, taxonomy.NOT_CONSOLIDATABLE_PDB,
                        f"pdb {pdb} prevents pod evictions (pod {pod})")
                continue
            out.append(claim)
        # unblocked pairs may re-publish if they block again later
        self._pdb_blocked_logged &= blocked_now
        return out

    def _pods_on(self, claim: NodeClaim) -> List[Pod]:
        node = self.cluster.node_for_claim(claim.name)
        if node is None:
            return []
        return [p for p in self.cluster.snapshot_pods()
                if p.node_name == node.name and not p.is_daemonset]

    def _disruption_cost(self, claim: NodeClaim) -> float:
        """Cheapest-to-disrupt first (consolidation.md disruption-cost
        scoring: fewer/lower-priority pods = cheaper to move)."""
        return float(sum(1 + p.priority for p in self._pods_on(claim)))

    # ---- what-if solve (the on-device consolidation query) ---------------

    def _removed_price(self, lattice, removed: Sequence[NodeClaim]) -> float:
        total = 0.0
        for c in removed:
            ti = lattice.name_to_idx.get(c.instance_type)
            if ti is None:
                continue
            zi = lattice.zones.index(c.zone) if c.zone in lattice.zones else 0
            ci = (lattice.capacity_types.index(c.capacity_type)
                  if c.capacity_type in lattice.capacity_types else 0)
            p = self.solver.lattice.price[ti, zi, ci]
            total += float(p) if np.isfinite(p) else 0.0
        return total

    def _what_if(self, removed: Sequence[NodeClaim]) -> Tuple[NodePlan, float]:
        """Solve the cluster with `removed` gone; returns (plan, removed $/hr).

        A candidate's node can vanish between candidate selection and this
        solve (interruption/GC run concurrently under the threaded
        runtime). Vanished-node claims are filtered from the WHOLE
        what-if — exclusion set, pod set, AND the removed price — with one
        consistent snapshot: counting a gone claim's price while
        re-placing none of its pods would over-credit the savings and
        admit unprofitable disruptions."""
        self._whatif_used += 1
        lattice = masked_view_versioned(self.solver.lattice,
                                        self.unavailable)
        node_by_claim = self.cluster.nodes_by_claim()
        by_node = self.cluster.pods_by_node(include_daemonsets=False)
        live = [c for c in removed if c.name in node_by_claim]
        removed_nodes = {node_by_claim[c.name].name for c in live}
        pods = [p for c in live
                for p in by_node.get(node_by_claim[c.name].name, ())]
        existing = [b for b in self.cluster.existing_bins(lattice)
                    if b.name not in removed_nodes
                    and b.name not in {c.name for c in live}]
        bound = [bp for bp in self.cluster.bound_pods()
                 if bp.node_name not in removed_nodes]
        pvcs, storage_classes = self.cluster.volume_state()
        plan = self.solver.solve_relaxed(
            pods, list(self.node_pools.values()), lattice,
            existing=existing, daemonset_pods=self.cluster.daemonset_pods(),
            bound_pods=bound, pvcs=pvcs, storage_classes=storage_classes)
        return plan, self._removed_price(lattice, live)

    def _probe_whatifs(self, removed_sets: Sequence[Sequence[NodeClaim]],
                       node_by_claim=None, by_node=None):
        """All of a pass's what-ifs as ONE batched device call — delegated
        to ConsolidationEngine.probe (solver/consolidate.py), which adds
        the zero-leg probe cache and the vmapped-envelope host-fallback
        split. Pods are probed with their soft constraints fully relaxed —
        the loosest state solve_relaxed can reach — so a probe's infeasible
        verdict is trustworthy while a feasible one is optimistic; the
        winning probe is re-verified by one exact _what_if before any node
        is touched. Returns [(ProbeResult, removed $/hr)] aligned with
        removed_sets."""
        verdicts = self.engine.probe(removed_sets,
                                     node_by_claim=node_by_claim,
                                     by_node=by_node)
        return [(v.probe, v.removed_price) for v in verdicts]

    def _within_budgets(self, removed: Sequence[NodeClaim],
                        reason: str) -> bool:
        """Cheap host-side mirror of _begin's per-pool budget gate, so the
        search never pays an exact device solve for a candidate set the
        budget is guaranteed to reject."""
        counts: Dict[str, int] = {}
        for c in removed:
            counts[c.node_pool] = counts.get(c.node_pool, 0) + 1
        return all(
            self._allowed_disruptions(self.node_pools[p], reason) >= n
            for p, n in counts.items())

    def _probe_ok(self, removed: Sequence[NodeClaim], pr,
                  removed_price: float) -> bool:
        """The consolidation criterion on probe aggregates (mirrors the
        exact-plan checks in _reconcile_consolidation)."""
        if not pr.feasible or pr.n_new > 1:
            return False
        if pr.new_cost >= removed_price - CONSOLIDATION_SAVINGS_EPS:
            return False
        if (pr.n_new == 1 and pr.new_cap_type == wk.CAPACITY_TYPE_SPOT
                and any(c.capacity_type == wk.CAPACITY_TYPE_SPOT
                        for c in removed)):
            if not self.spot_to_spot_consolidation:
                return False
            if pr.flex < SPOT_TO_SPOT_MIN_TYPES:
                return False
        return True

    def _spot_guard_ok(self, removed: Sequence[NodeClaim], plan: NodePlan) -> bool:
        """Spot→spot single-node replacement needs ≥15-type flexibility and
        the feature gate (disruption.md:129)."""
        if not plan.new_nodes:
            return True
        if not any(c.capacity_type == wk.CAPACITY_TYPE_SPOT for c in removed):
            return True
        if not any(n.capacity_type == wk.CAPACITY_TYPE_SPOT for n in plan.new_nodes):
            return True
        if not self.spot_to_spot_consolidation:
            return False
        return all(len(n.feasible_types) >= SPOT_TO_SPOT_MIN_TYPES
                   for n in plan.new_nodes
                   if n.capacity_type == wk.CAPACITY_TYPE_SPOT)

    # ---- reconcile --------------------------------------------------------

    def _consolidatable(self) -> List[NodeClaim]:
        """Candidates whose pool policy + consolidate_after window currently
        allow consolidation."""
        now = self.clock.now()
        out = []
        for claim in self._candidates():
            pool = self.node_pools[claim.node_pool]
            if pool.disruption.consolidation_policy != "WhenUnderutilized":
                continue
            after = pool.disruption.consolidate_after
            if after is not None:
                ref = claim.initialized_at or claim.created_at
                if now - ref < after:
                    continue
            out.append(claim)
        return out

    def _fingerprint(self, consolidatable: Optional[Sequence[NodeClaim]] = None):
        if consolidatable is None:
            consolidatable = self._consolidatable()
        return (
            tuple(sorted((p.name, p.node_name or "") for p in self.cluster.snapshot_pods())),
            tuple(sorted(self.cluster.claims)),
            self.unavailable.seq_num,
            # a pricing refresh can turn a previously-unprofitable
            # consolidation profitable: re-search after one
            self.solver.lattice.price_version,
            len(self._in_flight),
            # the negative cache must expire when a consolidate_after window
            # elapses: pure time passage changes which candidates are
            # eligible even though no pod/claim moved
            tuple(sorted(c.name for c in consolidatable)),
            # ... and when a scheduled budget's window opens or closes
            self._budget_window_state(),
            # ... and when a budget SPEC is edited (an unscheduled
            # budget has no window state, but raising its nodes value
            # un-blocks candidates the last search skipped)
            tuple(sorted(
                (p.name, tuple((str(b.nodes), b.schedule, b.duration,
                                tuple(b.reasons))
                               for b in p.disruption.budgets))
                for p in self.node_pools.values())),
        )

    def reconcile(self) -> None:
        # the pass is spanned so a disruption decision (probes, the
        # replacement re-solve, cordons) shows up in the flight recorder
        # as one causal tree; a pass that DECIDED NOTHING marks its root
        # `discard` and the recorder drops it — an idle reconcile every
        # step must not churn the trace ring
        with trace.span("disruption.reconcile") as sp:
            acted = self._reconcile_once()
            if not acted:
                sp.set(discard=True)

    def _reconcile_once(self) -> bool:
        self._advance_in_flight()
        self._whatif_used = 0
        # one new disruption decision per pass, in method order (the core
        # serializes voluntary disruption the same way)
        if self._reconcile_expiration():
            self._last_failed_fingerprint = None
            return True
        if self.drift_enabled and self._reconcile_drift():
            self._last_failed_fingerprint = None
            return True
        if self._reconcile_emptiness():
            self._last_failed_fingerprint = None
            return True
        consolidatable = self._consolidatable()
        fp = self._fingerprint(consolidatable)
        if fp == self._last_failed_fingerprint:
            return False  # nothing changed since the search came up empty
        if fp != self._last_search_fp:
            # the base state moved: prior passes' coverage proves nothing
            # under the new fingerprint
            self._covered = set()
            self._last_search_fp = fp
        self._search_truncated = False
        frontier = {c.name for c in consolidatable}
        if self._reconcile_consolidation(consolidatable):
            self._last_frontier = frontier
            self._last_failed_fingerprint = None
            return True
        if (self._whatif_used < self.max_whatif_per_pass
                and not self._search_truncated
                and frontier <= self._covered):
            self._last_failed_fingerprint = fp
        # a pass truncated by the what-if budget, the probe window, or a
        # weather hold proved nothing about the candidates it never
        # reached — never negative-cache it; repeat passes keep sweeping
        # (cursor advance + coverage set) until the WHOLE frontier has
        # been probed under this fingerprint
        self._last_frontier = frontier
        return False

    def _advance_in_flight(self) -> None:
        """Drain originals whose replacements have all registered."""
        done: List[DisruptionAction] = []
        for action in self._in_flight:
            ready = all(self.cluster.node_for_claim(r) is not None
                        for r in action.replacements
                        if r in self.cluster.claims)
            lost = [r for r in action.replacements if r not in self.cluster.claims]
            if lost:
                # replacement failed (ICE/liveness): abandon the action
                self.recorder.publish("Warning", "DisruptionAborted", "NodeClaim",
                                      action.claims[0] if action.claims else "",
                                      f"replacement(s) {lost} lost")
                done.append(action)
                continue
            if ready:
                for name in action.claims:
                    claim = self.cluster.claims.get(name)
                    if claim is not None:
                        self._m_disrupted.inc(nodepool=claim.node_pool,
                                              reason=action.reason)
                    self.termination.delete_claim(name)
                    self.recorder.publish("Normal", "Disrupted", "NodeClaim", name,
                                          action.reason)
                done.append(action)
        for a in done:
            self._in_flight.remove(a)

    def _begin(self, reason: str, removed: Sequence[NodeClaim],
               plan: NodePlan,
               max_replacement_cost: Optional[float] = None) -> bool:
        """Launch replacements (if any) then queue the drain.
        ``max_replacement_cost`` re-guards consolidation profitability after
        limit-driven instance-type substitution (a downsized-into-the-limit
        replacement is pricier than the solver's choice by construction)."""
        pool_budgets: Dict[str, int] = {}
        for c in removed:
            pool = self.node_pools[c.node_pool]
            pool_budgets.setdefault(c.node_pool, self._allowed_disruptions(pool, reason))
            if pool_budgets[c.node_pool] <= 0:
                return False
            pool_budgets[c.node_pool] -= 1
        # NodePool resource limits bind replacements exactly like fresh
        # provisioning (nodepools.md limits). Launch-before-drain means the
        # originals still count toward usage here — correct, both exist
        # during the transition. If any replacement cannot fit the limits
        # (even downsized), abort: never drain without standing capacity.
        planned, over_limit = self.provisioner._enforce_limits(
            list(plan.new_nodes))
        if over_limit:
            self.recorder.publish("Warning", "DisruptionBlocked", "NodeClaim",
                                  removed[0].name if removed else "",
                                  f"{reason} replacement exceeds nodepool limits")
            return False
        if max_replacement_cost is not None:
            new_cost = sum(n.price_per_hour for n in planned)
            if new_cost >= max_replacement_cost:
                self.recorder.publish(
                    "Warning", "DisruptionBlocked", "NodeClaim",
                    removed[0].name if removed else "",
                    f"{reason} no longer profitable after limit substitution")
                return False
        # limit substitution may also have narrowed launch flexibility below
        # the spot-to-spot guard's floor — re-check on the final plan
        # (consolidation only: the guard does not apply to drift/expiration
        # replacements, disruption.md:129)
        if reason == "Underutilized" and not self._spot_guard_ok(removed, plan):
            return False
        action = DisruptionAction(reason=reason, claims=[c.name for c in removed])
        for node in planned:
            claim = self.provisioner._make_claim(node)
            self.writer.create_claim(claim)
            try:
                self.cloud_provider.create(claim)
                self.writer.update_claim_status(claim)
            except Exception as e:
                # ICE or any launch failure: roll back — never drain without
                # standing replacement capacity
                self.recorder.publish("Warning", "ReplacementLaunchFailed",
                                      "NodeClaim", claim.name,
                                      f"{reason} disruption aborted: "
                                      f"{type(e).__name__}: {e}")
                for r in action.replacements:
                    self.termination.delete_claim(r)
                self.writer.rollback_claim(claim.name)
                return False
            action.replacements.append(claim.name)
        self._in_flight.append(action)
        return True

    # ---- methods ----------------------------------------------------------

    def _reconcile_expiration(self) -> bool:
        now = self.clock.now()
        for claim in self._candidates():
            pool = self.node_pools[claim.node_pool]
            expire = pool.disruption.expire_after
            if expire is None or now - claim.created_at < expire:
                continue
            plan, _ = self._what_if([claim])
            if plan.unschedulable:
                continue
            if self._begin("Expired", [claim], plan):
                return True
        return False

    def _reconcile_drift(self) -> bool:
        for claim in self._candidates():
            pool = self.node_pools[claim.node_pool]
            reason = self.cloud_provider.is_drifted(claim)
            if reason is None:
                have = claim.annotations.get(wk.ANNOTATION_NODEPOOL_HASH)
                have_ver = claim.annotations.get(
                    wk.ANNOTATION_NODEPOOL_HASH_VERSION)
                from .provisioning import NODEPOOL_HASH_VERSION
                if have is not None and have_ver != NODEPOOL_HASH_VERSION:
                    # hash formula changed between controller versions:
                    # RE-STAMP under the new formula instead of treating
                    # the formula change itself as drift (which would
                    # roll every pre-upgrade node fleet-wide)
                    claim.annotations[wk.ANNOTATION_NODEPOOL_HASH] = \
                        nodepool_hash(pool)
                    claim.annotations[wk.ANNOTATION_NODEPOOL_HASH_VERSION] = \
                        NODEPOOL_HASH_VERSION
                elif have is not None and have != nodepool_hash(pool):
                    reason = "NodePoolDrift"
            if reason is None:
                continue
            plan, _ = self._what_if([claim])
            if plan.unschedulable:
                continue
            if self._begin("Drifted", [claim], plan):
                return True
        return False

    def _reconcile_emptiness(self) -> bool:
        now = self.clock.now()
        empties: List[NodeClaim] = []
        for claim in self._candidates():
            pool = self.node_pools[claim.node_pool]
            after = pool.disruption.consolidate_after
            if after is None:
                continue
            if self._pods_on(claim):
                continue
            ref = claim.initialized_at or claim.created_at
            if now - ref < after:
                continue
            empties.append(claim)
        if not empties:
            return False
        # parallel empty-node delete, budget-capped per pool
        started = False
        by_pool: Dict[str, List[NodeClaim]] = {}
        for c in empties:
            by_pool.setdefault(c.node_pool, []).append(c)
        for pool_name, claims in by_pool.items():
            budget = self._allowed_disruptions(self.node_pools[pool_name], "Empty")
            batch = claims[:budget]
            if not batch:
                continue
            if self._begin("Empty", batch, NodePlan([], {}, {}, 0.0, 0.0, 0.0)):
                started = True
        return started

    def _reconcile_consolidation(
            self, candidates: Optional[List[NodeClaim]] = None) -> bool:
        if candidates is None:
            candidates = self._consolidatable()
        if not candidates:
            return False
        node_by_claim = self.cluster.nodes_by_claim()
        hold = self.engine.weather_hold()
        if hold:
            # never consolidate INTO an active storm or spot-crash window
            # (weather/simulator.py consolidation_advisory; an ice-age
            # never holds). A held pass proved nothing — mark it truncated
            # so it is not negative-cached and the search resumes the
            # moment the advisory clears.
            self.engine.note_weather_hold(
                [node_by_claim[c.name].name for c in candidates
                 if c.name in node_by_claim], hold)
            self._search_truncated = True
            return False
        # cheapest-to-disrupt first (consolidation.md scoring) off one
        # locked snapshot instead of an O(pods) scan per candidate
        by_node = self.cluster.pods_by_node(include_daemonsets=False)
        cost = {c.name: float(sum(
            1 + p.priority
            for p in by_node.get(node_by_claim[c.name].name, ())))
            for c in candidates if c.name in node_by_claim}
        candidates = [c for c in candidates if c.name in node_by_claim]
        if not candidates:
            return False  # snapshot drift removed every candidate's node
        candidates.sort(key=lambda c: cost[c.name])
        K = len(candidates)

        # the whole pass's search — every prefix of the cheapest-first
        # ladder (disruption.md:93-98) AND the single-node scan — is ONE
        # batched device probe (SURVEY §2.2 "embarrassingly batchable");
        # only the winning candidate set pays an exact decode solve, so a
        # pass costs ≤2 device calls instead of O(log n + budget) round
        # trips. Probing each prefix independently also beats the old
        # binary search when feasibility is not monotone in the prefix.
        if K > 1:
            ks = sorted({int(round(k)) for k in
                         np.linspace(2, K, min(K - 1, self.MAX_PREFIX_PROBES))})
        else:
            ks = []
        start = self._scan_cursor % K
        rotated = candidates[start:] + candidates[:start]
        # candidates that entered the frontier since the last pass jump
        # the window queue: a budget- or window-truncated sweep must
        # re-verify NEW candidates next pass, not make them wait a full
        # rotation behind ones already probed (stable sort keeps the
        # cheapest-first order within each class)
        new_names = {c.name for c in candidates} - self._last_frontier
        if new_names:
            rotated.sort(key=lambda c: c.name not in new_names)
        singles = rotated[: self.MAX_SINGLE_PROBES]
        probe_sets = [candidates[:k] for k in ks] + [[c] for c in singles]
        verdicts = self.engine.probe(probe_sets, node_by_claim=node_by_claim,
                                     by_node=by_node)
        n_prefix = len(ks)
        # the prefix ladder may only spend half the pass's exact-solve
        # budget: optimistic probes (soft constraints fully relaxed) can all
        # fail exact verification, and the single-node scan must still get
        # its turn before the pass is negative-cached
        prefix_budget = max(self.max_whatif_per_pass // 2, 1)

        # multi-node: largest probe-feasible prefix, verified by one exact
        # solve (the probe is optimistic — soft constraints fully relaxed).
        # A host-fallback set (outside the vmapped envelope) has no probe
        # verdict: it goes straight to the exact solve under the budget.
        for i in range(n_prefix - 1, -1, -1):
            removed = probe_sets[i]
            v = verdicts[i]
            if not v.host and not self._probe_ok(removed, v.probe,
                                                 v.removed_price):
                continue
            if not self._within_budgets(removed, "Underutilized"):
                continue  # budget can admit a smaller prefix — keep walking
            if self._whatif_used >= prefix_budget:
                # probe-positive prefixes remain unverified: the pass must
                # not be negative-cached on their account
                self._search_truncated = True
                break
            plan, removed_price = self._what_if(removed)
            ok = (not plan.unschedulable and len(plan.new_nodes) <= 1
                  and plan.new_node_cost < removed_price - CONSOLIDATION_SAVINGS_EPS
                  and self._spot_guard_ok(removed, plan))
            if ok:
                accepted, ratio = self.engine.referee(
                    removed, plan, node_by_claim=node_by_claim,
                    by_node=by_node)
                if not accepted:
                    # the device plan's costing disagrees with the host
                    # FFD oracle beyond the ≤2% envelope: a smaller
                    # prefix (or a single) may still referee clean
                    continue
                if self._begin("Underutilized", removed, plan,
                               max_replacement_cost=removed_price
                               - CONSOLIDATION_SAVINGS_EPS):
                    self.engine.note_accept(
                        removed, removed_price - plan.new_node_cost)
                    return True
                # _begin rejections surviving the budget pre-check (pool
                # limits, launch failure) are pass-invariant: stop paying
                # exact solves for smaller prefixes, leave budget for the
                # single-node scan
                break

        # single-node scan: only probe-positive candidates pay an exact
        # solve; bounded by the pass's remaining what-if budget
        truncated_at = None
        for j, claim in enumerate(singles):
            v = verdicts[n_prefix + j]
            node_name = node_by_claim[claim.name].name
            if not v.host and not self._probe_ok([claim], v.probe,
                                                 v.removed_price):
                # a probe-negative single IS the pass's answer for that
                # node — code it so `kpctl explain node` has one even when
                # the fleet is already tight (probes are optimistic, so a
                # probe-level "no savings" is conclusive, not provisional)
                if (v.probe.feasible and v.probe.n_new <= 1
                        and v.probe.new_cost
                        < v.removed_price - CONSOLIDATION_SAVINGS_EPS):
                    self.engine.note_skip(
                        node_name, taxonomy.CONSOLIDATION_SPOT_GUARD,
                        "spot replacement below the 15-type flexibility "
                        "floor or the spot-to-spot gate is off")
                else:
                    self.engine.note_skip(
                        node_name, taxonomy.CONSOLIDATION_NO_SAVINGS,
                        "probe: no repack within one replacement node "
                        f"cheaper than ${v.removed_price:.4f}/hr"
                        if not v.probe.feasible or v.probe.n_new > 1 else
                        f"probe: replacement ${v.probe.new_cost:.4f}/hr "
                        f"vs removed ${v.removed_price:.4f}/hr")
                continue
            if not self._within_budgets([claim], "Underutilized"):
                self.engine.note_skip(
                    node_name, taxonomy.NOT_CONSOLIDATABLE_BUDGET,
                    f"pool {claim.node_pool} disruption budget exhausted")
                continue
            if self._whatif_used >= self.max_whatif_per_pass:
                truncated_at = j
                break
            plan, removed_price = self._what_if([claim])
            if plan.unschedulable or len(plan.new_nodes) > 1:
                continue
            if plan.new_node_cost >= removed_price - CONSOLIDATION_SAVINGS_EPS:
                self.engine.note_skip(
                    node_name, taxonomy.CONSOLIDATION_NO_SAVINGS,
                    f"replacement ${plan.new_node_cost:.4f}/hr vs removed "
                    f"${removed_price:.4f}/hr")
                continue
            if not self._spot_guard_ok([claim], plan):
                self.engine.note_skip(
                    node_name, taxonomy.CONSOLIDATION_SPOT_GUARD,
                    "spot replacement below the 15-type flexibility floor "
                    "or the spot-to-spot gate is off")
                continue
            accepted, ratio = self.engine.referee(
                [claim], plan, node_by_claim=node_by_claim, by_node=by_node)
            if not accepted:
                self.engine.note_skip(
                    node_name, taxonomy.CONSOLIDATION_NO_SAVINGS,
                    f"device plan costs {ratio:.3f}x the host FFD referee "
                    f"(envelope 1.02)")
                continue
            if self._begin("Underutilized", [claim], plan,
                           max_replacement_cost=removed_price
                           - CONSOLIDATION_SAVINGS_EPS):
                self.engine.note_accept(
                    [claim], removed_price - plan.new_node_cost)
                return True
        # every single probed this pass is covered under the current
        # fingerprint (probe-negative IS an answer); candidates past a
        # budget truncation are not
        self._covered.update(
            c.name for c in (singles if truncated_at is None
                             else singles[:truncated_at]))
        if truncated_at is not None:
            # budget-truncated mid-window: resume exactly where the scan
            # stopped next pass (reconcile() skips the negative cache), and
            # always advance by >=1 so a deterministic repeat can't starve
            # the tail
            self._search_truncated = True
            self._scan_cursor = (start + max(truncated_at, 1)) % K
        elif self._whatif_used >= self.max_whatif_per_pass:
            # exhausted exactly at the window's end: next window
            self._search_truncated = True
            self._scan_cursor = (start + max(len(singles), 1)) % K
        elif len(singles) < K:
            # the window covered only part of the frontier even without
            # budget pressure (K > MAX_SINGLE_PROBES): advance so repeat
            # passes sweep the tail instead of deterministically
            # re-probing the same window — the coverage set keeps the
            # pass from negative-caching until the sweep completes
            self._scan_cursor = (start + len(singles)) % K
        else:
            self._scan_cursor = 0
        return False
