"""Leaked-capacity garbage collection.

Mirror of the reference's nodeclaim GC controller (reference
pkg/controllers/nodeclaim/garbagecollection/controller.go:55-89): cloud
instances older than 30 s with no matching NodeClaim are terminated
(launch succeeded but the claim write was lost), and claims whose backing
instance disappeared are removed so their pods reschedule. Also owns the
NodePool deletion cascade: the reference gets it from kube garbage
collection (claims carry an ownerReference to their NodePool, so
deleting the pool foreground-deletes the claims, whose termination
finalizer then drains them gracefully — reference nodepools.md
"deleting a NodePool deletes its nodes"); with no kube GC here, this
controller marks a gone pool's claims deleting, which starts the same
PDB-paced finalizer drain.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..apis.objects import NodeClaimPhase
from ..cloud.fake import parse_instance_id
from ..cloudprovider.cloudprovider import CloudProvider
from ..errors import NotFoundError
from ..events import Recorder
from ..state.cluster import ClusterState
from ..utils.clock import Clock

LEAK_GRACE_SECONDS = 30.0  # garbagecollection/controller.go:64


class GarbageCollectionController:
    def __init__(self, cluster: ClusterState, cloud_provider: CloudProvider,
                 recorder: Optional[Recorder] = None, clock: Optional[Clock] = None,
                 writer=None,
                 pool_exists: Optional[Callable[[str], bool]] = None):
        from ..utils.fanout import LazyPool
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock or Clock()
        from ..kube.writer import DirectWriter
        self.writer = writer or DirectWriter(cluster, self.clock)
        self.recorder = recorder or Recorder(self.clock)
        # ``pool_exists(name) -> bool`` answers whether the NodePool still
        # exists AT THE SOURCE OF TRUTH (the operator's pool dict, or the
        # nodepools informer store in API mode — NOT the config-guarded
        # active dict, where an invalid-config pool is absent but its
        # nodes must survive). None disables the cascade.
        self.pool_exists = pool_exists
        # claims already cascaded: in API mode the mirror's
        # deletion_timestamp lags the server write by one informer pump,
        # and re-entering the branch each tick would spam duplicate
        # NodePoolDeleted events (the server-side delete itself is a
        # no-op). Pruned when the claim leaves the mirror.
        self._cascaded: set = set()
        self._pool = LazyPool(self.EXISTENCE_WORKERS, "gc-exists")

    # reference garbagecollection/controller.go:78 checks 100-way parallel
    EXISTENCE_WORKERS = 100

    def reconcile(self) -> None:
        now = self.clock.now()
        claims = [c for c in list(self.cluster.claims.values())
                  if c.provider_id is not None]
        claimed_ids = {parse_instance_id(c.provider_id) for c in claims}

        # existence checks fan out (the cloud round trip is the slow part);
        # state mutation happens serially afterwards under one thread
        def exists(claim) -> bool:
            try:
                self.cloud_provider.get(claim.provider_id)
                return True
            except NotFoundError:
                return False

        alive = self._pool.run(claims, exists)
        for claim, ok in zip(claims, alive):
            if ok:
                continue
            # claim whose instance vanished out from under it -> delete the
            # claim (+node) so its pods reschedule
            iid = parse_instance_id(claim.provider_id)
            self.recorder.publish("Warning", "InstanceDisappeared", "NodeClaim",
                                  claim.name, f"instance {iid} is gone")
            node = self.cluster.node_for_claim(claim.name)
            if node is not None:
                # teardown deletes daemonset pods with the node — no
                # phantom overhead in future node sizing
                self.writer.teardown_node(node.name)
            # the backing instance is GONE: hard delete, no finalizer round
            self.writer.rollback_claim(claim.name)
        # leaked instances: running but unclaimed past the grace window
        for inst in self.cloud_provider.list_instances():
            if inst.id in claimed_ids or inst.state == "terminated":
                continue
            if now - inst.launch_time < LEAK_GRACE_SECONDS:
                continue
            self.recorder.publish("Warning", "LeakedInstance", "Instance", inst.id,
                                  "terminating instance with no nodeclaim")
            try:
                self.cloud_provider.cloud.terminate_instances([inst.id])
            except NotFoundError:
                pass
        # orphaned node leases: no owner reference, or the owner node is
        # gone (the kubelet that would heartbeat it no longer exists) —
        # reference integration/lease_garbagecollection_test.go
        for name in self.cluster.orphaned_leases():
            self.recorder.publish("Normal", "LeaseGarbageCollected", "Lease",
                                  name, "deleting orphaned node lease")
            self.writer.delete_lease(name)
        # NodePool deletion cascade (see module docstring): a gone pool's
        # claims start the graceful finalizer drain — never a hard
        # rollback; PDBs and grace periods pace the eviction exactly as
        # in voluntary disruption
        if self.pool_exists is not None:
            live = set()
            for claim in list(self.cluster.claims.values()):
                live.add(claim.name)
                if (claim.deletion_timestamp or not claim.node_pool
                        or claim.name in self._cascaded):
                    continue
                if not self.pool_exists(claim.node_pool):
                    self.recorder.publish(
                        "Normal", "NodePoolDeleted", "NodeClaim", claim.name,
                        f"nodepool {claim.node_pool} is gone; draining")
                    self.writer.mark_claim_deleting(claim.name)
                    self._cascaded.add(claim.name)
            self._cascaded &= live
