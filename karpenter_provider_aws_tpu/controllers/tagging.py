"""NodeClaim tagging controller.

Mirror of the reference's post-registration instance tagger (reference
pkg/controllers/nodeclaim/tagging/controller.go:57-110): once a NodeClaim's
node registers, its backing instance is tagged with ``Name`` (the node
name) and ``karpenter.sh/nodeclaim`` (the claim name). Already-present tags
are never overwritten (controller.go:99-104), success is recorded in the
``karpenter.sh/instance-tagged`` annotation so a claim is only processed
once, and a vanished instance is skipped without error (the GC controller
owns that case).
"""

from __future__ import annotations

from typing import Optional

from ..apis import wellknown as wk
from ..cloud.fake import parse_instance_id
from ..errors import NotFoundError
from ..events import Recorder
from ..state.cluster import ClusterState
from ..utils.clock import Clock


class TaggingController:
    def __init__(self, cluster: ClusterState, cloud, recorder: Optional[Recorder] = None,
                 clock: Optional[Clock] = None):
        self.cluster = cluster
        self.cloud = cloud
        self.clock = clock or Clock()
        self.recorder = recorder or Recorder(self.clock)

    def _taggable(self, claim) -> bool:
        """Registered, live, carries a provider id, not yet tagged
        (controller.go isTaggable)."""
        return (claim.provider_id is not None
                and claim.registered_at is not None
                and claim.deletion_timestamp is None
                and claim.annotations.get(wk.ANNOTATION_INSTANCE_TAGGED) != "true")

    def reconcile(self) -> int:
        tagged = 0
        for claim in list(self.cluster.claims.values()):
            if not self._taggable(claim):
                continue
            try:
                iid = parse_instance_id(claim.provider_id)
            except ValueError:
                # malformed provider id: do not retry until it changes
                # (controller.go:63-67)
                continue
            node = self.cluster.node_for_claim(claim.name)
            tags = {wk.TAG_NAME: node.name if node is not None else claim.name,
                    wk.TAG_NODECLAIM: claim.name}
            try:
                (inst,) = self.cloud.describe_instances([iid]) or (None,)
            except NotFoundError:
                inst = None
            if inst is None or inst.state == "terminated":
                continue  # GC owns vanished instances
            missing = {k: v for k, v in tags.items() if k not in inst.tags}
            if missing:
                try:
                    self.cloud.create_tags(iid, missing)
                except NotFoundError:
                    continue
            claim.annotations[wk.ANNOTATION_INSTANCE_TAGGED] = "true"
            tagged += 1
        return tagged
