"""NodeClass controller: status hydration + finalizer.

Mirror of reference pkg/controllers/nodeclass/controller.go: reconcile
resolves the NodeClass's subnets / security groups / AMIs / instance
profile into status (:150-233), stamps the spec hash annotation for drift
versioning (:84-92, :239-272), re-resolves every 5 minutes (:117), and the
finalizer blocks deletion until no NodeClaims reference the class, then
deletes the instance profile and launch templates (:120-148).
"""

from __future__ import annotations

from typing import Dict, Optional

from ..apis.objects import NodeClass
from ..apis import wellknown as wk
from ..cloudprovider.cloudprovider import nodeclass_hash
from ..events import Recorder
from ..providers.amifamily import AMIProvider
from ..providers.instanceprofile import InstanceProfileProvider
from ..providers.launchtemplate import LaunchTemplateProvider
from ..providers.securitygroup import SecurityGroupProvider
from ..providers.subnet import SubnetProvider
from ..providers.version import VersionProvider
from ..state.cluster import ClusterState
from ..utils.clock import Clock

RECONCILE_INTERVAL = 300.0  # requeue every 5 min (controller.go:117)


class NodeClassController:
    def __init__(self, node_classes: Dict[str, NodeClass],
                 cluster: ClusterState,
                 subnets: SubnetProvider,
                 security_groups: SecurityGroupProvider,
                 amis: AMIProvider,
                 instance_profiles: InstanceProfileProvider,
                 launch_templates: LaunchTemplateProvider,
                 version: VersionProvider,
                 recorder: Optional[Recorder] = None,
                 clock: Optional[Clock] = None):
        self.node_classes = node_classes
        self.cluster = cluster
        self.subnets = subnets
        self.security_groups = security_groups
        self.amis = amis
        self.instance_profiles = instance_profiles
        self.launch_templates = launch_templates
        self.version = version
        self.clock = clock or Clock()
        self.recorder = recorder or Recorder(self.clock)
        self._last: Dict[str, float] = {}
        self._deleting: Dict[str, bool] = {}

    def reconcile(self) -> None:
        now = self.clock.now()
        for nc in list(self.node_classes.values()):
            if self._deleting.get(nc.name):
                self._finalize(nc)
                continue
            if now - self._last.get(nc.name, -1e18) < RECONCILE_INTERVAL:
                continue
            self._hydrate(nc)
            self._last[nc.name] = now

    def _hydrate(self, nc: NodeClass) -> None:
        """Resolve spec → status (controller.go:150-233)."""
        ready = True
        nc.status_subnets = [{"id": s.id, "zone": s.zone,
                              "zoneType": s.zone_type}
                             for s in self.subnets.list(nc)]
        nc.status_security_groups = [{"id": g.id, "name": g.name}
                                     for g in self.security_groups.list(nc)]
        v = self.version.get()
        try:
            nc.status_amis = [{"id": a.id, "name": a.name, "arch": a.arch}
                              for a in self.amis.list(nc, v)]
        except ValueError as e:
            # e.g. unknown AMI family: degrade the class to NotReady (the
            # reference sets status conditions; it never crashes the manager)
            nc.status_amis = []
            self.recorder.publish("Warning", "NodeClassResolveFailed", "NodeClass",
                                  nc.name, str(e))
        try:
            nc.status_instance_profile = self.instance_profiles.create(nc)
        except ValueError:
            nc.status_instance_profile = None
        if not nc.status_subnets or not nc.status_security_groups or not nc.status_amis:
            ready = False
        # spec-hash annotation for drift versioning (controller.go:84-92)
        nc.annotations[wk.ANNOTATION_NODECLASS_HASH] = nodeclass_hash(nc)
        nc.status_conditions["Ready"] = ready
        nc.status_conditions["SubnetsReady"] = bool(nc.status_subnets)
        nc.status_conditions["SecurityGroupsReady"] = bool(nc.status_security_groups)
        nc.status_conditions["AMIsReady"] = bool(nc.status_amis)
        if not ready:
            self.recorder.publish("Warning", "NodeClassNotReady", "NodeClass", nc.name,
                                  f"unresolved: subnets={len(nc.status_subnets)} "
                                  f"sgs={len(nc.status_security_groups)} amis={len(nc.status_amis)}")

    def delete(self, name: str) -> None:
        """Begin NodeClass deletion (sets the finalizer-pending flag)."""
        if name in self.node_classes:
            self._deleting[name] = True

    def _finalize(self, nc: NodeClass) -> None:
        """Block until no claims reference the class, then clean the cloud
        side (controller.go:120-148)."""
        in_use = any(c.node_class_ref == nc.name for c in self.cluster.snapshot_claims())
        if in_use:
            self.recorder.publish("Warning", "NodeClassDeleteBlocked", "NodeClass",
                                  nc.name, "nodeclaims still reference this class")
            return
        self.instance_profiles.delete(nc)
        self.launch_templates.delete_all(nc)
        self.node_classes.pop(nc.name, None)
        self._deleting.pop(nc.name, None)
        self.recorder.publish("Normal", "NodeClassDeleted", "NodeClass", nc.name, "")
