"""Provisioning controller: pending pods → Solve() → NodeClaims → launches.

Mirror of the core provisioner loop (reference: pending-pod watch → batch
window 1 s idle / 10 s max → scheduler simulation → NodeClaim create →
CloudProvider.Create; SURVEY.md §3.2, website reference/settings.md:17-18).
The FFD simulation is replaced by the device solver: cluster state renders
to tensors, the ICE cache masks the lattice, one Solve() packs the whole
batch, and the decoded NodePlan becomes NodeClaims. NodePool resource
limits are enforced host-side on the plan (nodepools.md limits), and
launch failures feed back via UnavailableOfferings for the next pass.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import trace
from ..apis import wellknown as wk
from ..apis.objects import NodeClaim, NodeClaimPhase, NodePool, Pod
from ..apis.requirements import Operator, Requirement
from ..apis.resources import R, axis as res_axis, resources_to_vec
from ..cache.unavailable import UnavailableOfferings
from ..cloudprovider.cloudprovider import CloudProvider
from ..errors import UnfulfillableCapacityError
from ..events import Recorder
from ..lattice.tensors import Lattice, masked_view_versioned
from ..metrics import Registry, wire_core_metrics
from ..solver import explain as explain_mod
from ..solver import taxonomy
from ..solver.explain import DecisionAuditRing
from ..solver.solve import NodePlan, PlannedNode, Solver
from ..state.cluster import ClusterState
from ..utils.clock import Clock

BATCH_IDLE_SECONDS = 1.0   # settings.md:17 batch-idle-duration (default)
BATCH_MAX_SECONDS = 10.0   # settings.md:18 batch-max-duration (default)
_PODS_AXIS = res_axis("pods")

# Bumped whenever the nodepool_hash PAYLOAD SHAPE changes (e.g. the
# kubelet block joining it): claims stamped under an older version are
# RE-STAMPED instead of drift-compared, so a controller upgrade never
# rolls the whole fleet (the reference migrates its hash the same way —
# wellknown ANNOTATION_NODEPOOL_HASH_VERSION).
NODEPOOL_HASH_VERSION = "v5"  # v5: slice fields hash as SETS (+ startupTaints in v4)


def nodepool_hash(pool: NodePool) -> str:
    """Template hash for NodePool drift detection (the core's
    karpenter.sh/nodepool-hash annotation; CRD nodepools drift semantics).
    Every field stamped onto launched nodes participates; fields that
    only steer the SOLVE (weight, limits, the disruption block) stay
    out — retuning them must never roll the fleet. Slice fields hash
    ORDER-INSENSITIVELY (the reference's hashstructure SlicesAsSets):
    reordering semantically-identical taints/requirements in YAML must
    never roll a fleet."""
    import hashlib
    import json
    payload = json.dumps({
        "labels": sorted(pool.labels.items()),
        "annotations": sorted(pool.annotations.items()),
        # kubelet knobs are template spec: changing maxPods or clusterDNS
        # must drift (and roll) nodes launched with the old values
        "kubelet": ((pool.kubelet.max_pods, pool.kubelet.cluster_dns)
                    if pool.kubelet is not None else None),
        "taints": sorted((t.key, t.value or "", t.effect)
                         for t in pool.taints),
        # startupTaints shape the node exactly like taints do (the init
        # daemon contract changes with them); the reference hashes them
        "startup_taints": sorted((t.key, t.value or "", t.effect)
                                 for t in pool.startup_taints),
        "requirements": sorted((r.key, r.operator.value,
                                sorted(str(v) for v in r.values))
                               for r in pool.requirements),
        "node_class_ref": pool.node_class_ref,
    }, sort_keys=True, default=str)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


@dataclass
class ProvisionResult:
    plan: Optional[NodePlan]
    created_claims: List[NodeClaim] = field(default_factory=list)
    launched: int = 0
    launch_failures: int = 0
    pods_scheduled: int = 0
    pods_unschedulable: int = 0
    # degradation provenance of the pass (docs/concepts/degradation.md):
    # True when any solve left the primary device path, or when the solve
    # itself failed and the pass returned a PARTIAL result (pods stay
    # pending for the next pass instead of the wave being dropped)
    degraded: bool = False
    degraded_reason: str = ""


class Provisioner:
    def __init__(self, cluster: ClusterState, solver: Solver,
                 node_pools: Dict[str, NodePool],
                 cloud_provider: CloudProvider,
                 unavailable: UnavailableOfferings,
                 recorder: Optional[Recorder] = None,
                 clock: Optional[Clock] = None,
                 batch_idle_seconds: float = BATCH_IDLE_SECONDS,
                 batch_max_seconds: float = BATCH_MAX_SECONDS,
                 metrics: Optional[Registry] = None,
                 writer=None, slo=None):
        self.cluster = cluster
        self.solver = solver
        self.node_pools = node_pools
        self.cloud_provider = cloud_provider
        self.unavailable = unavailable
        self.clock = clock or Clock()
        from ..kube.writer import DirectWriter
        # every k8s-object write goes through the writer seam: direct to
        # the mirror (simulation stratum) or through the apiserver client
        # (kube/writer.py ApiWriter)
        self.writer = writer or DirectWriter(cluster, self.clock)
        self.recorder = recorder or Recorder(self.clock)
        self.batch_idle_seconds = batch_idle_seconds
        self.batch_max_seconds = batch_max_seconds
        from ..solver.incremental import IncrementalProblemBuilder
        # the steady-state incremental path: one builder per provisioner
        # retains the previous pass's Problem keyed at the cluster-state
        # revision; eligible small-churn passes delta-solve instead of
        # re-tensorizing from scratch (docs/concepts/performance.md
        # "Steady-state reconciles & the compile cache")
        self.inc_builder = IncrementalProblemBuilder()
        self._delta_enabled = bool(getattr(solver, "supports_delta", False))
        from ..state.cluster import DirtyJournalCoalescer
        # journal → device-block coalescer (docs/reference/microloop.md):
        # batch-window polls drain the dirty journal incrementally, so a
        # pass starts from an already-merged delta covering every tick
        # since the last build instead of one long locked journal walk
        self.journal_coalescer = DirtyJournalCoalescer(cluster)
        m = wire_core_metrics(metrics or Registry())  # single source of truth
        self._m_sched = m["scheduling_duration"]
        self._m_sim = m["scheduling_simulation_duration"]
        self._m_batch = m["batch_size"]
        self._m_sched_pods = m["pods_scheduled"]
        self._m_unsched_pods = m["pods_unschedulable"]
        self._m_created = m["nodeclaims_created"]
        self._m_launched = m["nodeclaims_launched"]
        self._m_degraded = m["solver_degraded"]
        self._m_solver_retries = m["solver_device_retries"]
        self._m_waves = m["solver_waves"]
        self._m_stage = m["solver_stage_duration"]
        self._m_delta = m["solver_delta_solves"]
        self._m_dirty_groups = m["solver_dirty_groups"]
        self._m_link_legs = m["solver_link_legs"]
        self._m_link_bytes = m["solver_link_bytes"]
        # last mirrored solver link_stats values (the counters are
        # cumulative on the Solver; the metric counters inc by delta)
        self._link_prev: Dict[str, int] = {}
        self._m_pods_state = m["pods_state"]
        self._m_unsched_reasons = m["pods_unschedulable_reasons"]
        self._m_eliminations = m["explain_eliminations"]
        # SLO burn tracking (introspect/slo.py): every pass records its
        # end-to-end solve latency; a sampled FFD-referee re-pack records
        # the cost ratio. None = untracked (bare Provisioner in tests).
        self.slo = slo
        self._claim_ids = itertools.count(1)
        # the decision-audit ring (solver/explain.py): one explanation
        # per pass, served via /debug/explain + `kpctl explain`; the
        # operator registers .stats as the "explain" provider
        self.explain = DecisionAuditRing()
        self._pass_seq = itertools.count(1)
        # FailedScheduling dedup: pod -> (last published reason CODE,
        # the Pod OBJECT it was published for). A stuck pod publishes
        # ONCE per (pod, reason-code); the entry re-arms when the
        # reason changes, the pod makes progress (binds, or is deleted
        # — it leaves the unschedulable set), or the NAME is reused by
        # a recreated pod (cluster state hands the same object every
        # pass, so a new object under an old name is a new pod and its
        # failure deserves its own event)
        self._failed_pub: Dict[str, Tuple[str, object]] = {}
        self._batch_start: Optional[float] = None
        self._last_pod_seen: Optional[float] = None
        self._known_pending: frozenset = frozenset()
        self._lock = threading.Lock()
        # introspection: pass counters + the last pass's outcome
        self.passes = 0
        self._last_pass: Dict[str, float] = {}

    # ---- batch window (settings.md:17-18) --------------------------------

    def batch_ready(self) -> bool:
        """Has the pending-pod batch window closed? New arrivals reset the
        idle timer; the max window bounds total latency. Arrival detection
        compares the pending-pod NAME set, not its size — one pod binding
        while another arrives in the same window is still an arrival.

        Every poll also streams the dirty journal into the coalescer:
        the open batch window is exactly when the controller is "behind"
        on ticks, and draining here keeps the pass-start journal walk
        O(since last poll) instead of O(since last pass)."""
        if self._delta_enabled:
            self.journal_coalescer.tick(self.inc_builder.rev)
        now = self.clock.now()
        with self._lock:
            names = frozenset(p.name for p in self.cluster.pending_pods())
            if not names:
                self._batch_start = None
                self._last_pod_seen = None
                self._known_pending = frozenset()
                return False
            if self._batch_start is None:
                self._batch_start = now
                self._last_pod_seen = now
                self._known_pending = names
                return False
            if names - self._known_pending:
                self._last_pod_seen = now
            self._known_pending = names
            idle_over = now - self._last_pod_seen >= self.batch_idle_seconds
            max_over = now - self._batch_start >= self.batch_max_seconds
            if idle_over or max_over:
                self._batch_start = None
                self._last_pod_seen = None
                self._known_pending = frozenset()
                return True
            return False

    # ---- one scheduling pass --------------------------------------------

    @staticmethod
    def _batch_trace_context(pending: Sequence[Pod]):
        """(parent, links) for the pass span. Pods created through the
        REST surface carry the admission span's traceparent as an
        annotation (kube/httpserver.py); the pass — which coalesced many
        pods behind the batch window — JOINS the first such trace and
        LINKS the rest, so one REST write's trace reaches all the way to
        the device solve while the other writes stay causally attached."""
        ctxs = []
        for p in pending:
            tp = p.annotations.get(wk.ANNOTATION_TRACEPARENT)
            if tp:
                ctxs.append(tp)
        return (ctxs[0] if ctxs else None), ctxs[1:]

    def provision_once(self) -> ProvisionResult:
        # the revision is read BEFORE the pending snapshot: the build is
        # keyed at rev0, so any mutation racing the snapshot (threaded
        # stratum) lands at a rev > rev0 and is re-examined by the next
        # pass's dirty read instead of silently falling between passes
        rev0 = self.cluster.state_rev
        pending = self.cluster.pending_pods()
        if not pending:
            return ProvisionResult(plan=None)
        parent, links = (self._batch_trace_context(pending)
                         if trace.enabled() else (None, ()))
        with trace.span("provisioner.provision", parent=parent, links=links,
                        pods=len(pending)) as sp:
            result = self._provision(pending, rev0)
            sp.set(degraded=result.degraded,
                   reason=result.degraded_reason,
                   launched=result.launched,
                   scheduled=result.pods_scheduled,
                   unschedulable=result.pods_unschedulable)
            return result

    def warm_build(self, solve: bool = False) -> bool:
        """Standby pre-build (state/replication.py StandbyReplica): run
        the pass's problem build — and optionally a PURE solve — over
        the replicated mirror WITHOUT dispatching a single write. The
        resident device problem and the persistent compile cache warm up
        exactly as a real pass would, so the first post-promotion pass
        is a delta, not a compile storm. Returns True when a problem was
        built."""
        lattice = masked_view_versioned(self.solver.lattice, self.unavailable)
        pvcs, storage_classes = self.cluster.volume_state()
        headroom = self._pool_headroom(self.cluster.pool_usage())
        pools = list(self.node_pools.values())
        pending = self.cluster.pending_pods()
        dirty = self.journal_coalescer.take(self.inc_builder.rev)
        touched = (self.cluster.touched_pods(dirty.pods)
                   if dirty.pods and not dirty.full else {})
        build = self.inc_builder.build(
            pending, pools, lattice,
            existing=lambda: self.cluster.existing_bins(lattice),
            daemonset_pods=self.cluster.daemonset_pods,
            bound_pods=self.cluster.bound_pods,
            pvcs=pvcs, storage_classes=storage_classes,
            pool_headroom=headroom, dirty=dirty, touched=touched)
        if solve and pending:
            # solve_relaxed is side-effect free: plans are computed, never
            # acted on — this is compile/trace warmth only
            self.solver.solve_relaxed(
                pending, pools, lattice,
                existing=self.cluster.existing_bins(lattice),
                daemonset_pods=self.cluster.daemonset_pods(),
                bound_pods=self.cluster.bound_pods(),
                pvcs=pvcs, storage_classes=storage_classes,
                pool_headroom=headroom, problem0=build.problem)
        return build.problem is not None

    def _provision(self, pending: Sequence[Pod],
                   rev0: Optional[int] = None) -> ProvisionResult:
        # versioned memo: the SAME view object comes back while prices and
        # the ICE set are unchanged, so the solver's identity-keyed
        # narrowing cache hits across steady-state passes
        lattice = masked_view_versioned(self.solver.lattice, self.unavailable)
        pvcs, storage_classes = self.cluster.volume_state()
        # one usage snapshot serves the whole pass: the initial solve's
        # headroom, every _enforce_limits round, and every retry's headroom
        pass_usage = self.cluster.pool_usage()
        headroom = self._pool_headroom(pass_usage)
        pools = list(self.node_pools.values())
        # memoized thunks: the O(pods) cluster scans resolve at most once
        # per pass, and NOT AT ALL when the incremental builder proves
        # from the dirty journal that their inputs did not change
        resolved: Dict[str, object] = {}

        def _existing():
            if "existing" not in resolved:
                resolved["existing"] = self.cluster.existing_bins(lattice)
            return resolved["existing"]

        def _ds():
            if "ds" not in resolved:
                resolved["ds"] = self.cluster.daemonset_pods()
            return resolved["ds"]

        def _bound():
            if "bound" not in resolved:
                resolved["bound"] = self.cluster.bound_pods()
            return resolved["bound"]

        problem0 = None   # the round-0 problem (carries the ledgers)
        batched = [False]   # overlap seam fired (observation staged)?
        try:
            if self._delta_enabled:
                # the coalescer already merged every journal tick since
                # the last build (batch_ready polls drain it); take() is
                # one short drain, not the whole backlog
                dirty = self.journal_coalescer.take(self.inc_builder.rev)
                if rev0 is not None:
                    # key the build at the pre-snapshot revision: journal
                    # entries racing the pending snapshot stay > rev0 and
                    # are re-read (idempotently) next pass
                    dirty.rev = rev0
                touched = (self.cluster.touched_pods(dirty.pods)
                           if dirty.pods and not dirty.full else {})
                build = self.inc_builder.build(
                    pending, pools, lattice, existing=_existing,
                    daemonset_pods=_ds, bound_pods=_bound, pvcs=pvcs,
                    storage_classes=storage_classes,
                    pool_headroom=headroom, dirty=dirty, touched=touched)
                problem0 = build.problem
                if build.incremental:
                    # the steady-state fast path: patched problem, the
                    # device-resident microloop, dirty blocks only over
                    # the link. Admission bookkeeping rides the in-
                    # flight dispatch through the overlap seam instead
                    # of serializing behind the solve.
                    # the seam only STAGES the observation — the commit
                    # happens after the solve lands, so a pass whose
                    # dispatch fired the seam but then dropped its wave
                    # (post-dispatch device fault + fallback failure)
                    # never skews the admission histograms
                    def _admission_overlap():
                        batched[0] = True
                    plan = self.solver.solve_delta(
                        build.problem, dirty_groups=build.dirty_groups,
                        overlap=_admission_overlap)
                    self._m_delta.inc()
                else:
                    # full path; round 0 reuses the problem already built
                    plan = self.solver.solve_relaxed(
                        pending, pools, lattice, existing=_existing(),
                        daemonset_pods=_ds(), bound_pods=_bound(),
                        pvcs=pvcs, storage_classes=storage_classes,
                        pool_headroom=headroom, problem0=build.problem)
            else:
                plan = self.solver.solve_relaxed(
                    pending, pools, lattice, existing=_existing(),
                    daemonset_pods=_ds(), bound_pods=_bound(),
                    pvcs=pvcs, storage_classes=storage_classes,
                    pool_headroom=headroom)
        except Exception as e:
            # the solve ladder already absorbs device failures; anything
            # that still escapes must not kill the reconcile loop. Report a
            # PARTIAL (empty) result — the pods stay pending and the next
            # pass retries — instead of dropping the wave with a crash.
            return self._solve_failed(e, len(pending))
        # admission metrics commit only for a LANDED wave (a failed pass
        # returned above) — the staged overlap observation included
        self._m_batch.observe(len(pending))
        if batched[0]:
            self._m_dirty_groups.observe(len(build.dirty_groups))
        self._m_sched.observe(plan.solve_seconds)
        self._m_sim.observe(plan.device_seconds)
        self._mirror_link_metrics()
        if self.slo is not None:
            # the rolling latency window behind
            # karpenter_slo_latency_budget_burn; the cost referee is
            # cadence-gated inside the tracker (a host FFD re-pack of
            # the SAME inputs, never on every pass)
            self.slo.record_latency(plan.solve_seconds)

            def _referee_problem():
                from ..solver.problem import build_problem
                return build_problem(
                    list(pending), list(self.node_pools.values()), lattice,
                    existing=self.cluster.existing_bins(lattice),
                    daemonset_pods=self.cluster.daemonset_pods(),
                    bound_pods=self.cluster.bound_pods(),
                    pvcs=pvcs, storage_classes=storage_classes,
                    pool_headroom=self._pool_headroom(pass_usage))
            self.slo.maybe_cost_referee(plan, _referee_problem)
        result = ProvisionResult(plan=plan)
        self._observe_solver_health(plan, result)

        # the pass explanation: ledgers from the round-0 problem + the
        # plan's outcome; limit-fallback drops and claim rationale fold
        # in below, and the finished record lands in the audit ring at
        # pass end. RemoteSolver passes (no local problem) still record
        # outcome + reason codes, just without the waterfall.
        sp_now = trace.current()
        expl = explain_mod.explain_pass(
            problem0, plan, next(self._pass_seq),
            sp_now.trace_id if sp_now is not None else "",
            self.clock.now())
        # every unschedulable reason seen THIS pass (all plans + limit
        # drops): the dedup map re-arms from it at pass end
        seen_unsched: Dict[str, str] = {}
        pod_by_name: Dict[str, Pod] = {}

        def surface_unschedulable(p: NodePlan, first: bool = False) -> None:
            if p.unschedulable and not pod_by_name:
                # built only when a pass actually has unschedulable pods
                pod_by_name.update({q.name: q for q in pending})
            for name, reason in p.unschedulable.items():
                self._publish_failed(name, reason, seen_unsched,
                                     pod=pod_by_name.get(name))
                if not first:
                    explain_mod.add_unschedulable(expl, name, reason)
            result.pods_unschedulable += len(p.unschedulable)

        def bind_existing(p: NodePlan) -> None:
            # pods that fit existing capacity bind (in the real control
            # plane the kube-scheduler binds; the sim binds directly,
            # reference stratum-2). The whole plan's binds go as ONE
            # batched write (writer.bind_pods → the apiserver bulk
            # verb): bind_pod was the profiled #1 write-path frame,
            # paying lock + fan-out per pod.
            to_bind: List[Tuple[str, str]] = []
            for node_name, pods in p.existing_assignments.items():
                target_is_claim = (node_name in self.cluster.claims
                                   and node_name not in self.cluster.nodes)
                for pn in pods:
                    if target_is_claim:
                        # nominations count at decision time — a pod
                        # deleted before the claim registers drops out
                        # of nominated_pods() and is simply never bound
                        self.cluster.nominate(pn, node_name)
                        result.pods_scheduled += 1
                    else:
                        to_bind.append((pn, node_name))
            if to_bind:
                # raced binds (pod evicted/deleted under us in threaded
                # API mode) report False and don't count as scheduled
                result.pods_scheduled += sum(self.writer.bind_pods(to_bind))

        surface_unschedulable(plan, first=True)
        bind_existing(plan)

        # limits + fallback (scheduling.md:488): a node the pool's limits
        # cannot hold re-solves its pods against the remaining pools —
        # the reserved-capacity pattern (high-weight limited pool fills
        # first, overflow lands on the generic pool). The loop terminates:
        # each retry excludes at least one more saturated pool.
        planned: List[PlannedNode] = []
        # each planned node remembers the PLAN that produced it (the
        # limit-fallback loop can mix plans in one pass), so its claim is
        # stamped with the right solve's provenance annotations
        prov_by_node: Dict[int, Dict[str, str]] = {}
        current = plan
        excluded: set = set()
        for _ in range(len(self.node_pools) + 1):
            fitting, dropped = self._enforce_limits(current.new_nodes,
                                                    usage=pass_usage)
            planned += fitting
            prov = self._provenance_annotations(current)
            for n in fitting:
                prov_by_node[id(n)] = prov
            if not dropped:
                break
            excluded |= {n.node_pool for n in dropped}
            pools_left = [p for p in self.node_pools.values()
                          if p.name not in excluded]
            retry_pods = [self.cluster.pods[pn] for n in dropped
                          for pn in n.pods if pn in self.cluster.pods]
            if not pools_left or not retry_pods:
                for n in dropped:
                    live = [pn for pn in n.pods if pn in self.cluster.pods]
                    msg = taxonomy.reason(
                        taxonomy.POOL_LIMITS,
                        f"nodepool {n.node_pool} limit exceeded")
                    for pn in live:
                        self._publish_failed(pn, msg, seen_unsched,
                                             pod=self.cluster.pods.get(pn))
                        explain_mod.add_unschedulable(expl, pn, msg)
                    result.pods_unschedulable += len(live)
                break
            try:
                current = self.solver.solve_relaxed(
                    retry_pods, pools_left, lattice,
                    existing=self.cluster.existing_bins(lattice),
                    daemonset_pods=self.cluster.daemonset_pods(),
                    bound_pods=self.cluster.bound_pods(),
                    pvcs=pvcs, storage_classes=storage_classes,
                    pool_headroom=self._pool_headroom(pass_usage))
            except Exception as e:
                # a failed limit-fallback re-solve degrades to a partial
                # pass: keep everything already planned/bound, leave the
                # retry pods pending for the next pass
                self._note_solve_failure(e, result)
                break
            self._observe_solver_health(current, result)
            surface_unschedulable(current)
            bind_existing(current)
            # retry-round existing-capacity placements reach the audit
            # ring too (round 0's came in with explain_pass)
            explain_mod.add_placements(expl, current)
        for node in planned:
            claim = self._make_claim(node)
            claim.annotations.update(prov_by_node.get(id(node), {}))
            self.writer.create_claim(claim)
            self._m_created.inc(nodepool=claim.node_pool)
            result.created_claims.append(claim)
            for p in node.pods:
                self.cluster.nominate(p, claim.name)
            try:
                self.cloud_provider.create(claim)
                # write the launch results (providerID/type/zone/phase)
                # back through the seam — the reference's status update
                self.writer.update_claim_status(claim)
                self._m_launched.inc(nodepool=claim.node_pool)
                result.launched += 1
                result.pods_scheduled += len(node.pods)
                # the launch fixed the zone: bind nominated pods' unbound
                # claims NOW so a cross-batch consumer arriving before the
                # node registers already sees the pinned zone
                for p in node.pods:
                    self.writer.bind_volumes(p, claim.zone)
                self.recorder.publish("Normal", "Launched", "NodeClaim", claim.name,
                                      f"{claim.instance_type}/{claim.zone}/{claim.capacity_type} "
                                      f"for {len(node.pods)} pod(s)")
                # placement rationale (chosen offering, runner-up type +
                # price delta) for `kpctl explain nodeclaim`
                explain_mod.add_claim(expl, claim.name, node,
                                      runner_up=self._runner_up(node))
            except UnfulfillableCapacityError:
                # offerings already marked unavailable by the provider; the
                # pods return to pending and the next pass re-solves with the
                # tightened ICE mask (instance.go:348-354 feedback loop)
                result.launch_failures += 1
                self.writer.rollback_claim(claim.name)
                result.created_claims.pop()
            except Exception as e:
                # a reconcile loop must survive any launch failure
                # (misconfigured NodeClass, transient API error): roll the
                # claim back, surface the cause, keep launching the rest
                result.launch_failures += 1
                self.recorder.publish("Warning", "LaunchFailed", "NodeClaim",
                                      claim.name, f"{type(e).__name__}: {e}")
                self.writer.rollback_claim(claim.name)
                result.created_claims.pop()
        self._m_sched_pods.inc(result.pods_scheduled)
        self._m_unsched_pods.set(result.pods_unschedulable)
        # the explain surfaces: reason-code counters (rate-able per
        # pass, like FailedScheduling events pre-dedup), per-stage
        # elimination counters, and the audit-ring record
        for code, n in expl.reason_counts.items():
            self._m_unsched_reasons.inc(n, code=code)
        for stage, n in expl.eliminations.items():
            self._m_eliminations.inc(n, stage=stage)
        self.explain.record(expl)
        self._finish_pass(result, len(pending),
                          solve_ms=plan.solve_seconds * 1000.0,
                          seen_unsched=seen_unsched)
        return result

    def _publish_failed(self, name: str, reason: str,
                        seen: Dict[str, str], pod=None) -> None:
        """Publish FailedScheduling deduped per (pod, reason-code): the
        same stuck pod re-surfacing with the same code on every pass
        publishes ONCE; a changed code, a renewed failure after
        progress, or a same-name RECREATED pod (different object — see
        _failed_pub) re-publishes. ``seen`` collects this pass's
        unschedulable set for the re-arm sweep in _finish_pass."""
        seen[name] = reason
        code = taxonomy.code_of(reason)
        prev = self._failed_pub.get(name)
        if prev is not None and prev[0] == code \
                and (pod is None or prev[1] is pod):
            return
        self._failed_pub[name] = (code, pod)
        self.recorder.publish("Warning", "FailedScheduling", "Pod",
                              name, reason)

    def _runner_up(self, node: PlannedNode):
        """(type, cheapest offering price) of the bin's second-cheapest
        feasible type — the price delta `kpctl explain nodeclaim`
        renders next to the chosen offering. Priced against the MASKED
        lattice (the one the pass solved against): an ICE'd-out
        offering must never present as the viable alternative. None
        when the bin had no (currently available) flexibility."""
        alts = [t for t in node.feasible_types if t != node.instance_type]
        if not alts:
            return None
        import dataclasses
        probe = dataclasses.replace(node, instance_type=alts[0], pods=[])
        price = self._offering_price(
            probe, lat=masked_view_versioned(self.solver.lattice,
                                             self.unavailable))
        return (alts[0], price) if np.isfinite(price) else None

    def _finish_pass(self, result: ProvisionResult, n_pending: int,
                     solve_ms: float = 0.0,
                     seen_unsched: Optional[Dict[str, str]] = None) -> None:
        """End-of-pass bookkeeping: the pods_state gauge re-renders from
        the mirror (binds/nominations just changed the phase split) and
        the introspection record captures the pass's outcome."""
        counts = self.cluster.pod_phase_counts()
        self._m_pods_state.replace({(k,): float(v)
                                    for k, v in counts.items()})
        if seen_unsched is not None:
            # re-arm the FailedScheduling dedup for pods that made
            # progress: anything no longer unschedulable this pass
            # (bound, nominated, deleted) drops out, so a LATER failure
            # publishes again. A solve-error pass passes None — the
            # batch never got examined, nothing re-arms.
            for gone in [n for n in self._failed_pub
                         if n not in seen_unsched]:
                del self._failed_pub[gone]
        with self._lock:
            self.passes += 1
            self._last_pass = {
                "t": round(self.clock.now(), 3),
                "pods": n_pending,
                "launched": result.launched,
                "scheduled": result.pods_scheduled,
                "unschedulable": result.pods_unschedulable,
                "degraded": 1.0 if result.degraded else 0.0,
                "solve_ms": round(solve_ms, 3),
            }

    def stats(self) -> Dict[str, float]:
        """Introspection provider: batch-window occupancy + solver
        cadence (what `kpctl top`'s BATCH/SOLVER rows render)."""
        now = self.clock.now()
        with self._lock:
            out: Dict[str, float] = {
                "batch_pending": len(self._known_pending),
                "batch_age_seconds": (round(now - self._batch_start, 3)
                                      if self._batch_start is not None
                                      else 0.0),
                "passes": self.passes,
                # the incremental problem builder's build split
                # (solver/incremental.py; the delta-SOLVE counters ride
                # the solver provider)
                "incremental_builds": self.inc_builder.incremental_builds,
                "full_builds": self.inc_builder.full_builds,
                # journal → device-block coalescer activity (state/
                # cluster.py DirtyJournalCoalescer): batch-window drains,
                # pass-start takes, and anchor-mismatch fallbacks
                "journal_ticks": self.journal_coalescer.ticks,
                "journal_takes": self.journal_coalescer.takes,
                "journal_take_fallbacks": self.journal_coalescer.fallbacks,
            }
            out.update({"last_pass_" + k: v
                        for k, v in self._last_pass.items()})
        return out

    def _mirror_link_metrics(self) -> None:
        """Mirror the solver's cumulative link accounting into the
        karpenter_solver_link_legs_total / _link_bytes_total counters
        (per-pass delta inc — the solver counts transfers, the metric
        registry owns exposition). A solver without link accounting
        (RemoteSolver, SolverPool) simply never moves these."""
        ls = getattr(self.solver, "link_stats", None)
        if not ls:
            return
        for direction in ("upload", "fetch"):
            for kind, metric in (("legs", self._m_link_legs),
                                 ("bytes", self._m_link_bytes)):
                k = f"{direction}_{kind}"
                cur = int(ls.get(k, 0))
                d = cur - self._link_prev.get(k, 0)
                if d > 0:
                    metric.inc(d, direction=direction)
                self._link_prev[k] = cur

    # ---- degradation observation (docs/concepts/degradation.md) ----------

    def _provenance_annotations(self, plan: NodePlan) -> Dict[str, str]:
        """Solver provenance for a claim's annotations — the wire-visible
        record of WHY this claim's solve was slow or degraded, which
        `kpctl describe nodeclaims` renders for operators. The pass
        span's traceparent rides along so a claim points straight at its
        flight-recorder trace (and NodeClaim registration joins it)."""
        import json as _json
        ann = {
            wk.ANNOTATION_SOLVER_PATH: plan.solver_path,
            wk.ANNOTATION_SOLVER_PIPELINED:
                "true" if plan.pipelined else "false",
            wk.ANNOTATION_SOLVER_WAVES: str(plan.waves),
        }
        if getattr(plan, "mesh_devices", 1) > 1:
            # the sharded production path: which mesh packed this claim
            # (absent = single-device; kpctl describe renders the row)
            ann[wk.ANNOTATION_SOLVER_MESH_DEVICES] = str(plan.mesh_devices)
        if plan.degraded_reason:
            ann[wk.ANNOTATION_SOLVER_DEGRADED_REASON] = plan.degraded_reason
        if plan.stage_ms:
            ann[wk.ANNOTATION_SOLVER_STAGE_MS] = _json.dumps(
                {k: round(float(v), 3) for k, v in plan.stage_ms.items()},
                sort_keys=True, separators=(",", ":"))
        tp = trace.capture()
        if tp:
            ann[wk.ANNOTATION_TRACEPARENT] = tp
        return ann

    def _observe_solver_health(self, plan: NodePlan,
                               result: ProvisionResult) -> None:
        """Mirror a plan's degradation provenance into the metric surface
        and the event stream — the operator-facing signal that the solve
        left the primary device path."""
        if plan.device_retries:
            self._m_solver_retries.inc(plan.device_retries)
        self._m_waves.observe(plan.waves)
        # per-stage timings (seconds, like every duration series): the
        # overlap evidence — on a pipelined solve "download" is only the
        # residual wait after prefetch/decode-prep ran inside the window.
        # The ambient pass span's trace id rides as an EXEMPLAR, so a
        # dashboard's slow histogram bucket links to a concrete retained
        # trace (`kpctl trace export <id>`).
        sp = trace.current()
        exemplar = sp.trace_id if sp is not None else None
        for stage, ms in plan.stage_ms.items():
            self._m_stage.observe(ms / 1000.0, exemplar=exemplar,
                                  stage=stage)
        if plan.degraded:
            reason = plan.degraded_reason or "unknown"
            self._m_degraded.inc(path=plan.solver_path, reason=reason)
            result.degraded = True
            result.degraded_reason = result.degraded_reason or reason
            self.recorder.publish(
                "Warning", "SolverDegraded", "Provisioner", "default",
                f"solve degraded to {plan.solver_path} ({reason}, "
                f"{plan.waves} wave(s))")

    def _note_solve_failure(self, e: Exception,
                            result: ProvisionResult) -> None:
        self._m_degraded.inc(path="none", reason="solve-error")
        result.degraded = True
        result.degraded_reason = result.degraded_reason or "solve-error"
        self.recorder.publish("Warning", "SolverFailed", "Provisioner",
                              "default", f"{type(e).__name__}: {e}")

    def _solve_failed(self, e: Exception, n_pending: int) -> ProvisionResult:
        result = ProvisionResult(plan=None)
        self._note_solve_failure(e, result)
        # the early return skips the end-of-pass gauge update: reflect the
        # whole stuck batch as unschedulable so dashboards show the outage's
        # blast radius instead of freezing at the previous pass's value
        result.pods_unschedulable = n_pending
        self._m_unsched_pods.set(n_pending)
        # the audit ring records the outage pass too: the whole batch is
        # pending for reason solve-error (partial-result guard), so
        # `kpctl explain pass` answers "why is everything stuck" during
        # a solver outage
        sp_now = trace.current()
        expl = explain_mod.PassExplanation(
            pass_id=next(self._pass_seq),
            trace_id=sp_now.trace_id if sp_now is not None else "",
            t=self.clock.now(), pods=n_pending,
            note=f"solve failed: {type(e).__name__}: {e}")
        expl.unschedulable_total = n_pending
        expl.reason_counts[taxonomy.SOLVE_ERROR] = n_pending
        self._m_unsched_reasons.inc(n_pending, code=taxonomy.SOLVE_ERROR)
        self.explain.record(expl)
        self._finish_pass(result, n_pending)
        return result

    @staticmethod
    def _remaining(pool: NodePool, current: np.ndarray) -> Optional[np.ndarray]:
        """The pool's remaining limit budget per axis: limit - current on
        every axis the pool names (an explicit 0 is the standard
        pause-this-pool pattern and must block), np.inf elsewhere. The
        single source of the limited-axes semantics — both the solve-time
        headroom mask and _enforce_limits consume it."""
        limit = pool.limits_vec()
        if limit is None:
            return None
        rem = np.full((R,), np.inf, np.float32)
        for key in pool.limits:
            try:
                ax = res_axis(key)
            except KeyError:
                continue
            rem[ax] = max(limit[ax] - current[ax], 0.0)
        return rem

    def _pool_headroom(self, usage: Dict[str, np.ndarray]
                       ) -> Dict[str, np.ndarray]:
        """Per limited pool: remaining capacity budget (see _remaining).
        Fed into the solve so a fresh node's type options shrink as the
        pool approaches spec.limits — the reference caps its in-flight
        simulated nodes the same way, which is what lets a limited pool
        fill partially instead of all-or-nothing."""
        zeros = np.zeros((R,), np.float32)
        out: Dict[str, np.ndarray] = {}
        for name, pool in self.node_pools.items():
            rem = self._remaining(pool, usage.get(name, zeros))
            if rem is not None:
                out[name] = rem
        return out

    def _offering_price(self, node: PlannedNode,
                        lat: Optional[Lattice] = None) -> float:
        """Cheapest available offering price for the node's instance type
        within its feasible zone/capacity-type sets (``lat`` overrides
        the base lattice — the runner-up rationale prices against the
        ICE-masked view)."""
        lat = lat if lat is not None else self.solver.lattice
        ti = lat.name_to_idx.get(node.instance_type)
        if ti is None:
            return float("inf")
        zs = [lat.zones.index(z) for z in (node.feasible_zones or lat.zones)
              if z in lat.zones]
        cs = [lat.capacity_types.index(c)
              for c in (node.feasible_capacity_types or lat.capacity_types)
              if c in lat.capacity_types]
        if not zs or not cs:
            return float("inf")
        sub = np.where(lat.available[np.ix_([ti], zs, cs)],
                       lat.price[np.ix_([ti], zs, cs)], np.inf)
        return float(sub.min())

    def _enforce_limits(self, nodes: Sequence[PlannedNode],
                        usage: Optional[Dict[str, np.ndarray]] = None,
                        ) -> Tuple[List[PlannedNode], List[PlannedNode]]:
        """Enforce NodePool resource limits on the plan (CRD nodepools
        limits). A violating node first tries to DOWNSIZE: every type in the
        bin's feasible set can hold the bin's pods by construction, so the
        cheapest one whose capacity fits the remaining budget substitutes.
        Returns (fitting nodes, dropped nodes) — the caller decides whether
        dropped pods retry against other pools (the scheduling.md:488
        Fallback pattern) or surface as unschedulable.

        ``usage`` carries committed capacity ACROSS calls: the fallback
        loop passes one dict for the whole pass so nodes accepted in an
        earlier retry round keep counting against their pool's limit
        (cluster state alone misses them — their claims are only created
        after the loop)."""
        if usage is None:
            usage = self.cluster.pool_usage()
        out: List[PlannedNode] = []
        dropped: List[PlannedNode] = []
        lat = self.solver.lattice
        for node in nodes:
            pool = self.node_pools.get(node.node_pool)
            limit = pool.limits_vec() if pool is not None else None
            if limit is None:
                out.append(node)
                continue
            current = usage.get(node.node_pool, np.zeros((R,), np.float32))
            remaining = self._remaining(pool, current)
            kub = pool.kubelet

            def node_capacity(tname: str) -> np.ndarray:
                """What the launched node will actually charge against
                the pool's limits — the kubelet maxPods clamp applies at
                create, so limit accounting must see the clamped value
                (pool_usage later charges exactly this)."""
                cap = lat.capacity[lat.name_to_idx[tname]]
                if kub is not None and kub.max_pods is not None:
                    cap = cap.copy()
                    cap[_PODS_AXIS] = kub.clamp_pods(cap[_PODS_AXIS])
                return cap

            def fits(tname: str) -> bool:
                return bool(np.all(node_capacity(tname) <= remaining + 1e-6))

            candidates = node.feasible_types or [node.instance_type]
            fitting = [t for t in candidates if fits(t)]
            if not fitting:
                dropped.append(node)
                continue
            # restrict the claim's launch flexibility to limit-fitting types
            node.feasible_types = fitting
            if node.instance_type not in fitting:
                node.instance_type = fitting[0]  # cheapest-first order
                node.price_per_hour = self._offering_price(node)
            usage[node.node_pool] = current + node_capacity(node.instance_type)
            out.append(node)
        return out, dropped

    def _make_claim(self, node: PlannedNode) -> NodeClaim:
        """NodePlan bin → NodeClaim launch contract. The claim carries the
        bin's full feasible offering sets so the launch path has CreateFleet
        flexibility without a re-solve."""
        pool = self.node_pools[node.node_pool]
        name = f"{node.node_pool}-{next(self._claim_ids):05d}"
        reqs: List[Requirement] = list(pool.requirements)
        if node.feasible_types:
            reqs.append(Requirement(wk.LABEL_INSTANCE_TYPE, Operator.IN,
                                    tuple(node.feasible_types)))
        else:
            reqs.append(Requirement(wk.LABEL_INSTANCE_TYPE, Operator.IN,
                                    (node.instance_type,)))
        reqs.append(Requirement(wk.LABEL_ZONE, Operator.IN,
                                tuple(node.feasible_zones or [node.zone])))
        reqs.append(Requirement(wk.LABEL_CAPACITY_TYPE, Operator.IN,
                                tuple(node.feasible_capacity_types or [node.capacity_type])))
        requests: Dict[str, float] = {}
        total = np.zeros((R,), np.float32)
        for p in node.pods:
            pod = self.cluster.pods.get(p)
            if pod is not None:
                total += resources_to_vec(pod.requests, implicit_pod=True)
        from ..apis.resources import vec_to_resources
        requests = vec_to_resources(total)
        labels = {**pool.labels, **node.extra_labels}
        # a value-free template requirement on a custom key (Exists, or In
        # over several values) means the node must still CARRY the label
        # even when no workload named one — generate/pick it
        # (scheduling.md:554 "Karpenter will generate a random label")
        from ..solver.problem import _is_custom_key
        for r in pool.requirements:
            if not _is_custom_key(r.key) or r.key in labels:
                continue
            if r.operator == Operator.EXISTS:
                labels[r.key] = f"kpat-{name}"
            elif r.operator == Operator.IN and r.values:
                labels[r.key] = sorted(r.values)[0]
        # the node's OS label comes from the pool's resolved OS (the AMI
        # family's, pool_os — the same resolution build_problem pins the
        # pool's constraint to, so label and schedulability always agree)
        from ..apis.objects import pool_os
        labels.setdefault(wk.LABEL_OS, pool_os(pool))
        claim = NodeClaim(
            name=name, node_pool=node.node_pool,
            requirements=reqs, resource_requests=requests,
            labels=labels,
            # template annotations propagate (disruption.md:294 — a
            # do-not-disrupt NodePool shields every node it launches)
            annotations={**pool.annotations,
                         wk.ANNOTATION_NODEPOOL_HASH: nodepool_hash(pool),
                         wk.ANNOTATION_NODEPOOL_HASH_VERSION:
                             NODEPOOL_HASH_VERSION},
            taints=list(pool.taints), node_class_ref=pool.node_class_ref,
            max_pods=(pool.kubelet.max_pods if pool.kubelet is not None
                      else None),
            cluster_dns=(pool.kubelet.cluster_dns if pool.kubelet is not None
                         else None),
            created_at=self.clock.now())
        return claim
