"""Termination controller: finalizer-style drain then instance delete.

Mirror of the core termination flow (reference designs/termination.md;
website concepts/disruption.md:29-36): a NodeClaim with a deletion
timestamp gets its node tainted (cordon), pods evicted back to pending,
then CloudProvider.Delete terminates the instance, and finally the claim
and node objects are removed (finalizer cleared).
"""

from __future__ import annotations

from typing import Optional

from ..apis.objects import NodeClaim, NodeClaimPhase, Taint, TaintEffect
from ..apis import wellknown as wk
from ..cloudprovider.cloudprovider import CloudProvider
from ..errors import NotFoundError
from ..events import Recorder
from ..metrics import Registry, wire_core_metrics
from ..state.cluster import ClusterState
from ..utils.clock import Clock

DISRUPTION_TAINT = Taint(key=f"{wk.KARPENTER_PREFIX}/disruption", value="disrupting",
                         effect=TaintEffect.NO_SCHEDULE)


class TerminationController:
    def __init__(self, cluster: ClusterState, cloud_provider: CloudProvider,
                 recorder: Optional[Recorder] = None, clock: Optional[Clock] = None,
                 metrics: Optional[Registry] = None,
                 termination_grace_period: Optional[float] = None,
                 writer=None):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock or Clock()
        from ..kube.writer import DirectWriter
        self.writer = writer or DirectWriter(cluster, self.clock)
        self.recorder = recorder or Recorder(self.clock)
        # None = a PDB-blocked drain waits forever (the pinned reference
        # release); a float force-drains claims terminating longer than
        # this, so a zero-allowance budget cannot bill an instance forever
        self.termination_grace_period = termination_grace_period
        # claims whose DrainBlocked event already published this episode
        self._drain_blocked_logged: set = set()
        m = wire_core_metrics(metrics or Registry())
        self._m_terminated = m["nodeclaims_terminated"]

    def delete_claim(self, claim_name: str) -> None:
        """Mark for deletion (the k8s delete that starts the finalizer flow)."""
        self.writer.mark_claim_deleting(claim_name)

    def reconcile(self) -> None:
        for claim in list(self.cluster.claims.values()):
            if not claim.deletion_timestamp:
                continue
            node = self.cluster.node_for_claim(claim.name)
            if node is not None:
                # cordon, then PDB-respecting drain: the node is deleted
                # only once fully drained (reference disruption.md:33 —
                # evict via the Eviction API to respect PDBs, wait for the
                # node to be fully drained before terminating)
                if self.writer.cordon(node, DISRUPTION_TAINT):
                    self.recorder.publish("Normal", "Cordoned", "Node", node.name, "")
                evicted, blocked = self.writer.drain_node(node.name)
                if evicted:
                    self.recorder.publish("Normal", "Drained", "Node", node.name,
                                          f"evicted {len(evicted)} pod(s)")
                grace_expired = (
                    self.termination_grace_period is not None
                    and self.clock.now() - claim.deletion_timestamp
                    >= self.termination_grace_period)
                if blocked and grace_expired:
                    # force-drain backstop: the budget lost its veto; the
                    # blocked pods evict in the final teardown below
                    self.recorder.publish(
                        "Warning", "ForceDrained", "Node", node.name,
                        f"termination grace period expired; evicting "
                        f"{len(blocked)} budget-blocked pod(s)")
                    blocked = []
                if blocked:
                    # retry next pass: rescheduled pods going healthy
                    # elsewhere restore the budgets' allowance. One event
                    # per blockage episode — this runs every second in
                    # the async runtime and must not flood the recorder
                    if claim.name not in self._drain_blocked_logged:
                        self._drain_blocked_logged.add(claim.name)
                        pdb = self.cluster.pdb_blockers(blocked)
                        self.recorder.publish(
                            "Warning", "DrainBlocked", "Node", node.name,
                            f"{len(blocked)} pod(s) await disruption budget "
                            f"({', '.join(sorted(set(pdb.values())) or ['-'])})")
                    continue
                self._drain_blocked_logged.discard(claim.name)
                # fully drained (or force-drained): final teardown evicts
                # any stragglers and deletes daemonset pods with the node
                self.writer.teardown_node(node.name)
            if claim.provider_id is not None:
                try:
                    self.cloud_provider.delete(claim)
                except NotFoundError:
                    pass
            claim.phase = NodeClaimPhase.TERMINATED
            self._m_terminated.inc(nodepool=claim.node_pool)
            self._drain_blocked_logged.discard(claim.name)
            # finalizer cleared -> the claim object is removed
            self.writer.finalize_claim(claim)
            self.recorder.publish("Normal", "Terminated", "NodeClaim", claim.name, "")
