"""Admission window in front of Solve(): coalesce concurrent solves.

The reference coalesces CreateFleet/DescribeInstances calls behind idle/
max windows (batcher.go); the TPU-native analog is the SOLVE call — the
operator's reconcile loop, the gRPC sidecar's RPC handlers, and any
in-process controller can all reach the resident Solver concurrently,
and each caller that misses the solver lock pays the tunneled link's
round trip SERIALLY after the previous caller's solve. The window parks
concurrent arrivals for a few milliseconds, then one worker drains the
batch back-to-back under a SINGLE solver-lock acquisition:

- callers that arrived together stop interleaving with unrelated device
  work (no lock convoy, no re-warming another caller's resident state),
- the drain runs on the solver's pipelined path, so request k+1's input
  upload overlaps request k's decode — the batch pays the link once per
  solve's compute, not once per caller wait-cycle,
- the resident-input delta cache (solver/pipeline.py) sees consecutive
  same-shaped problems, exactly the access pattern it is built for.

Results (or per-request exceptions) fan back out positionally, like
every other Batcher user.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

from .. import trace
from .batcher import Batcher, BatcherOptions

# a solve window is much tighter than the CreateFleet window: the point
# is catching genuinely-concurrent callers, not delaying a lone one
SOLVE_WINDOW_OPTIONS = BatcherOptions(idle_seconds=0.005, max_seconds=0.25,
                                      max_items=64)


class SolveWindow:
    """Batcher-fronted entry to ``Solver.solve_relaxed``.

    ``solve_relaxed(...)`` mirrors the Solver signature and blocks until
    the fused drain completes; requests that arrive inside the window
    execute back-to-back holding the solver lock once."""

    def __init__(self, solver, options: Optional[BatcherOptions] = None,
                 timeout: float = 300.0):
        self.solver = solver
        self.timeout = timeout
        self._batcher: Batcher = Batcher(
            self._drain, options or SOLVE_WINDOW_OPTIONS)
        from ..introspect import contention
        self._lock = contention.lock("solve_window")
        # observability: how often the window actually fused callers
        self.batches = 0
        self.coalesced = 0      # requests that shared a drain with others

    def solve_relaxed(self, *args, **kwargs):
        # the caller's trace context rides the request tuple: the drain
        # runs on the bucket worker (no ambient context), and each
        # coalesced solve must land in ITS caller's trace — a sidecar RPC
        # that waited out the window still yields one connected span tree
        return self._batcher.add((args, kwargs, trace.capture()),
                                 timeout=self.timeout)

    def stats(self) -> dict:
        """Introspection provider: how often the window actually fused
        concurrent callers, plus the underlying batcher's occupancy."""
        with self._lock:
            out = {"batches": self.batches, "coalesced": self.coalesced}
        for k, v in self._batcher.stats().items():
            out["batcher_" + k] = v
        return out

    def _drain(self, requests: List[Tuple[tuple, dict, object]]) -> Sequence:
        with self._lock:
            self.batches += 1
            if len(requests) > 1:
                self.coalesced += len(requests)
        out = []
        # one lock acquisition for the whole batch: the drain owns the
        # device until every coalesced request is served (re-entrant —
        # solve_relaxed takes the same lock)
        with self.solver._solve_lock:
            for args, kwargs, ctx in requests:
                try:
                    # re-parent onto the producer: the solver's span tree
                    # (solve → waves → stages) nests under the caller's
                    # trace, and the drain position records how long the
                    # request queued behind its batch-mates
                    with trace.span("solve.window", parent=ctx,
                                    coalesced=len(requests)):
                        out.append(self.solver.solve_relaxed(*args, **kwargs))
                except BaseException as e:   # fail just this caller
                    out.append(e)
        return out
