from .batcher import Batcher, BatcherOptions

__all__ = ["Batcher", "BatcherOptions"]
