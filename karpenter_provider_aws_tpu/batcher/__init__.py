from .batcher import Batcher, BatcherOptions
from .solve_window import SolveWindow, SOLVE_WINDOW_OPTIONS

__all__ = ["Batcher", "BatcherOptions", "SolveWindow",
           "SOLVE_WINDOW_OPTIONS"]
