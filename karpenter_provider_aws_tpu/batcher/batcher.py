"""Generic request coalescer.

Mirror of the reference's hash-bucketed batcher (reference
pkg/batcher/batcher.go:61-131): concurrent callers Add() individual
requests; a worker collects them until an idle window elapses with no new
arrivals, a max window elapses, or the batch hits max_items, then executes
one fused call and fans results back out. The reference coalesces
CreateFleet at 35 ms idle / 1 s max / 1000 items
(createfleet.go:70-72) and DescribeInstances at 100 ms / 1 s / 500
(describeinstances.go:185-187); this framework reuses the same windows for
the fake-cloud launch/terminate paths AND as the device-batch admission
window in front of Solve() (SURVEY.md §2.3).

Requests are bucketed by an options hash so only like-for-like requests
fuse (the reference hashes everything but the instance-id list).
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Callable, Dict, Generic, Hashable, List, Sequence, Tuple, TypeVar

from .. import trace
from ..utils.clock import Clock, WALL

T = TypeVar("T")  # request
U = TypeVar("U")  # response


@dataclass
class BatcherOptions:
    idle_seconds: float = 0.035   # CreateFleet window (createfleet.go:70)
    max_seconds: float = 1.0
    max_items: int = 1000


class _Bucket(Generic[T, U]):
    """One hash bucket with a PERSISTENT worker thread.

    A drained worker parks on the wakeup event with NO timeout — an idle
    bucket costs zero periodic wakeups (the previous design timed out
    every idle window regardless). The max-window clock (``started_at``)
    starts at the batch's FIRST ARRIVAL (set by ``add`` when pending goes
    empty → non-empty), not at batch execution, so the max_seconds bound
    is measured from when the oldest caller started waiting."""

    def __init__(self, opts: BatcherOptions,
                 batch_fn: Callable[[List[T]], Sequence[U]],
                 clock: Clock = None):
        self.opts = opts
        self.batch_fn = batch_fn
        # the max-window clock reads the INJECTED clock (FakeClock in the
        # deterministic stratum; the shared wall instance otherwise) —
        # the idle-window park below stays a real Event wait either way
        self._clock = clock if clock is not None else WALL
        # (request, future, producer traceparent-or-None): the producer's
        # trace context rides the queue so the drain — which runs on the
        # bucket's own worker thread, outside any caller's contextvars —
        # can LINK its fused-call span back to every caller it served
        self.pending: List[Tuple[T, Future, object]] = []
        self.wakeup = threading.Event()
        # instrumented (introspect/contention.py): producer-vs-drain
        # contention on the bucket queue
        from ..introspect import contention
        self.lock = contention.lock("batcher_bucket")
        self.thread: threading.Thread = None
        self.started_at: float = 0.0
        # occupancy counters (introspect/ providers read these through
        # Batcher.stats(); mutated only under self.lock)
        self.batches = 0        # drains executed
        self.items = 0          # requests served
        self.max_batch = 0      # largest single drain

    def add(self, request: T, fut: Future) -> None:
        ctx = trace.capture()
        with self.lock:
            if not self.pending:
                # first arrival of this batch arms the max-window clock
                self.started_at = self._clock.monotonic()
            self.pending.append((request, fut, ctx))
            start = self.thread is None
            if start:
                self.thread = threading.Thread(target=self.run, daemon=True)
        self.wakeup.set()
        if start:
            self.thread.start()

    def run(self):
        while True:
            # drained: park with no timeout until the next arrival
            self.wakeup.wait()
            while True:
                self.wakeup.clear()
                with self.lock:
                    if not self.pending:
                        break   # back to the park
                    time_left = self.opts.max_seconds - (
                        self._clock.monotonic() - self.started_at)
                    full = len(self.pending) >= self.opts.max_items
                if not full and time_left > 0:
                    fired = self.wakeup.wait(
                        timeout=min(self.opts.idle_seconds, time_left))
                    if fired:
                        # new arrival inside the idle window: keep
                        # coalescing (until the max window closes)
                        continue
                with self.lock:
                    batch, self.pending = self.pending, []
                    if batch:
                        self.batches += 1
                        self.items += len(batch)
                        self.max_batch = max(self.max_batch, len(batch))
                if batch:
                    try:
                        self._execute(batch)
                    except BaseException as e:
                        # the worker is PERSISTENT now — a crash here
                        # would orphan this bucket's future arrivals, so
                        # fail this batch's callers and keep running
                        for _, fut, _ctx in batch:
                            if not fut.done():
                                fut.set_exception(e)

    def _execute(self, batch: List[Tuple[T, Future, object]]):
        inputs = [b[0] for b in batch]
        # the drain's span is a fresh root on the worker thread, LINKED to
        # every producer that contributed a request — the flight-recorder
        # view of "these N callers shared one fused call"
        links = [c for _, _, c in batch if c]
        # a single-caller drain JOINS its caller's trace; a fused drain is
        # its own root linked to every producer (a span cannot have N
        # parents — links are the standard answer)
        parent = links[0] if len(links) == 1 else None
        try:
            # materialize before the length check: a generator-returning
            # batch_fn must fail its callers, not kill the worker
            with trace.span("batch.drain", parent=parent,
                            links=links if len(links) > 1 else (),
                            n=len(batch), coalesced=len(batch) > 1):
                results = list(self.batch_fn(inputs))
            if len(results) != len(batch):
                raise RuntimeError(
                    f"batch_fn returned {len(results)} results "
                    f"for {len(batch)} requests")
        except BaseException as e:  # fan the failure out to every caller
            for _, fut, _ctx in batch:
                fut.set_exception(e)
            return
        for (_, fut, _ctx), res in zip(batch, results):
            if isinstance(res, BaseException):
                fut.set_exception(res)
            else:
                fut.set_result(res)


class Batcher(Generic[T, U]):
    """``batch_fn(requests) -> responses`` (positionally aligned; a response
    may be an exception instance to fail just that caller)."""

    def __init__(self, batch_fn: Callable[[List[T]], Sequence[U]],
                 options: BatcherOptions = None,
                 hasher: Callable[[T], Hashable] = None,
                 clock: Clock = None):
        self.batch_fn = batch_fn
        self.opts = options or BatcherOptions()
        self.hasher = hasher or (lambda _req: 0)
        self._clock = clock
        self._buckets: Dict[Hashable, _Bucket] = {}
        self._lock = threading.Lock()

    def add(self, request: T, timeout: float = 30.0) -> U:
        """Block until the fused call completes; return this request's result."""
        fut: Future = Future()
        key = self.hasher(request)
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None:
                bucket = _Bucket(self.opts, self.batch_fn, self._clock)
                self._buckets[key] = bucket
        bucket.add(request, fut)
        return fut.result(timeout=timeout)

    def stats(self) -> Dict[str, int]:
        """Occupancy snapshot for the introspection registry: bucket
        count, queued depth, drain counters. Cheap — per-bucket counter
        reads under each bucket's own lock, never blocking a drain."""
        with self._lock:
            buckets = list(self._buckets.values())
        pending = batches = items = 0
        max_batch = 0
        for b in buckets:
            with b.lock:
                pending += len(b.pending)
                batches += b.batches
                items += b.items
                max_batch = max(max_batch, b.max_batch)
        return {"buckets": len(buckets), "pending": pending,
                "batches": batches, "items": items, "max_batch": max_batch}

    def headroom_probe(self) -> Dict[str, float]:
        """Deepest bucket vs the max_items drain trigger
        (introspect/headroom.py). ``kind="ring"`` in the registry's
        sense — hitting max_items forces an immediate drain (the bound
        is a flush trigger, not a loss edge), so full is by design."""
        with self._lock:
            buckets = list(self._buckets.values())
        deepest = 0
        for b in buckets:
            with b.lock:
                if len(b.pending) > deepest:
                    deepest = len(b.pending)
        return {"depth": float(deepest),
                "capacity": float(self.opts.max_items),
                "kind": "ring"}
