"""Generic request coalescer.

Mirror of the reference's hash-bucketed batcher (reference
pkg/batcher/batcher.go:61-131): concurrent callers Add() individual
requests; a worker collects them until an idle window elapses with no new
arrivals, a max window elapses, or the batch hits max_items, then executes
one fused call and fans results back out. The reference coalesces
CreateFleet at 35 ms idle / 1 s max / 1000 items
(createfleet.go:70-72) and DescribeInstances at 100 ms / 1 s / 500
(describeinstances.go:185-187); this framework reuses the same windows for
the fake-cloud launch/terminate paths AND as the device-batch admission
window in front of Solve() (SURVEY.md §2.3).

Requests are bucketed by an options hash so only like-for-like requests
fuse (the reference hashes everything but the instance-id list).
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Callable, Dict, Generic, Hashable, List, Sequence, Tuple, TypeVar

T = TypeVar("T")  # request
U = TypeVar("U")  # response


@dataclass
class BatcherOptions:
    idle_seconds: float = 0.035   # CreateFleet window (createfleet.go:70)
    max_seconds: float = 1.0
    max_items: int = 1000


class _Bucket(Generic[T, U]):
    def __init__(self, opts: BatcherOptions,
                 batch_fn: Callable[[List[T]], Sequence[U]]):
        self.opts = opts
        self.batch_fn = batch_fn
        self.pending: List[Tuple[T, Future]] = []
        self.wakeup = threading.Event()
        self.lock = threading.Lock()
        self.thread: threading.Thread = None
        self.started_at: float = 0.0

    def run(self):
        import time
        while True:
            time_left = self.opts.max_seconds - (time.monotonic() - self.started_at)
            self.wakeup.clear()
            fired = self.wakeup.wait(timeout=min(self.opts.idle_seconds, max(time_left, 0.0)))
            with self.lock:
                if len(self.pending) >= self.opts.max_items:
                    fired = False
                    time_left = 0.0
            if fired and time_left > 0:
                continue  # new arrival inside the idle window: keep coalescing
            with self.lock:
                batch, self.pending = self.pending, []
                self.thread = None
            self._execute(batch)
            return

    def _execute(self, batch: List[Tuple[T, Future]]):
        inputs = [b[0] for b in batch]
        try:
            results = self.batch_fn(inputs)
        except BaseException as e:  # fan the failure out to every caller
            for _, fut in batch:
                fut.set_exception(e)
            return
        if len(results) != len(batch):
            err = RuntimeError(
                f"batch_fn returned {len(results)} results for {len(batch)} requests")
            for _, fut in batch:
                fut.set_exception(err)
            return
        for (_, fut), res in zip(batch, results):
            if isinstance(res, BaseException):
                fut.set_exception(res)
            else:
                fut.set_result(res)


class Batcher(Generic[T, U]):
    """``batch_fn(requests) -> responses`` (positionally aligned; a response
    may be an exception instance to fail just that caller)."""

    def __init__(self, batch_fn: Callable[[List[T]], Sequence[U]],
                 options: BatcherOptions = None,
                 hasher: Callable[[T], Hashable] = None):
        self.batch_fn = batch_fn
        self.opts = options or BatcherOptions()
        self.hasher = hasher or (lambda _req: 0)
        self._buckets: Dict[Hashable, _Bucket] = {}
        self._lock = threading.Lock()

    def add(self, request: T, timeout: float = 30.0) -> U:
        """Block until the fused call completes; return this request's result."""
        import time
        fut: Future = Future()
        key = self.hasher(request)
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None or bucket.thread is None:
                bucket = _Bucket(self.opts, self.batch_fn)
                self._buckets[key] = bucket
        with bucket.lock:
            if bucket.thread is None:
                bucket.started_at = time.monotonic()
                bucket.thread = threading.Thread(target=bucket.run, daemon=True)
                start = True
            else:
                start = False
            bucket.pending.append((request, fut))
            bucket.wakeup.set()
        if start:
            bucket.thread.start()
        return fut.result(timeout=timeout)
