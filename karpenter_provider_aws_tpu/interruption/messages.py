"""Interruption message schemas + parser registry.

Mirror of the reference's four EventBridge schemas and its registry keyed
on (version, source, detail-type) (reference
pkg/controllers/interruption/messages/* and parser.go:53-93):

- spot interruption warning       (2-minute notice)
- rebalance recommendation        (observational; NoAction)
- scheduled change / health event (degraded hardware etc.)
- instance state change           (stopping / terminating)

Unknown (source, detail-type) parses to a NoOp message rather than an
error, like the reference's default parser.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class MessageKind(str, enum.Enum):
    SPOT_INTERRUPTION = "SpotInterruptionKind"
    REBALANCE_RECOMMENDATION = "RebalanceRecommendationKind"
    SCHEDULED_CHANGE = "ScheduledChangeKind"
    STATE_CHANGE = "StateChangeKind"
    NOOP = "NoOpKind"
    # a body that is not a dict, or that matched a registered parser but
    # blew it up (missing/mistyped detail fields): counted and dropped —
    # distinct from NOOP (a well-formed message we deliberately ignore)
    # so the karpenter_interruption_messages_total{kind="malformed"}
    # series can alarm on a misconfigured event rule
    MALFORMED = "MalformedKind"


# metric label values per kind (karpenter_interruption_messages_total)
KIND_LABELS = {
    MessageKind.SPOT_INTERRUPTION: "spot-interruption",
    MessageKind.REBALANCE_RECOMMENDATION: "rebalance-recommendation",
    MessageKind.SCHEDULED_CHANGE: "scheduled-change",
    MessageKind.STATE_CHANGE: "state-change",
    MessageKind.NOOP: "noop",
    MessageKind.MALFORMED: "malformed",
}


@dataclass(frozen=True)
class InterruptionMessage:
    kind: MessageKind
    instance_ids: Tuple[str, ...]
    source: str = ""
    detail_type: str = ""
    detail: Dict = field(default_factory=dict)


# ---- message constructors (what the cloud's event bridge would emit) ----

def spot_interruption(instance_id: str) -> Dict:
    return {
        "version": "0", "source": "aws.ec2",
        "detail-type": "EC2 Spot Instance Interruption Warning",
        "detail": {"instance-id": instance_id, "instance-action": "terminate"},
    }


def rebalance_recommendation(instance_id: str) -> Dict:
    return {
        "version": "0", "source": "aws.ec2",
        "detail-type": "EC2 Instance Rebalance Recommendation",
        "detail": {"instance-id": instance_id},
    }


def scheduled_change(*instance_ids: str) -> Dict:
    return {
        "version": "0", "source": "aws.health",
        "detail-type": "AWS Health Event",
        "detail": {
            "service": "EC2", "eventTypeCategory": "scheduledChange",
            "affectedEntities": [{"entityValue": i} for i in instance_ids],
        },
    }


def state_change(instance_id: str, state: str = "stopping") -> Dict:
    return {
        "version": "0", "source": "aws.ec2",
        "detail-type": "EC2 Instance State-change Notification",
        "detail": {"instance-id": instance_id, "state": state},
    }


# ---- parser registry (parser.go:53-93) ----------------------------------

def _parse_spot(body: Dict) -> InterruptionMessage:
    return InterruptionMessage(
        kind=MessageKind.SPOT_INTERRUPTION,
        instance_ids=(body["detail"]["instance-id"],),
        source=body["source"], detail_type=body["detail-type"], detail=body["detail"])


def _parse_rebalance(body: Dict) -> InterruptionMessage:
    return InterruptionMessage(
        kind=MessageKind.REBALANCE_RECOMMENDATION,
        instance_ids=(body["detail"]["instance-id"],),
        source=body["source"], detail_type=body["detail-type"], detail=body["detail"])


def _parse_scheduled(body: Dict) -> InterruptionMessage:
    # only EC2 scheduled changes / account-specific health events act on nodes
    detail = body.get("detail", {})
    if detail.get("service") != "EC2":
        return InterruptionMessage(kind=MessageKind.NOOP, instance_ids=())
    ids = tuple(e.get("entityValue", "") for e in detail.get("affectedEntities", ())
                if e.get("entityValue"))
    return InterruptionMessage(
        kind=MessageKind.SCHEDULED_CHANGE, instance_ids=ids,
        source=body["source"], detail_type=body["detail-type"], detail=detail)


# stopping/terminating act; running/pending etc. are NoOps (statechange pkg)
_ACTIONABLE_STATES = {"stopping", "stopped", "shutting-down", "terminated"}


def _parse_state_change(body: Dict) -> InterruptionMessage:
    detail = body.get("detail", {})
    if detail.get("state") not in _ACTIONABLE_STATES:
        return InterruptionMessage(kind=MessageKind.NOOP, instance_ids=())
    return InterruptionMessage(
        kind=MessageKind.STATE_CHANGE,
        instance_ids=(detail["instance-id"],),
        source=body["source"], detail_type=body["detail-type"], detail=detail)


_PARSERS = {
    ("aws.ec2", "EC2 Spot Instance Interruption Warning"): _parse_spot,
    ("aws.ec2", "EC2 Instance Rebalance Recommendation"): _parse_rebalance,
    ("aws.health", "AWS Health Event"): _parse_scheduled,
    ("aws.ec2", "EC2 Instance State-change Notification"): _parse_state_change,
}


def parse_message(body: Dict) -> InterruptionMessage:
    """Never raises. A non-dict body (the isinstance check runs BEFORE any
    ``body.get`` — a list/str body used to crash the noop construction
    itself) and a registered parser blowing up both classify as MALFORMED;
    an unknown (source, detail-type) pair is a well-formed NOOP, like the
    reference's default parser."""
    if not isinstance(body, dict):
        return InterruptionMessage(kind=MessageKind.MALFORMED, instance_ids=())
    parser = _PARSERS.get((body.get("source", ""), body.get("detail-type", "")))
    if parser is None:
        return InterruptionMessage(
            kind=MessageKind.NOOP, instance_ids=(),
            source=str(body.get("source", "")),
            detail_type=str(body.get("detail-type", "")))
    try:
        return parser(body)
    except Exception:
        # a malformed body must never poison the queue: classify it so the
        # controller counts + deletes it (the reference's parsers degrade
        # to a drop the same way)
        return InterruptionMessage(
            kind=MessageKind.MALFORMED, instance_ids=(),
            source=str(body.get("source", "")),
            detail_type=str(body.get("detail-type", "")))
