"""Interruption event queue.

Mirror of the reference's SQS provider (reference pkg/providers/sqs/sqs.go:
52-72: 20 s long-poll receive, max 10 messages, delete on handled). The
fake is the default backend of the simulation environment; a real
deployment implements the same three-method surface over its message bus.
"""

from __future__ import annotations

import itertools
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

MAX_MESSAGES = 10        # sqs.go MaxNumberOfMessages
WAIT_TIME_SECONDS = 20   # sqs.go WaitTimeSeconds (long poll)


@dataclass
class QueueMessage:
    id: str
    body: Dict
    receipt_handle: str


class FakeQueue:
    """In-memory queue with SQS receive/delete semantics (at-least-once:
    received messages stay until deleted). A deque of ids carries receive
    order; deleted ids are dropped lazily off the front and compacted when
    they dominate, so a 15k-message FIFO drain (the reference's
    interruption benchmark depth, interruption_benchmark_test.go:61-75)
    is amortized O(batch) per receive and O(1) per delete — never
    quadratic on the queue itself."""

    def __init__(self, name: str = "interruption-queue"):
        self.name = name
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._messages: Dict[str, QueueMessage] = {}
        self._pending: Deque[str] = deque()

    def send(self, body: Dict) -> str:
        with self._lock:
            mid = f"m-{next(self._ids):06d}"
            self._messages[mid] = QueueMessage(id=mid, body=body, receipt_handle=mid)
            self._pending.append(mid)
            return mid

    def receive(self, max_messages: int = MAX_MESSAGES) -> List[QueueMessage]:
        """Non-blocking receive, oldest first (the sim loop polls; a live
        deployment long-polls for WAIT_TIME_SECONDS). Received messages are
        re-delivered until deleted."""
        with self._lock:
            while self._pending and self._pending[0] not in self._messages:
                self._pending.popleft()
            if len(self._pending) > 2 * len(self._messages):
                # out-of-order deletes left dead ids mid-deque: compact
                self._pending = deque(
                    m for m in self._pending if m in self._messages)
            out = []
            for mid in self._pending:
                msg = self._messages.get(mid)
                if msg is not None:
                    out.append(msg)
                    if len(out) >= max_messages:
                        break
            return out

    def delete(self, receipt_handle: str) -> None:
        with self._lock:
            self._messages.pop(receipt_handle, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._messages)

    def reset(self) -> None:
        with self._lock:
            self._messages.clear()
            self._pending.clear()
