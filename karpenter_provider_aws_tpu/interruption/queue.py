"""Interruption event queue.

Mirror of the reference's SQS provider (reference pkg/providers/sqs/sqs.go:
52-72: 20 s long-poll receive, max 10 messages, delete on handled). The
fake is the default backend of the simulation environment; a real
deployment implements the same three-method surface over its message bus.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

MAX_MESSAGES = 10        # sqs.go MaxNumberOfMessages
WAIT_TIME_SECONDS = 20   # sqs.go WaitTimeSeconds (long poll)


@dataclass
class QueueMessage:
    id: str
    body: Dict
    receipt_handle: str


class FakeQueue:
    """In-memory queue with SQS receive/delete semantics (at-least-once:
    received messages stay until deleted). Backed by one insertion-ordered
    dict so receive (oldest first) and delete are O(batch)/O(1) — a
    15k-message drain (the reference's interruption benchmark depth,
    interruption_benchmark_test.go:61-75) must not go quadratic on the
    queue itself."""

    def __init__(self, name: str = "interruption-queue"):
        self.name = name
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._messages: Dict[str, QueueMessage] = {}

    def send(self, body: Dict) -> str:
        with self._lock:
            mid = f"m-{next(self._ids):06d}"
            self._messages[mid] = QueueMessage(id=mid, body=body, receipt_handle=mid)
            return mid

    def receive(self, max_messages: int = MAX_MESSAGES) -> List[QueueMessage]:
        """Non-blocking receive (the sim loop polls; a live deployment
        long-polls for WAIT_TIME_SECONDS)."""
        with self._lock:
            return list(itertools.islice(self._messages.values(), max_messages))

    def delete(self, receipt_handle: str) -> None:
        with self._lock:
            self._messages.pop(receipt_handle, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._messages)

    def reset(self) -> None:
        with self._lock:
            self._messages.clear()
