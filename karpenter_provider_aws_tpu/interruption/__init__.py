from .messages import (
    InterruptionMessage, MessageKind, parse_message,
    rebalance_recommendation, scheduled_change, spot_interruption, state_change,
)
from .queue import FakeQueue, QueueMessage
from .controller import InterruptionController

__all__ = ["InterruptionController", "FakeQueue", "QueueMessage",
           "InterruptionMessage", "MessageKind", "parse_message",
           "spot_interruption", "rebalance_recommendation", "scheduled_change",
           "state_change"]
