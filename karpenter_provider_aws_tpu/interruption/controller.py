"""Interruption controller: queue events → ICE mask + cordon-and-drain.

Mirror of the reference controller (reference
pkg/controllers/interruption/controller.go:83-223): receive queue messages,
parse via the registry, map instance-id → NodeClaim, then

- spot interruption → mark the offering unavailable in the ICE cache
  (controller.go:194-200) AND cordon-and-drain,
- scheduled change / actionable state change → cordon-and-drain,
- rebalance recommendation → events/metrics only (NoAction,
  controller.go:291-296),

and delete the message. Draining deletes the NodeClaim, which the
termination controller turns into evict + instance terminate; the evicted
pods re-enter the next scheduling batch, whose solve already excludes the
ICE'd offering — proactive replacement before the 2-minute reclaim.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..apis import wellknown as wk
from ..apis.objects import NodeClaim
from ..cache.unavailable import UnavailableOfferings
from ..cloud.fake import parse_instance_id
from ..events import Recorder
from ..metrics import Registry, wire_core_metrics
from ..state.cluster import ClusterState
from ..utils.clock import Clock
from .messages import (InterruptionMessage, KIND_LABELS, MessageKind,
                       parse_message)
from .queue import FakeQueue

_ACTIONABLE = {MessageKind.SPOT_INTERRUPTION, MessageKind.SCHEDULED_CHANGE,
               MessageKind.STATE_CHANGE}
# kinds whose handler runs at all (rebalance publishes an event; noop and
# malformed bodies are counted + deleted without touching the cluster)
_HANDLED = _ACTIONABLE | {MessageKind.REBALANCE_RECOMMENDATION}


class InterruptionController:
    def __init__(self, queue: FakeQueue, cluster: ClusterState,
                 termination, unavailable: UnavailableOfferings,
                 recorder: Optional[Recorder] = None,
                 clock: Optional[Clock] = None,
                 metrics: Optional[Registry] = None):
        self.queue = queue
        self.cluster = cluster
        self.termination = termination
        self.unavailable = unavailable
        self.clock = clock or Clock()
        self.recorder = recorder or Recorder(self.clock)
        m = wire_core_metrics(metrics or Registry())
        self._m_received = m["interruption_received"]
        self._m_deleted = m["interruption_deleted"]
        self._m_actions = m["interruption_actions"]
        self._m_messages = m["interruption_messages"]
        # NOTE: karpenter_interruption_queue_depth is emitted by
        # Operator.emit_gauges from the headroom registry's reading
        # (introspect/headroom.py) — one source of truth for the depth,
        # never two code paths reporting different numbers
        # plain counters mirrored into stats() (the introspection
        # registry's "interruption" provider): per-kind totals plus the
        # two robustness signals a storm soak asserts on
        import threading
        self._stats_lock = threading.Lock()
        self._kind_counts: Dict[str, int] = {}
        self.handler_errors = 0
        self.poison_dropped = 0
        # per-message handler-failure counts (the SQS
        # ApproximateReceiveCount analog): a TRANSIENT handler failure
        # leaves the message in the queue for redelivery (at-least-once
        # holds — a 2-minute spot notice must not be lost to one cloud
        # hiccup), while a message that fails HANDLER_RETRY_LIMIT times
        # is a poison pill: counted and dropped so it can neither crash
        # nor wedge the loop. Entries are removed on delete, so the map
        # is bounded by live queue depth.
        self._attempts: Dict[str, int] = {}
        from ..utils.fanout import LazyPool
        self._pool = LazyPool(self.MESSAGE_WORKERS, "interruption-msg")

    HANDLER_RETRY_LIMIT = 3

    def _claims_by_instance_id(self) -> Dict[str, NodeClaim]:
        out: Dict[str, NodeClaim] = {}
        for claim in self.cluster.snapshot_claims():
            if claim.provider_id:
                out[parse_instance_id(claim.provider_id)] = claim
        return out

    # reference controller.go:104 fans message handling 10-way
    MESSAGE_WORKERS = 10

    def reconcile(self) -> int:
        """One receive→handle→delete pass (10-way parallel like
        workqueue.ParallelizeUntil, controller.go:104). Returns messages
        handled; the at-least-once contract holds — a message is deleted
        only after its handler ran, and a handler blow-up leaves it in
        the queue for redelivery. Malformed/unknown bodies and messages
        whose handler keeps failing (HANDLER_RETRY_LIMIT) are COUNTED
        and dropped: one poison pill can neither crash the controller
        loop nor wedge it via endless redelivery while a storm rages."""
        msgs = self.queue.receive()
        if not msgs:
            return 0
        claims_by_id = self._claims_by_instance_id()

        def one(qm) -> int:
            msg = parse_message(qm.body)   # never raises (messages.py)
            # the legacy received counter keeps true receive semantics
            # (one inc per delivery, redeliveries included)
            self._m_received.inc(message_type=msg.kind.value)
            if msg.kind in _HANDLED:
                try:
                    self._handle(msg, claims_by_id)
                except Exception:
                    with self._stats_lock:
                        self.handler_errors += 1
                        attempts = self._attempts.get(qm.id, 0) + 1
                        self._attempts[qm.id] = attempts
                    if attempts < self.HANDLER_RETRY_LIMIT:
                        # transient until proven otherwise: leave the
                        # message for redelivery (at-least-once)
                        return 0
                    with self._stats_lock:
                        self.poison_dropped += 1
            # the per-kind processed counters count on DISPOSAL (exactly
            # once per message), never per delivery — a transiently
            # retried message must not pad them (the soak's >100
            # interruptions-handled evidence sums these)
            label = KIND_LABELS[msg.kind]
            self._m_messages.inc(kind=label)
            with self._stats_lock:
                self._kind_counts[label] = \
                    self._kind_counts.get(label, 0) + 1
                self._attempts.pop(qm.id, None)
            self.queue.delete(qm.receipt_handle)
            self._m_deleted.inc()
            return 1

        n = sum(self._pool.run(msgs, one))
        return n

    def stats(self) -> Dict:
        """Introspection provider (docs/reference/introspection.md): queue
        depth plus per-kind message totals and the robustness counters."""
        with self._stats_lock:
            out: Dict = {f"received_{k.replace('-', '_')}": v
                         for k, v in self._kind_counts.items()}
            out["handler_errors"] = self.handler_errors
            out["poison_dropped"] = self.poison_dropped
        out["queue_depth"] = len(self.queue)
        return out

    def headroom_probe(self) -> Dict[str, float]:
        """Interruption backlog (introspect/headroom.py): undeleted
        messages. Unbounded (a real SQS queue buffers days), so the
        forecast rides the fill rate; drops = the pre-existing poison
        counter (the only way this controller ever discards)."""
        with self._stats_lock:
            poison = self.poison_dropped
        return {"depth": float(len(self.queue)), "capacity": 0.0,
                "drops": float(poison)}

    def _handle(self, msg: InterruptionMessage, claims_by_id: Dict[str, NodeClaim]) -> None:
        for iid in msg.instance_ids:
            claim = claims_by_id.get(iid)
            if claim is None:
                # event for an instance we don't manage — ignore (the
                # reference logs and drops, controller.go:249-289)
                continue
            if msg.kind == MessageKind.SPOT_INTERRUPTION:
                # remember the reclaimed pool so the replacement solve
                # avoids it (controller.go:194-200) — only when the claim
                # really is spot: a mislabeled event for an on-demand node
                # must not poison the spot pool for that type/zone
                if (claim.capacity_type == wk.CAPACITY_TYPE_SPOT
                        and claim.instance_type and claim.zone):
                    self.unavailable.mark_unavailable(
                        msg.kind.value, wk.CAPACITY_TYPE_SPOT,
                        claim.instance_type, claim.zone)
            self.recorder.publish(
                "Warning", msg.kind.value, "NodeClaim", claim.name,
                f"interruption event for instance {iid}")
            if msg.kind in _ACTIONABLE:
                self.termination.delete_claim(claim.name)
                self._m_actions.inc(action="CordonAndDrain")
