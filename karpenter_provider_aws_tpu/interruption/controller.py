"""Interruption controller: queue events → ICE mask + cordon-and-drain.

Mirror of the reference controller (reference
pkg/controllers/interruption/controller.go:83-223): receive queue messages,
parse via the registry, map instance-id → NodeClaim, then

- spot interruption → mark the offering unavailable in the ICE cache
  (controller.go:194-200) AND cordon-and-drain,
- scheduled change / actionable state change → cordon-and-drain,
- rebalance recommendation → events/metrics only (NoAction,
  controller.go:291-296),

and delete the message. Draining deletes the NodeClaim, which the
termination controller turns into evict + instance terminate; the evicted
pods re-enter the next scheduling batch, whose solve already excludes the
ICE'd offering — proactive replacement before the 2-minute reclaim.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..apis import wellknown as wk
from ..apis.objects import NodeClaim
from ..cache.unavailable import UnavailableOfferings
from ..cloud.fake import parse_instance_id
from ..events import Recorder
from ..metrics import Registry, wire_core_metrics
from ..state.cluster import ClusterState
from ..utils.clock import Clock
from .messages import InterruptionMessage, MessageKind, parse_message
from .queue import FakeQueue

_ACTIONABLE = {MessageKind.SPOT_INTERRUPTION, MessageKind.SCHEDULED_CHANGE,
               MessageKind.STATE_CHANGE}


class InterruptionController:
    def __init__(self, queue: FakeQueue, cluster: ClusterState,
                 termination, unavailable: UnavailableOfferings,
                 recorder: Optional[Recorder] = None,
                 clock: Optional[Clock] = None,
                 metrics: Optional[Registry] = None):
        self.queue = queue
        self.cluster = cluster
        self.termination = termination
        self.unavailable = unavailable
        self.clock = clock or Clock()
        self.recorder = recorder or Recorder(self.clock)
        m = wire_core_metrics(metrics or Registry())
        self._m_received = m["interruption_received"]
        self._m_deleted = m["interruption_deleted"]
        self._m_actions = m["interruption_actions"]
        from ..utils.fanout import LazyPool
        self._pool = LazyPool(self.MESSAGE_WORKERS, "interruption-msg")

    def _claims_by_instance_id(self) -> Dict[str, NodeClaim]:
        out: Dict[str, NodeClaim] = {}
        for claim in self.cluster.snapshot_claims():
            if claim.provider_id:
                out[parse_instance_id(claim.provider_id)] = claim
        return out

    # reference controller.go:104 fans message handling 10-way
    MESSAGE_WORKERS = 10

    def reconcile(self) -> int:
        """One receive→handle→delete pass (10-way parallel like
        workqueue.ParallelizeUntil, controller.go:104). Returns messages
        handled; the at-least-once contract holds — a message is deleted
        only after its handler ran."""
        msgs = self.queue.receive()
        if not msgs:
            return 0
        claims_by_id = self._claims_by_instance_id()

        def one(qm) -> int:
            msg = parse_message(qm.body)
            self._m_received.inc(message_type=msg.kind.value)
            if msg.kind != MessageKind.NOOP:
                self._handle(msg, claims_by_id)
            self.queue.delete(qm.receipt_handle)
            self._m_deleted.inc()
            return 1

        return sum(self._pool.run(msgs, one))

    def _handle(self, msg: InterruptionMessage, claims_by_id: Dict[str, NodeClaim]) -> None:
        for iid in msg.instance_ids:
            claim = claims_by_id.get(iid)
            if claim is None:
                # event for an instance we don't manage — ignore (the
                # reference logs and drops, controller.go:249-289)
                continue
            if msg.kind == MessageKind.SPOT_INTERRUPTION:
                # remember the reclaimed pool so the replacement solve
                # avoids it (controller.go:194-200) — only when the claim
                # really is spot: a mislabeled event for an on-demand node
                # must not poison the spot pool for that type/zone
                if (claim.capacity_type == wk.CAPACITY_TYPE_SPOT
                        and claim.instance_type and claim.zone):
                    self.unavailable.mark_unavailable(
                        msg.kind.value, wk.CAPACITY_TYPE_SPOT,
                        claim.instance_type, claim.zone)
            self.recorder.publish(
                "Warning", msg.kind.value, "NodeClaim", claim.name,
                f"interruption event for instance {iid}")
            if msg.kind in _ACTIONABLE:
                self.termination.delete_claim(claim.name)
                self._m_actions.inc(action="CordonAndDrain")
