"""Kubernetes version provider.

Mirror of reference pkg/providers/version/version.go: control-plane
version discovery (used to parameterize the AMI SSM paths), cached.
"""

from __future__ import annotations

from typing import Optional

from ..cache.ttl import TTLCache
from ..cloud.fake import FakeCloud
from ..utils.clock import Clock

VERSION_TTL = 900.0


class VersionProvider:
    def __init__(self, cloud: FakeCloud, clock: Optional[Clock] = None):
        self.cloud = cloud
        self._cache = TTLCache(VERSION_TTL, clock)

    def get(self) -> str:
        return self._cache.get_or_compute("version",
                                          lambda: self.cloud.network.k8s_version)

    def reset(self) -> None:
        self._cache.flush()
