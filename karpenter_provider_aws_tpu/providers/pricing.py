"""Pricing provider.

Mirror of reference pkg/providers/pricing/pricing.go: on-demand prices
(parallel standard+metal fetch, :150-217), per-zone spot prices
(:348-391), and compiled-in static fallback for air-gapped operation
(:43, :411-423 — here the catalog's generated prices ARE the static
table). Dynamic updates overlay the static base and rebuild the lattice's
price tensor so the device solver prices with live data.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

from ..lattice import catalog as cat
from ..lattice.tensors import Lattice
from ..utils.clock import Clock
from ..utils.logging import ChangeMonitor, get_logger

PRICING_REFRESH_SECONDS = 12 * 3600.0  # 12h loop (pricing controller.go:56)


class PricingProvider:
    def __init__(self, lattice: Lattice, clock: Optional[Clock] = None,
                 isolated_vpc: bool = False):
        self.lattice = lattice
        self.clock = clock or Clock()
        # isolated VPC: the Pricing API has no VPC endpoint, so live OD
        # lookups are skipped and static prices serve (options.go:53)
        self.isolated_vpc = isolated_vpc
        self._lock = threading.Lock()
        self._log = get_logger("pricing")
        # log-on-delta (reference instancetype.go:150-152 idiom): a 12h
        # refresh loop re-asserting identical prices stays quiet
        self._monitor = ChangeMonitor(self.clock)
        # static fallback = the catalog prices compiled into the lattice
        self._static = lattice.price.copy()
        self._od_overrides: Dict[str, float] = {}                  # type -> $/hr
        self._spot_overrides: Dict[Tuple[str, str], float] = {}    # (type, zone) -> $/hr
        self.last_update: Optional[float] = None

    def on_demand_price(self, instance_type: str) -> float:
        with self._lock:
            if instance_type in self._od_overrides:
                return self._od_overrides[instance_type]
        lat = self.lattice
        ti = lat.name_to_idx.get(instance_type)
        if ti is None:
            return float("inf")
        ci = lat.capacity_types.index("on-demand")
        return float(np.min(self._static[ti, :, ci]))

    def spot_price(self, instance_type: str, zone: str) -> float:
        with self._lock:
            if (instance_type, zone) in self._spot_overrides:
                return self._spot_overrides[(instance_type, zone)]
        lat = self.lattice
        ti = lat.name_to_idx.get(instance_type)
        if ti is None or zone not in lat.zones:
            return float("inf")
        zi = lat.zones.index(zone)
        ci = lat.capacity_types.index("spot")
        return float(self._static[ti, zi, ci])

    def update_on_demand_pricing(self, prices: Dict[str, float]) -> int:
        """Overlay live OD prices (the 12h Pricing-API fetch)."""
        if self.isolated_vpc:
            # the Pricing API has no VPC endpoint: static prices serve
            # (reference pricing.go:150-163)
            if self._monitor.has_changed("isolated-od", True):
                self._log.debug("isolated VPC: on-demand pricing not updated")
            return 0
        with self._lock:
            self._od_overrides.update(prices)
            self.last_update = self.clock.now()
            # gate on the RESULTING overlay state, not the call payload
            # (partial re-sends of effective prices stay quiet) — decided
            # under the lock so concurrent updates can't log stale state
            changed = self._monitor.has_changed(
                "od-prices", tuple(sorted(self._od_overrides.items())))
            n = len(self._od_overrides)
        self._rebuild()
        if changed:
            self._log.info("updated on-demand pricing", entries=n)
        return len(prices)

    def update_spot_pricing(self, prices: Dict[Tuple[str, str], float]) -> int:
        """Overlay live per-zone spot prices (DescribeSpotPriceHistory —
        an EC2 API with a VPC endpoint, so isolated VPCs still get it,
        reference pricing.go:348-391 UpdateSpotPricing has no gate)."""
        with self._lock:
            self._spot_overrides.update(prices)
            self.last_update = self.clock.now()
            changed = self._monitor.has_changed(
                "spot-prices", tuple(sorted(self._spot_overrides.items())))
            n = len(self._spot_overrides)
        self._rebuild()
        if changed:
            self._log.info("updated spot pricing", entries=n)
        return len(prices)

    def _rebuild(self) -> None:
        """Write the overlaid prices back into the lattice tensor in place,
        so every on-device solve (which holds a reference to lattice.price)
        prices with current data; unavailable offerings stay +inf."""
        lat = self.lattice
        with self._lock:
            price = self._static.copy()
            if "on-demand" in lat.capacity_types:
                ci = lat.capacity_types.index("on-demand")
                # the Pricing API reports ONE regional OD price; zonal
                # premiums (local zones) scale it per zone, same as the
                # static lattice build (catalog.od_price)
                zone_scale = np.array(
                    [cat.od_zone_multiplier(z) for z in lat.zones],
                    np.float32)
                for t, p in self._od_overrides.items():
                    ti = lat.name_to_idx.get(t)
                    if ti is not None:
                        price[ti, :, ci] = np.where(
                            lat.available[ti, :, ci], p * zone_scale, np.inf)
            if "spot" in lat.capacity_types:
                ci = lat.capacity_types.index("spot")
                for (t, z), p in self._spot_overrides.items():
                    ti = lat.name_to_idx.get(t)
                    if ti is not None and z in lat.zones:
                        zi = lat.zones.index(z)
                        if lat.available[ti, zi, ci]:
                            price[ti, zi, ci] = p
            lat.price[...] = price
            lat.price_version += 1

    def liveness_ok(self) -> bool:
        return True

    def reset(self) -> None:
        with self._lock:
            self._od_overrides.clear()
            self._spot_overrides.clear()
            self.last_update = None
            # re-arm the log-on-delta gates (under the lock — an in-flight
            # update must not race the swap): post-wipe re-applications
            # are real changes and must leave an audit line
            self._monitor = ChangeMonitor(self.clock)
        self.lattice.price[...] = self._static
        self.lattice.price_version += 1


class PricingController:
    """Singleton 12h refresh loop (reference
    pkg/controllers/pricing/controller.go:42-57). The fake market has no
    live feed, so a refresh re-applies overlays; a real backend plugs its
    fetchers into the two update hooks."""

    def __init__(self, provider: PricingProvider, clock: Optional[Clock] = None):
        self.provider = provider
        self.clock = clock or Clock()
        self._last = 0.0

    def reconcile(self) -> bool:
        now = self.clock.now()
        if now - self._last < PRICING_REFRESH_SECONDS:
            return False
        self._last = now
        self.provider._rebuild()
        return True
