"""Launch template provider.

Mirror of reference pkg/providers/launchtemplate/launchtemplate.go:
ensure-or-create launch templates named by content hash (:149-155),
materialized from the AMI family's resolved launch parameters + security
groups + instance profile (:241-318), a cache whose eviction deletes the
stale cloud template (delete-on-evict GC, :372-389), and startup cache
hydration (:355-370).
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional

from ..apis.objects import NodeClass
from ..cache.ttl import TTLCache
from ..cloud.fake import FakeCloud
from ..cloud.network import LaunchTemplate
from ..errors import AlreadyExistsError, NotFoundError
from ..utils.clock import Clock
from .amifamily import AMIProvider, LaunchParameters
from .instanceprofile import InstanceProfileProvider
from .securitygroup import SecurityGroupProvider

LAUNCH_TEMPLATE_TTL = 300.0
LT_PREFIX = "karpenter.sim"


class LaunchTemplateProvider:
    def __init__(self, cloud: FakeCloud,
                 security_groups: SecurityGroupProvider,
                 instance_profiles: InstanceProfileProvider,
                 amis: AMIProvider,
                 clock: Optional[Clock] = None,
                 cluster_name: str = "sim"):
        self.cloud = cloud
        self.security_groups = security_groups
        self.instance_profiles = instance_profiles
        self.amis = amis
        self.cluster_name = cluster_name
        # evicting a template from the cache deletes the cloud object — the
        # reference's stale-LT GC (launchtemplate.go:372-389)
        self._cache = TTLCache(LAUNCH_TEMPLATE_TTL, clock, on_evict=self._evict)
        self._hydrated = False

    def _evict(self, name: str, _lt) -> None:
        try:
            self.cloud.network.delete_launch_template(name)
        except NotFoundError:
            pass

    def _lt_name(self, content_hash: str) -> str:
        return f"{LT_PREFIX}/{content_hash}"

    def hydrate(self) -> int:
        """Prime the cache from cloud state on startup (after leader
        election in the reference, :100-108, :355-370)."""
        if self._hydrated:
            return 0
        n = 0
        for lt in self.cloud.network.describe_launch_templates(
                tags={f"karpenter.sim/cluster": self.cluster_name}):
            self._cache.set(lt.name, lt)
            n += 1
        self._hydrated = True
        return n

    def ensure_all(self, node_class: NodeClass, k8s_version: str,
                   cluster_dns: Optional[str] = None) -> List[LaunchTemplate]:
        """One launch template per resolved (AMI, arch) launch parameter set
        (EnsureAll, :112-136). ``cluster_dns`` parameterizes the userdata
        (it feeds the content hash, so a pool-level kubelet ClusterDNS
        override gets its own template)."""
        self.hydrate()
        sgs = tuple(g.id for g in self.security_groups.list(node_class))
        profile = self.instance_profiles.create(node_class)
        out: List[LaunchTemplate] = []
        for params in self.amis.resolve_launch_parameters(
                node_class, k8s_version, cluster_dns=cluster_dns):
            out.append(self._ensure_one(node_class, params, sgs, profile))
        return out

    def _ensure_one(self, node_class: NodeClass, params: LaunchParameters,
                    sg_ids, profile: str) -> LaunchTemplate:
        content = "|".join([
            params.ami.id, params.user_data, ",".join(sg_ids), profile,
            repr(sorted(node_class.tags.items())),
            repr(vars(node_class.metadata_options)),
            repr(node_class.block_device_mappings),
        ])
        h = hashlib.sha256(content.encode()).hexdigest()[:16]
        name = self._lt_name(h)
        cached = self._cache.get(name)
        if cached is not None:
            # refresh expiry on use: an actively-referenced template must
            # never be evicted (and thereby GC'd from the cloud) mid-use
            self._cache.set(name, cached)
            return cached
        existing = self.cloud.network.describe_launch_templates(names=[name])
        if existing:
            self._cache.set(name, existing[0])
            return existing[0]
        lt = LaunchTemplate(
            id="", name=name, image_id=params.ami.id, user_data=params.user_data,
            security_group_ids=tuple(sg_ids), instance_profile=profile,
            tags={"karpenter.sim/cluster": self.cluster_name,
                  "karpenter.sim/nodeclass": node_class.name},
            metadata_options=dict(vars(node_class.metadata_options)),
            block_device_mappings=tuple(map(repr, node_class.block_device_mappings)))
        try:
            lt = self.cloud.network.create_launch_template(lt)
        except AlreadyExistsError:
            lt = self.cloud.network.describe_launch_templates(names=[name])[0]
        self._cache.set(name, lt)
        return lt

    def delete_all(self, node_class: NodeClass) -> int:
        """Delete the NodeClass's templates (nodeclass finalizer flow)."""
        n = 0
        for lt in self.cloud.network.describe_launch_templates(
                tags={"karpenter.sim/nodeclass": node_class.name}):
            try:
                self.cloud.network.delete_launch_template(lt.name)
                n += 1
            except NotFoundError:
                pass
            self._cache.delete(lt.name)
        return n

    def cleanup(self) -> int:
        """Periodic cache sweep; evictions GC stale cloud templates."""
        return self._cache.cleanup()

    def reset(self) -> None:
        self._cache.flush()
        self._hydrated = False
