"""Subnet provider.

Mirror of reference pkg/providers/subnet/subnet.go: selector-term discovery
(:58-94), zonal subnet choice by most free IPs with in-flight IP
accounting (:109-145, :148-204). The in-flight bookkeeping matters: many
launches in one batch must not all pick the same almost-full subnet.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ..apis.objects import NodeClass, NodeClassSelectorTerm
from ..cache.ttl import TTLCache
from ..cloud.fake import FakeCloud
from ..cloud.network import Subnet
from ..utils.clock import Clock

SUBNET_TTL = 60.0  # default 1-min cache (reference cache.go:26)


class SubnetProvider:
    def __init__(self, cloud: FakeCloud, clock: Optional[Clock] = None,
                 cluster_name: str = "sim"):
        self.cloud = cloud
        self.cluster_name = cluster_name
        self._cache = TTLCache(SUBNET_TTL, clock)
        self._clock = clock or Clock()
        # in-flight IP bookings decay after the subnet-cache window: by then
        # the describe refresh reflects the launched instances' real usage
        # (reference re-baselines the same way, subnet.go:148-204)
        self._inflight: Dict[str, List[Tuple[float, int]]] = {}
        self._lock = threading.Lock()

    def list(self, node_class: NodeClass) -> List[Subnet]:
        """Resolve the NodeClass's subnet selector terms (OR across terms)."""
        terms = node_class.subnet_selector_terms or [
            NodeClassSelectorTerm(tags=((f"kubernetes.io/cluster/{self.cluster_name}", "*"),))]
        key = repr(sorted((t.id, t.name, tuple(sorted(t.tags))) for t in terms))

        def fetch():
            found: Dict[str, Subnet] = {}
            for t in terms:
                if t.id:
                    for s in self.cloud.network.describe_subnets(ids=[t.id]):
                        found[s.id] = s
                else:
                    for s in self.cloud.network.describe_subnets(tags=dict(t.tags)):
                        found[s.id] = s
            return sorted(found.values(), key=lambda s: s.id)

        return self._cache.get_or_compute(key, fetch)

    def _inflight_for(self, subnet_id: str) -> int:
        now = self._clock.now()
        entries = self._inflight.get(subnet_id)
        if not entries:
            return 0
        live = [(exp, n) for exp, n in entries if exp > now]
        self._inflight[subnet_id] = live
        return sum(n for _, n in live)

    def zonal_subnets_for_launch(self, node_class: NodeClass) -> Dict[str, Subnet]:
        """zone -> chosen subnet (max free IPs minus in-flight, subnet.go:109-145)."""
        with self._lock:
            best: Dict[str, Subnet] = {}
            for s in self.list(node_class):
                free = s.available_ips - self._inflight_for(s.id)
                cur = best.get(s.zone)
                cur_free = (cur.available_ips - self._inflight_for(cur.id)) if cur else -1
                if free > cur_free:
                    best[s.zone] = s
            return best

    def update_inflight_ips(self, subnet_id: str, ips: int = 1) -> None:
        """Book IPs consumed by a just-issued launch (subnet.go:148-204);
        bookings expire with the describe-cache window, when the refreshed
        subnet data reflects them for real."""
        with self._lock:
            self._inflight.setdefault(subnet_id, []).append(
                (self._clock.now() + SUBNET_TTL, ips))

    def reset(self) -> None:
        with self._lock:
            self._inflight.clear()
        self._cache.flush()
