"""Instance profile provider.

Mirror of reference pkg/providers/instanceprofile/instanceprofile.go:
create/reconcile/delete an IAM instance profile per NodeClass role
(:50-128), with the deterministic name = hash(region + nodeclass)
(:130-134) and a long-TTL cache (15 min, reference cache.go:33).
"""

from __future__ import annotations

import hashlib
from typing import Optional

from ..apis.objects import NodeClass
from ..cache.ttl import TTLCache
from ..cloud.fake import FakeCloud
from ..errors import AlreadyExistsError, NotFoundError
from ..utils.clock import Clock

INSTANCE_PROFILE_TTL = 900.0
REGION = "us-west-2"


def profile_name(node_class_name: str, region: str = REGION) -> str:
    digest = hashlib.sha256(f"{region}/{node_class_name}".encode()).hexdigest()[:20]
    return f"karpenter_{digest}"


class InstanceProfileProvider:
    def __init__(self, cloud: FakeCloud, clock: Optional[Clock] = None):
        self.cloud = cloud
        self._cache = TTLCache(INSTANCE_PROFILE_TTL, clock)

    def create(self, node_class: NodeClass) -> str:
        """Ensure the profile exists with the NodeClass's role; returns its
        name. Explicit spec.instance_profile wins over role-derived
        (ec2nodeclass spec precedence)."""
        if node_class.instance_profile:
            return node_class.instance_profile
        if not node_class.role:
            raise ValueError(f"nodeclass {node_class.name}: role or instance_profile required")
        name = profile_name(node_class.name)
        if name in self._cache:
            return name

        try:
            existing = self.cloud.network.get_instance_profile(name)
            if existing.role != node_class.role:
                # role changed: recreate (reference reconciles the role)
                self.cloud.network.delete_instance_profile(name)
                raise NotFoundError(name)
        except NotFoundError:
            try:
                self.cloud.network.create_instance_profile(name, node_class.role)
            except AlreadyExistsError:
                pass
        self._cache.set(name, True)
        return name

    def delete(self, node_class: NodeClass) -> None:
        if node_class.instance_profile:
            return  # user-managed profile: never delete
        name = profile_name(node_class.name)
        try:
            self.cloud.network.delete_instance_profile(name)
        except NotFoundError:
            pass
        self._cache.delete(name)

    def reset(self) -> None:
        self._cache.flush()
