"""Security group provider.

Mirror of reference pkg/providers/securitygroup/securitygroup.go:54-94:
tag/id/name selector-term discovery with a hash-keyed TTL cache.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..apis.objects import NodeClass, NodeClassSelectorTerm
from ..cache.ttl import TTLCache
from ..cloud.fake import FakeCloud
from ..cloud.network import SecurityGroup
from ..utils.clock import Clock

SECURITY_GROUP_TTL = 60.0


class SecurityGroupProvider:
    def __init__(self, cloud: FakeCloud, clock: Optional[Clock] = None,
                 cluster_name: str = "sim"):
        self.cloud = cloud
        self.cluster_name = cluster_name
        self._cache = TTLCache(SECURITY_GROUP_TTL, clock)

    def list(self, node_class: NodeClass) -> List[SecurityGroup]:
        terms = node_class.security_group_selector_terms or [
            NodeClassSelectorTerm(tags=((f"kubernetes.io/cluster/{self.cluster_name}", "*"),))]
        key = repr(sorted((t.id, t.name, tuple(sorted(t.tags))) for t in terms))

        def fetch():
            found: Dict[str, SecurityGroup] = {}
            for t in terms:
                if t.id:
                    for g in self.cloud.network.describe_security_groups(ids=[t.id]):
                        found[g.id] = g
                elif t.name:
                    for g in self.cloud.network.describe_security_groups(names=[t.name]):
                        found[g.id] = g
                else:
                    for g in self.cloud.network.describe_security_groups(tags=dict(t.tags)):
                        found[g.id] = g
            return sorted(found.values(), key=lambda g: g.id)

        return self._cache.get_or_compute(key, fetch)

    def reset(self) -> None:
        self._cache.flush()
