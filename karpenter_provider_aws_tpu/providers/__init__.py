from .subnet import SubnetProvider
from .securitygroup import SecurityGroupProvider
from .instanceprofile import InstanceProfileProvider
from .amifamily import AMI_FAMILIES, AMIProvider, resolve_ami_family, storage_config
from .launchtemplate import LaunchTemplateProvider
from .pricing import PricingProvider
from .version import VersionProvider

__all__ = ["SubnetProvider", "SecurityGroupProvider", "InstanceProfileProvider",
           "AMIProvider", "AMI_FAMILIES", "resolve_ami_family",
           "LaunchTemplateProvider", "PricingProvider", "VersionProvider"]
