"""AMI family strategies + AMI resolver.

Mirror of reference pkg/providers/amifamily: the strategy pattern over
AMI families (resolver.go:167-184 — AL2, AL2023, Bottlerocket, Ubuntu,
Windows, Custom), SSM-parameter default-AMI discovery (ami.go:136-181),
AMI→architecture compatibility mapping (ami.go:91-102), and per-AMI
launch-parameter resolution (resolver.go:122-165). User data rendering is
family-specific: shell/MIME for AL2, nodeadm YAML-ish for AL2023, TOML for
Bottlerocket — enough structure for drift hashing and tests; a real
bootstrap would extend the same hooks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..apis.objects import NodeClass
from ..cache.ttl import TTLCache
from ..cloud.fake import FakeCloud
from ..cloud.network import Image
from ..errors import NotFoundError
from ..utils.clock import Clock

AMI_TTL = 300.0  # 5 min


@dataclass
class ResolvedAMI:
    id: str
    name: str
    arch: str            # amd64 | arm64


@dataclass
class LaunchParameters:
    """Per-(AMI, arch, userdata) launch template parameterization
    (resolver.go:122-165 groups by {AMI, maxPods, EFA}); userdata varies
    with the kubelet cluster-DNS, so pools with different kubelet blocks
    resolve distinct parameter sets (and distinct launch templates via
    the content hash)."""

    ami: ResolvedAMI
    user_data: str
    arch: str


class AMIFamily:
    name = "Custom"
    _arch_alias = {"amd64": "x86_64", "arm64": "arm64"}
    # root/ephemeral device the family's AMIs mount (reference
    # amifamily/<family>.go EphemeralBlockDevice); None = unknown (Custom)
    ephemeral_block_device: Optional[str] = None

    def default_ami_ssm_parameters(self, k8s_version: str) -> Dict[str, str]:
        """arch -> SSM parameter path for the family's default AMI."""
        return {}

    def user_data(self, node_class: NodeClass, cluster_name: str,
                  cluster_endpoint: str,
                  cluster_dns: Optional[str] = None) -> str:
        # Custom AMIs own their full userdata, incl. DNS wiring
        return node_class.user_data or ""


class AL2(AMIFamily):
    name = "AL2"
    ephemeral_block_device = "/dev/xvda"

    def default_ami_ssm_parameters(self, k8s_version):
        base = "/aws/service/eks/optimized-ami/{v}/amazon-linux-2{suffix}/recommended/image_id"
        return {
            "amd64": base.format(v=k8s_version, suffix=""),
            "arm64": base.format(v=k8s_version, suffix="-arm64"),
        }

    def user_data(self, node_class, cluster_name, cluster_endpoint,
                  cluster_dns=None):
        custom = node_class.user_data or ""
        dns = f" --dns-cluster-ip '{cluster_dns}'" if cluster_dns else ""
        return (
            "MIME-Version: 1.0\n"
            f"{custom}\n"
            f"/etc/eks/bootstrap.sh {cluster_name} --apiserver-endpoint {cluster_endpoint}{dns}\n"
        )


class AL2023(AMIFamily):
    name = "AL2023"
    ephemeral_block_device = "/dev/xvda"

    def default_ami_ssm_parameters(self, k8s_version):
        base = "/aws/service/eks/optimized-ami/{v}/amazon-linux-2023/{arch}/standard/recommended/image_id"
        return {a: base.format(v=k8s_version, arch=self._arch_alias[a])
                for a in ("amd64", "arm64")}

    def user_data(self, node_class, cluster_name, cluster_endpoint,
                  cluster_dns=None):
        custom = node_class.user_data or ""
        dns = f"  clusterDNS: {cluster_dns}\n" if cluster_dns else ""
        return (
            "apiVersion: node.eks.aws/v1alpha1\nkind: NodeConfig\n"
            f"cluster:\n  name: {cluster_name}\n  apiServerEndpoint: {cluster_endpoint}\n"
            f"{dns}{custom}\n"
        )


class Bottlerocket(AMIFamily):
    name = "Bottlerocket"
    ephemeral_block_device = "/dev/xvdb"

    def default_ami_ssm_parameters(self, k8s_version):
        base = "/aws/service/bottlerocket/aws-k8s-{v}/{arch}/latest/image_id"
        return {a: base.format(v=k8s_version, arch=self._arch_alias[a])
                for a in ("amd64", "arm64")}

    def user_data(self, node_class, cluster_name, cluster_endpoint,
                  cluster_dns=None):
        custom = node_class.user_data or ""
        dns = f'cluster-dns-ip = "{cluster_dns}"\n' if cluster_dns else ""
        return (
            "[settings.kubernetes]\n"
            f'cluster-name = "{cluster_name}"\n'
            f'api-server = "{cluster_endpoint}"\n'
            f"{dns}{custom}\n"
        )


class Ubuntu(AMIFamily):
    name = "Ubuntu"
    ephemeral_block_device = "/dev/sda1"

    def default_ami_ssm_parameters(self, k8s_version):
        base = "/aws/service/canonical/ubuntu/eks/22.04/{v}/stable/current/{arch}/hvm/ebs-gp2/ami-id"
        return {a: base.format(v=k8s_version, arch=self._arch_alias[a])
                for a in ("amd64", "arm64")}

    def user_data(self, node_class, cluster_name, cluster_endpoint,
                  cluster_dns=None):
        return AL2().user_data(node_class, cluster_name, cluster_endpoint,
                               cluster_dns=cluster_dns)


class Windows(AMIFamily):
    name = "Windows"
    ephemeral_block_device = "/dev/sda1"

    def default_ami_ssm_parameters(self, k8s_version):
        return {"amd64":
                f"/aws/service/ami-windows-latest/Windows_Server-2022-English-Core-EKS_Optimized-{k8s_version}/image_id"}

    def user_data(self, node_class, cluster_name, cluster_endpoint,
                  cluster_dns=None):
        custom = node_class.user_data or ""
        dns = f" -DNSClusterIP '{cluster_dns}'" if cluster_dns else ""
        return (f"<powershell>\n{custom}\n"
                f"[EKS bootstrap {cluster_name}{dns}]\n</powershell>\n")


class Custom(AMIFamily):
    """No defaults: AMI selector terms are required; user data passes
    through verbatim (amifamily/custom.go)."""
    name = "Custom"


AMI_FAMILIES: Dict[str, AMIFamily] = {
    f.name: f for f in (AL2(), AL2023(), Bottlerocket(), Ubuntu(), Windows(), Custom())
}


def resolve_ami_family(name: str) -> AMIFamily:
    fam = AMI_FAMILIES.get(name)
    if fam is None:
        raise ValueError(f"unknown AMI family {name!r}; known: {sorted(AMI_FAMILIES)}")
    return fam


def storage_config(node_class: NodeClass) -> "StorageConfig":
    """NodeClass storage knobs + its AMI family's root device → the
    lattice's per-type ephemeral-storage resolution inputs (reference
    types.go:210-240 ephemeralStorage)."""
    from ..lattice.tensors import StorageConfig
    fam = resolve_ami_family(node_class.ami_family)
    return StorageConfig(
        instance_store_policy=node_class.instance_store_policy,
        block_device_mappings=tuple(node_class.block_device_mappings),
        ephemeral_block_device=fam.ephemeral_block_device,
        custom_ami_family=fam.name == "Custom")


class AMIProvider:
    def __init__(self, cloud: FakeCloud, clock: Optional[Clock] = None,
                 cluster_name: str = "sim",
                 cluster_endpoint: Optional[str] = None):
        """``cluster_endpoint`` overrides network discovery for node
        bootstrap userdata (the reference's CLUSTER_ENDPOINT option,
        operator.go:119-124; None = discover)."""
        self.cloud = cloud
        self.cluster_name = cluster_name
        self.cluster_endpoint = cluster_endpoint
        self._cache = TTLCache(AMI_TTL, clock)

    def list(self, node_class: NodeClass, k8s_version: str) -> List[ResolvedAMI]:
        """Resolve AMIs: explicit selector terms win; otherwise the family's
        SSM default parameters (ami.go:136-181). Newest per arch wins
        (ami.go:91-102 sorts by creation date)."""
        key = f"{node_class.name}:{k8s_version}:{node_class.ami_family}:{node_class.ami_selector_terms!r}"

        def fetch():
            images: Dict[str, Image] = {}
            if node_class.ami_selector_terms:
                for t in node_class.ami_selector_terms:
                    if t.id:
                        for im in self.cloud.network.describe_images(ids=[t.id]):
                            images[im.id] = im
                    elif t.name:
                        for im in self.cloud.network.describe_images(names=[t.name]):
                            images[im.id] = im
                    else:
                        for im in self.cloud.network.describe_images(tags=dict(t.tags)):
                            images[im.id] = im
            else:
                fam = resolve_ami_family(node_class.ami_family)
                for arch, param in fam.default_ami_ssm_parameters(k8s_version).items():
                    try:
                        ami_id = self.cloud.network.get_parameter(param)
                    except NotFoundError:
                        continue
                    for im in self.cloud.network.describe_images(ids=[ami_id]):
                        images[im.id] = im
            best_per_arch: Dict[str, Image] = {}
            for im in images.values():
                if im.deprecated:
                    continue
                cur = best_per_arch.get(im.arch)
                if cur is None or im.creation_date > cur.creation_date:
                    best_per_arch[im.arch] = im
            return [ResolvedAMI(id=im.id, name=im.name, arch=im.arch)
                    for im in sorted(best_per_arch.values(), key=lambda i: i.arch)]

        return self._cache.get_or_compute(key, fetch)

    def resolve_launch_parameters(self, node_class: NodeClass,
                                  k8s_version: str,
                                  cluster_dns: Optional[str] = None) -> List[LaunchParameters]:
        """One launch parameter set per resolved AMI (resolver.go:122-165)."""
        fam = resolve_ami_family(node_class.ami_family)
        endpoint = self.cluster_endpoint or self.cloud.network.cluster_endpoint
        return [LaunchParameters(
                    ami=ami, arch=ami.arch,
                    user_data=fam.user_data(node_class, self.cluster_name,
                                            endpoint, cluster_dns=cluster_dns))
                for ami in self.list(node_class, k8s_version)]

    def reset(self) -> None:
        self._cache.flush()
