"""Standalone controller entrypoint.

The analog of the reference's `cmd/controller/main.go:32` (operator
construction + controller registration + serving) with the flag surface of
`pkg/operator/options/options.go:46-60`: every flag falls back to its env
var (CLUSTER_NAME, VM_MEMORY_OVERHEAD_PERCENT, INTERRUPTION_QUEUE, ...)
the way the reference's `env.WithDefault*` wiring does, and feature gates
take the reference's `--feature-gates Drift=true,...` form
(settings.md:40-47).

While the reconcile loop runs, the process serves:
- ``/metrics``  — the Prometheus text exposition of the registry
  (including the per-offering lattice gauge surface),
- ``/validate`` — the HTTP admission endpoint (POST an AdmissionReview-
  shaped document; schema + semantic validation answer allowed/denied —
  the reference serves the same contract from pkg/webhooks)
- ``/healthz`` and ``/readyz`` — liveness/readiness, mirroring the
  operator's AddHealthzCheck wiring (main.go:44).
"""

from __future__ import annotations

import argparse
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

from .operator import Operator, Options
from .webhooks import validate_wire

_GATES = {
    "Drift": "drift_enabled",
    "SpotToSpotConsolidation": "spot_to_spot_consolidation",
}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="karpenter-tpu-controller",
        description="TPU-native karpenter controller (device-solved "
                    "scheduling over an instance-type lattice).")
    p.add_argument("--cluster-endpoint", default=None,
                   help="apiserver endpoint for node bootstrap userdata "
                        "(env CLUSTER_ENDPOINT; empty = discover from the "
                        "cloud network, the reference's EKS fallback)")
    p.add_argument("--assume-role-arn", default=None,
                   help="role to assume for cloud calls "
                        "(env ASSUME_ROLE_ARN; reference STS session "
                        "layering)")
    p.add_argument("--cluster-name", default=None,
                   help="The cluster name for resource discovery "
                        "(env CLUSTER_NAME).")
    p.add_argument("--vm-memory-overhead-percent", type=float, default=None,
                   help="VM memory overhead subtracted from every instance "
                        "type's memory (env VM_MEMORY_OVERHEAD_PERCENT, "
                        "default 0.075).")
    p.add_argument("--isolated-vpc", action="store_true", default=None,
                   help="Assume AWS services without a VPC endpoint are "
                        "unreachable; live on-demand pricing lookups are "
                        "skipped and static prices serve (ISOLATED_VPC)")
    p.add_argument("--reserved-enis", type=int, default=None,
                   help="ENIs excluded from max-pods math "
                        "(env RESERVED_ENIS).")
    p.add_argument("--batch-idle-duration", type=float, default=None,
                   help="Seconds of pod-arrival quiet before a scheduling "
                        "pass (env BATCH_IDLE_DURATION, default 1).")
    p.add_argument("--batch-max-duration", type=float, default=None,
                   help="Max seconds a scheduling batch may wait "
                        "(env BATCH_MAX_DURATION, default 10).")
    p.add_argument("--interruption-queue", default=None,
                   help="Interruption queue name; interruption handling is "
                        "disabled if not specified "
                        "(env INTERRUPTION_QUEUE).")
    p.add_argument("--termination-grace-period", type=float, default=None,
                   help="Seconds after which a terminating node force-drains "
                        "even PDB-blocked pods; unset waits forever "
                        "(env TERMINATION_GRACE_PERIOD).")
    p.add_argument("--feature-gates", default=None,
                   help="Comma-separated gates, e.g. "
                        "'Drift=true,SpotToSpotConsolidation=false'.")
    p.add_argument("--log-level", default="INFO",
                   choices=("DEBUG", "INFO", "WARNING", "ERROR"),
                   help="Structured log verbosity (key=value lines on the "
                        "karpenter.* loggers)")
    p.add_argument("--metrics-port", type=int, default=8000,
                   help="Port serving /metrics, /healthz, /readyz "
                        "(0 disables).")
    p.add_argument("--warm-start", action="store_true",
                   help="Compile the boot (G,B) solver bucket ladder on a "
                        "background thread at startup (XLA charges 20-40s "
                        "per shape on first trace; without this the first "
                        "pending-pod batch pays it). With "
                        "--compile-cache-dir set, shapes are AOT-lowered "
                        "and compiled without executing (the first real "
                        "solve loads them from the persistent cache); "
                        "otherwise each shape executes once to warm jit's "
                        "dispatch cache. The SLO tracker holds its warmup "
                        "window open until the ladder finishes so a cold "
                        "first pass cannot fire a SloBudgetBurn. Covers "
                        "the configured pool count with no affinity "
                        "classes; workloads that add hostname-affinity "
                        "classes or custom-label virtual pools compile "
                        "their shapes on first use")
    p.add_argument("--compile-cache-dir", default=None,
                   help="Directory for JAX's persistent compilation cache "
                        "(env COMPILE_CACHE_DIR): compiled bucket-ladder "
                        "executables survive operator restarts, so a "
                        "SECOND boot pays no fresh XLA compile at all — "
                        "pair with --warm-start to also keep the FIRST "
                        "boot's compiles off the serving path. Empty "
                        "disables (in-memory jit cache only).")
    p.add_argument("--profile-dir", default=None,
                   help="Write a JAX profiler (xprof) trace of every device "
                        "solve under this directory.")
    p.add_argument("--profile", action="store_true",
                   help="Run the whole-process wall-clock sampling "
                        "profiler (docs/reference/profiling.md): a daemon "
                        "thread samples every thread's stack at "
                        "--profile-hz into a bounded folded-stack store, "
                        "served at /debug/pprof/profile (folded / "
                        "Chrome-trace forms) on both the metrics server "
                        "and the REST apiserver; kpctl profile "
                        "capture|top|diff is the CLI. Lock/queue "
                        "contention accounting and the device cost model "
                        "report regardless; this flag adds the stack "
                        "sampler (<5%% overhead measured, zero when off).")
    p.add_argument("--profile-hz", type=float, default=50.0,
                   help="Sampling frequency for --profile (default 50).")
    p.add_argument("--profile-captures", type=int, default=8,
                   help="Burn-triggered profile+contention snapshots "
                        "retained (flight-recorder-style ring): a "
                        "sustained SLO burn or a grossly over-budget "
                        "pass captures evidence at /debug/pprof/captures.")
    p.add_argument("--trace", action="store_true",
                   help="Enable request-scoped tracing + the flight "
                        "recorder (docs/reference/tracing.md): causal "
                        "spans from REST admission to the device solve, "
                        "tail-sampled retention of degraded/slow/errored "
                        "traces, served at /debug/traces (REST apiserver "
                        "and metrics server) and exported by kpctl trace.")
    p.add_argument("--trace-ring", type=int, default=256,
                   help="Completed traces kept in the flight recorder's "
                        "ring before the oldest unretained one drops.")
    p.add_argument("--trace-retained", type=int, default=64,
                   help="Tail-retained traces (errored / degraded / over "
                        "budget) pinned past ring wrap-around.")
    p.add_argument("--trace-latency-budget-ms", type=float, default=1000.0,
                   help="End-to-end trace duration above which the flight "
                        "recorder tail-retains the trace as 'slow'.")
    p.add_argument("--sidecar-address", default=None,
                   help="Also serve the solver as a gRPC sidecar on this "
                        "address (e.g. unix:/run/karpenter/solver.sock or "
                        ":50051) so external controllers can Solve() "
                        "against the resident lattice.")
    p.add_argument("--mesh", default=None,
                   help="Device mesh for the sharded solver (env "
                        "SOLVER_MESH; docs/reference/sharding.md): "
                        "'auto' (default) uses every device of a real "
                        "multi-chip backend and stays single-device on "
                        "the cpu backend (whose device count is the "
                        "--xla_force_host_platform_device_count dry-run "
                        "knob, not hardware); an integer N forces an "
                        "N-way mesh (falling back to the virtual cpu "
                        "device list, as the multichip dry-run does); "
                        "'off' pins the single-device path. With a mesh "
                        "planned, EVERY solve — full, wave-split, and "
                        "the steady-state delta — runs pod-axis sharded "
                        "over it.")
    p.add_argument("--solver-address", default=None,
                   help="Delegate provisioning solves to a POOL of solver "
                        "sidecar processes: a comma-separated list of "
                        "gRPC addresses (python -m "
                        "karpenter_provider_aws_tpu.parallel.sidecar; env "
                        "SOLVER_ADDRESSES, singular SOLVER_ADDRESS still "
                        "works). Each endpoint gets a circuit breaker "
                        "with health-checked half-open probation; solves "
                        "fail over to the least-loaded healthy endpoint, "
                        "and the local solver is the final rung only "
                        "when the whole pool is dark "
                        "(docs/reference/solver-pool.md).")
    p.add_argument("--solver-solve-deadline", type=float, default=None,
                   help="Solve RPC deadline in seconds against pool "
                        "endpoints (env SOLVER_SOLVE_DEADLINE; 0 = "
                        "derive from the SLO latency budget x 50, i.e. "
                        "10 s at the 200 ms bar). A hung sidecar costs "
                        "at most one deadline before its breaker opens.")
    p.add_argument("--solver-health-deadline", type=float, default=None,
                   help="Health/liveness RPC deadline in seconds (env "
                        "SOLVER_HEALTH_DEADLINE, default 1.0): probes "
                        "against a hung sidecar answer in about a "
                        "second instead of a solve timeout.")
    p.add_argument("--duration", type=float, default=0.0,
                   help="Run for this many seconds then exit "
                        "(0 = run until SIGINT/SIGTERM).")
    p.add_argument("--step", type=float, default=1.0,
                   help="Seconds between reconcile passes "
                        "(single-threaded loop only).")
    p.add_argument("--async-runtime", action="store_true",
                   help="Run each controller on its own cadence in its own "
                        "thread (the controller-runtime analog with "
                        "MaxConcurrentReconciles-style concurrency) instead "
                        "of the deterministic single-threaded loop.")
    p.add_argument("--api-host", default="127.0.0.1",
                   help="Bind host for --api-port (default loopback). "
                        "Binding beyond loopback requires TLS + a bearer "
                        "token, or the explicit --api-insecure opt-out: "
                        "the REST surface is write-capable.")
    p.add_argument("--api-token-file", default=None,
                   help="File holding the bearer token every REST / "
                        "admission request must present "
                        "(Authorization: Bearer <token>; 401 otherwise).")
    p.add_argument("--api-tls-cert", default=None,
                   help="PEM certificate for serving the REST apiserver "
                        "and admission endpoint over HTTPS "
                        "(deploy/gen_certs.sh mints self-signed material).")
    p.add_argument("--api-tls-key", default=None,
                   help="PEM private key matching --api-tls-cert.")
    p.add_argument("--api-watch-queue-bound", type=int, default=None,
                   help="Per-watcher event queue bound on the REST "
                        "apiserver's watch hub (env API_WATCH_QUEUE_BOUND, "
                        "default 8192): a subscriber that overruns it is "
                        "dropped to 410/relist instead of growing an "
                        "unbounded queue (docs/reference/watch.md)")
    p.add_argument("--api-bookmark-every", type=int, default=None,
                   help="Deliveries between per-watcher BOOKMARK events "
                        "carrying the current resourceVersion (env "
                        "API_BOOKMARK_EVERY, default 256; 0 disables) — "
                        "keeps idle watchers' resume points fresh")
    p.add_argument("--headroom-high-water-fraction", type=float,
                   default=None,
                   help="Occupancy fraction at which a bounded queue "
                        "counts as saturating (env "
                        "HEADROOM_HIGH_WATER_FRACTION, default 0.9): "
                        "crossing it fires one burn-capture per episode "
                        "(docs/reference/headroom.md)")
    p.add_argument("--api-insecure", action="store_true",
                   help="Explicitly allow serving the write-capable REST "
                        "surface beyond loopback WITHOUT TLS + token.")
    p.add_argument("--api-port", type=int, default=0,
                   help="Serve the control plane's apiserver over HTTP "
                        "REST on this port (kube/httpserver.py: "
                        "list/watch/create/update/patch/delete + "
                        "binding/eviction subresources). The operator "
                        "runs in API mode: controllers write through "
                        "the client, informers feed the mirror, and "
                        "EXTERNAL agents drive the same seam over the "
                        "wire. 0 disables (direct mode).")
    p.add_argument("--leader-elect-lease-file", default=None,
                   help="Enable lease-based leader election over this "
                        "shared file (async runtime only): standby "
                        "replicas idle until the lease is won, mirroring "
                        "the reference's 2-replica client-go election.")
    return p


def options_from_args(args: argparse.Namespace) -> Options:
    overrides = {}
    if args.cluster_name is not None:
        overrides["cluster_name"] = args.cluster_name
    if args.cluster_endpoint is not None:
        overrides["cluster_endpoint"] = args.cluster_endpoint
    if args.assume_role_arn is not None:
        overrides["assume_role_arn"] = args.assume_role_arn
    if args.vm_memory_overhead_percent is not None:
        overrides["vm_memory_overhead_percent"] = args.vm_memory_overhead_percent
    if args.reserved_enis is not None:
        overrides["reserved_enis"] = args.reserved_enis
    if args.isolated_vpc:
        overrides["isolated_vpc"] = True
    if args.batch_idle_duration is not None:
        overrides["batch_idle_duration"] = args.batch_idle_duration
    if args.batch_max_duration is not None:
        overrides["batch_max_duration"] = args.batch_max_duration
    if args.interruption_queue is not None:
        overrides["interruption_queue"] = args.interruption_queue
    if args.termination_grace_period is not None:
        overrides["termination_grace_period"] = args.termination_grace_period
    if args.solver_address is not None:
        overrides["solver_address"] = args.solver_address
    if args.solver_solve_deadline is not None:
        overrides["solver_solve_deadline"] = args.solver_solve_deadline
    if args.solver_health_deadline is not None:
        overrides["solver_health_deadline"] = args.solver_health_deadline
    if args.mesh is not None:
        overrides["mesh"] = args.mesh
    if args.compile_cache_dir is not None:
        overrides["compile_cache_dir"] = args.compile_cache_dir
    if args.api_watch_queue_bound is not None:
        overrides["api_watch_queue_bound"] = args.api_watch_queue_bound
    if args.api_bookmark_every is not None:
        overrides["api_bookmark_every"] = args.api_bookmark_every
    if args.headroom_high_water_fraction is not None:
        overrides["headroom_high_water_fraction"] = \
            args.headroom_high_water_fraction
    for gate in (args.feature_gates or "").split(","):
        gate = gate.strip()
        if not gate:
            continue
        name, _, val = gate.partition("=")
        field = _GATES.get(name.strip())
        if field is None:
            raise SystemExit(
                f"unknown feature gate {name!r} (known: {sorted(_GATES)})")
        val = val.strip().lower()
        if val in ("true", "1", "yes"):
            overrides[field] = True
        elif val in ("false", "0", "no"):
            overrides[field] = False
        else:
            raise SystemExit(
                f"feature gate {name.strip()}: value {val!r} is not true/false")
    return Options.from_env(**overrides)


def start_server(op: Operator, port: int,
                 certfile: Optional[str] = None,
                 keyfile: Optional[str] = None) -> ThreadingHTTPServer:
    """Serve /metrics, /healthz, /readyz and POST /validate on a daemon
    thread; ``certfile``/``keyfile`` serve it all over HTTPS (the
    reference's webhook cert posture; the TLS handshake runs
    per-connection, kube/httpserver.py). The whole surface is
    deliberately token-free: metrics/health are the scrape/probe
    contract, and /validate must be callable by a kube-apiserver webhook
    client, which authenticates the SERVER via the caBundle but sends no
    bearer token — and validation is a pure function with nothing to
    protect. Port 0 binds an ephemeral port (server.server_address
    reports it)."""

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            # HTTP admission endpoint (reference pkg/webhooks/webhooks.go
            # serves knative-style admission). Two review dialects:
            # - native: {"kind": <plural>, "spec": <wire dict>} →
            #   {"allowed": bool, "causes": [...]}
            # - AdmissionReview v1 (what a real kube-apiserver POSTs per
            #   deploy/templates/webhooks.yaml): {"kind":
            #   "AdmissionReview", "request": {"uid", "resource":
            #   {"resource": <plural>}, "object": {"spec": ...}}} →
            #   the AdmissionReview response envelope.
            if self.path not in ("/validate", "/validate/"):
                self.send_error(404)
                return
            import json as _json
            try:
                length = int(self.headers.get("Content-Length", "0"))
                review = _json.loads(self.rfile.read(length) or b"{}")
                if review.get("kind") == "AdmissionReview":
                    req = review["request"]
                    uid = req.get("uid", "")
                    kind = req["resource"]["resource"]
                    obj = req["object"]
                    spec = dict(obj.get("spec", obj))
                    # real k8s objects carry name under metadata; the
                    # wire schema requires spec.name — fold it in
                    meta_name = obj.get("metadata", {}).get("name")
                    if "name" not in spec and meta_name:
                        spec["name"] = meta_name
                    wrap = "admissionreview"
                else:
                    uid, wrap = "", "native"
                    kind = review["kind"]
                    spec = review["spec"]
                if not isinstance(kind, str) or not isinstance(spec, dict):
                    raise ValueError("kind must be a string, spec an object")
            except Exception as e:
                # a malformed review is the CLIENT's fault: 400, never a
                # dropped connection
                self.send_error(400, f"bad review document: {e}")
                return
            try:
                causes = validate_wire(kind, spec)
            except Exception:
                # a bug in the validation chain is OUR fault: 500, and no
                # internal exception text leaks to the caller
                self.send_error(500, "validation error")
                return
            if wrap == "admissionreview":
                doc = {"apiVersion": "admission.k8s.io/v1",
                       "kind": "AdmissionReview",
                       "response": {"uid": uid, "allowed": not causes,
                                    **({"status": {"message": "; ".join(
                                        causes)}} if causes else {})}}
            else:
                doc = {"allowed": not causes, "causes": causes}
            body = _json.dumps(doc).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            encoding = None
            if self.path.startswith("/debug/statusz") or \
                    self.path.startswith("/debug/vars") or \
                    self.path.startswith("/debug/pprof") or \
                    self.path.startswith("/debug/explain") or \
                    self.path.startswith("/debug/headroom"):
                # the introspection surfaces (docs/reference/
                # introspection.md), mounted here like /debug/traces so
                # deployments without --api-port still reach them
                from urllib.parse import parse_qs as _pq
                from urllib.parse import urlparse as _up
                from . import introspect as _introspect
                url = _up(self.path)
                rendered = _introspect.debug_doc(url.path, _pq(url.query))
                if rendered is None:
                    self.send_error(404)
                    return
                body, ctype = rendered
                from .kube.httpserver import maybe_gzip
                body, encoding = maybe_gzip(
                    body, self.headers.get("Accept-Encoding"))
            elif self.path.startswith("/debug/traces"):
                # the flight recorder's read surface, also mounted here so
                # deployments without --api-port still reach their traces
                import json as _json
                from urllib.parse import parse_qs as _pq
                from urllib.parse import urlparse as _up
                from . import trace as _trace
                url = _up(self.path)
                rec = _trace.recorder()
                doc = (rec.debug_doc(url.path, _pq(url.query))
                       if rec is not None else None)
                if doc is None:
                    self.send_error(404, "no such trace (or tracing "
                                         "disabled; pass --trace)")
                    return
                body = _json.dumps(doc).encode()
                ctype = "application/json"
            elif self.path == "/metrics":
                body = op.metrics.render().encode()
                ctype = "text/plain; version=0.0.4"
                # the scrape grew with the per-offering gauge surface and
                # the new lock-wait histogram; Prometheus sends
                # Accept-Encoding: gzip on every scrape
                from .kube.httpserver import maybe_gzip
                body, encoding = maybe_gzip(
                    body, self.headers.get("Accept-Encoding"))
            elif self.path in ("/healthz", "/readyz"):
                # the reference's liveness probe is the cloud connectivity
                # check (main.go:44 cloud-provider healthz)
                try:
                    op.cloud.liveness_probe()
                    body, ctype = b"ok", "text/plain"
                except Exception as e:
                    self.send_error(503, str(e))
                    return
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            if encoding:
                self.send_header("Content-Encoding", encoding)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet by default
            pass

    from .kube.httpserver import make_http_server
    server = make_http_server(("0.0.0.0", port), Handler, certfile, keyfile)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


def main(argv: Optional[Sequence[str]] = None,
         stop_event: Optional[threading.Event] = None) -> int:
    """``stop_event`` is the programmatic SIGTERM: tests (which cannot
    signal a thread) set it to end the run early."""
    args = build_parser().parse_args(argv)
    from .utils.logging import configure as configure_logging
    configure_logging(args.log_level)
    opts = options_from_args(args)
    if args.trace:
        # before ANY server/operator construction so the first admitted
        # request is already traceable
        from . import trace
        from .trace import FlightRecorder
        trace.enable(FlightRecorder(
            ring=args.trace_ring, retained=args.trace_retained,
            latency_budget_ms=args.trace_latency_budget_ms))
    if args.profile:
        # likewise before the operator build: boot compile cost is
        # usually exactly what a profile is for
        from . import introspect
        introspect.enable_profiling(hz=args.profile_hz)
    api_token = None
    if args.api_token_file:
        api_token = open(args.api_token_file).read().strip()
        if not api_token:
            raise SystemExit(f"--api-token-file {args.api_token_file} "
                             "is empty")
    if bool(args.api_tls_cert) != bool(args.api_tls_key):
        raise SystemExit("--api-tls-cert and --api-tls-key go together")
    api_server = None
    api_httpd = None
    queue = None
    if args.api_port:
        # loopback names resolvable by the AF_INET server only
        loopback = args.api_host in ("127.0.0.1", "localhost")
        if (not loopback and not args.api_insecure
                and not (api_token and args.api_tls_cert)):
            raise SystemExit(
                "refusing to serve the write-capable REST surface on "
                f"{args.api_host} without TLS (--api-tls-cert/key) AND a "
                "bearer token (--api-token-file); pass --api-insecure to "
                "override explicitly")
        from .interruption.queue import FakeQueue
        from .kube import (FakeAPIServer, install_admission,
                           install_default_indexes)
        from .kube.httpserver import serve as serve_api
        # watch tuning rides the CONSTRUCTOR: this surface serves (and
        # accepts watch subscriptions, whose queue bound is frozen at
        # subscribe time) before the slow Operator build applies options
        api_server = FakeAPIServer(
            watch_queue_bound=opts.api_watch_queue_bound,
            bookmark_every=opts.api_bookmark_every)
        # admission/indexes are wired BEFORE the first byte is served:
        # objects written during the (slow) operator build face the same
        # 422-with-causes contract as every later write — and the
        # surface comes up BEFORE that build, so external agents connect
        # while JAX imports/compiles. The interruption queue is built
        # here (injected into the Operator below) so its wire route
        # serves equally early.
        install_default_indexes(api_server)
        install_admission(api_server)
        if opts.interruption_queue:
            queue = FakeQueue(opts.interruption_queue)
        api_httpd = serve_api(api_server, args.api_port,
                              host=args.api_host, token=api_token,
                              certfile=args.api_tls_cert,
                              keyfile=args.api_tls_key,
                              queue=queue)
        from .utils.logging import get_logger
        get_logger("cli").info(
            "apiserver REST surface listening",
            port=api_httpd.server_address[1],
            tls=bool(args.api_tls_cert), auth=bool(api_token))
    op = Operator(options=opts, api_server=api_server,
                  interruption_queue=queue)
    # the introspection sampler (docs/reference/introspection.md): 1 Hz
    # ring series behind /debug/vars?series=1 and kpctl top. One provider
    # fan-out per second — off every hot path by construction.
    op.sampler.start(interval=1.0)
    op.burn_capture.resize(args.profile_captures)
    if args.profile:
        # the device cost model fills from a lowering-only trace of the
        # warm ladder (no XLA compile, no execution) so measured-vs-
        # modeled attribution works from the first real solve; the AOT
        # warmup path below records the same analyses from its compiled
        # handles
        capture_fn = getattr(op.solver, "capture_cost_model", None)
        if capture_fn is not None:   # RemoteSolver solves out-of-process
            threading.Thread(
                target=lambda: capture_fn(
                    node_pools_count=len(op.node_pools)),
                name="costmodel-capture", daemon=True).start()

    stop = stop_event or threading.Event()

    def _stop(signum, frame):
        stop.set()

    try:
        signal.signal(signal.SIGINT, _stop)
        signal.signal(signal.SIGTERM, _stop)
    except ValueError:
        pass  # not the main thread (tests drive main() directly)

    server = (start_server(op, args.metrics_port,
                           certfile=args.api_tls_cert,
                           keyfile=args.api_tls_key)
              if args.metrics_port else None)
    sidecar = None
    if args.sidecar_address:
        from .parallel.sidecar import serve as serve_sidecar
        sidecar = serve_sidecar(op.solver, args.sidecar_address)
    if args.warm_start:
        # the SLO warmup window opens NOW and closes when the AOT ladder
        # finishes: latency recorded while shapes still compile is boot
        # cost, not steady-state burn (introspect/slo.py)
        op.slo.begin_warmup()
        # AOT (compile-without-execute) ONLY pays off when the compiled
        # executables land somewhere the first real solve can load them
        # — the persistent cache; without it the executing path is what
        # actually warms jit's dispatch cache
        op.solver.warmup(node_pools_count=len(op.node_pools),
                         g_buckets=op.solver.BOOT_G_BUCKETS,
                         b_buckets=op.solver.BOOT_B_BUCKETS,
                         probes=True, background=True,
                         aot=bool(opts.compile_cache_dir),
                         on_done=op.slo.end_warmup)
    if args.profile_dir:
        op.solver.start_profiling(args.profile_dir)
    deadline = (time.monotonic() + args.duration) if args.duration > 0 else None
    runtime = None
    try:
        if args.async_runtime:
            from .operator.runtime import ControllerRuntime, operator_specs
            elector = None
            if args.leader_elect_lease_file:
                import os
                from .operator.leaderelection import FileLeaseStore, LeaderElector
                elector = LeaderElector(
                    FileLeaseStore(args.leader_elect_lease_file),
                    identity=f"{os.uname().nodename}-{os.getpid()}")
            elif api_server is not None:
                # API mode elects through the apiserver's coordination
                # lease (client-go semantics) with no extra wiring
                import os
                from .operator.leaderelection import (ApiLeaseStore,
                                                      LeaderElector)
                elector = LeaderElector(
                    ApiLeaseStore(api_server),
                    identity=f"{os.uname().nodename}-{os.getpid()}")
            runtime = ControllerRuntime(operator_specs(op),
                                        elector=elector).start()
            while not stop.is_set():
                if deadline is not None and time.monotonic() >= deadline:
                    break
                stop.wait(0.2)
        else:
            while not stop.is_set():
                op.run_once()
                if deadline is not None and time.monotonic() >= deadline:
                    break
                stop.wait(args.step)
    finally:
        op.sampler.stop()
        if args.profile:
            from . import introspect
            prof = introspect.profiler_instance()
            if prof is not None:
                prof.stop()
        if runtime is not None:
            runtime.stop()
        if args.profile_dir:
            op.solver.stop_profiling()
        if sidecar is not None:
            sidecar.stop(grace=None)
        if server is not None:
            server.shutdown()
        if api_httpd is not None:
            api_httpd.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
