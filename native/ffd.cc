// Native First-Fit-Decreasing referee.
//
// C++ mirror of the Python FFD oracle (karpenter_provider_aws_tpu/solver/
// oracle.py, itself a faithful reimplementation of the reference's
// sequential Go scheduler loop — reference designs/bin-packing.md:16-43).
// The Python referee is exact but per-pod Python-object work makes it
// unusable at the 50k-pod benchmark scale; this native referee runs the
// identical algorithm over dense arrays in ~1 s, so the device kernel's
// cost parity (BASELINE.md <=2% envelope) is checkable at full scale on
// every bench run.
//
// Scope: new-node packing with per-group type/zone/captype masks, pool
// masks + weight order, daemonset overhead, per-bin caps, per-pool
// allocatable ceilings (kubelet maxPods), and pre-existing (fixed) bins
// with their own reported allocatable — the semantics the large-scale
// benchmark configs exercise, incl. the 500-node consolidation repack.
// Hostname affinity classes (pm/po symmetry checks, presence needs,
// spread-class skew caps, single-bin co-location) are in scope too; only
// strict custom-key matching over unknown-pool nodes stays Python-side.
//
// Built on demand by karpenter_provider_aws_tpu/native/build.py:
//   g++ -O3 -shared -fPIC -o libffd.so ffd.cc

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Bin {
    std::vector<uint64_t> tmask;  // feasible types (bitset over T)
    std::vector<uint64_t> zmask;  // bitset over Z
    std::vector<uint64_t> cmask;  // bitset over C
    std::vector<float> cum;       // [R]
    std::vector<int32_t> pm;      // [A] pods matching affinity class a
    std::vector<uint64_t> po;     // bitset over A: holds an owner of class a
    int np_idx;                   // -1 = unknown pool (fixed bins only)
    int npods;                    // pods ADDED by this pack
    int last_group;               // per-row cap bookkeeping
    int last_group_count;
    int e_idx;                    // >=0: fixed existing bin (type pinned,
                                  // capacity = its own reported allocatable,
                                  // excluded from finalization cost)
};

inline bool bit(const std::vector<uint64_t>& m, int i) {
    return (m[i >> 6] >> (i & 63)) & 1ull;
}

inline void clear_bit(std::vector<uint64_t>& m, int i) {
    m[i >> 6] &= ~(1ull << (i & 63));
}

inline bool any(const std::vector<uint64_t>& m) {
    for (uint64_t w : m) if (w) return true;
    return false;
}

}  // namespace

extern "C" {

// Returns the number of opened bins (>=0) or -1 on error.
// Outputs: out_cost[0] = total $/hr of opened bins (cheapest offering per
// bin), out_leftover[0] = pods that fit nowhere, out_chosen_t/z/c[b] = the
// finalized offering per bin (arrays sized max_bins).
int ffd_pack(
    int T, int Z, int C, int R, int G, int NP, int E, int A,
    const float* alloc,        // [T,R]
    const uint8_t* avail,      // [T,Z,C]
    const float* price,        // [T,Z,C]
    const float* g_req,        // [G,R]
    const int32_t* g_count,    // [G]
    const uint8_t* g_type,     // [G,T]
    const uint8_t* g_zone,     // [G,Z]
    const uint8_t* g_cap,      // [G,C]
    const uint8_t* g_np,       // [G,NP]
    const int32_t* g_maxper,   // [G] per-bin cap (INT32_MAX = none)
    const int32_t* g_spread,   // [G] spread class whose pm count the cap
                               // tracks (-1 = cap is per-row)
    const uint8_t* g_single,   // [G] all replicas share one bin
    const uint8_t* g_match,    // [G,A] affinity classes the group matches
    const uint8_t* g_owner,    // [G,A] anti-affinity terms the group owns
    const uint8_t* g_need,     // [G,A] classes the bin must already hold
    const uint8_t* np_type,    // [NP,T]
    const uint8_t* np_zone,    // [NP,Z]
    const uint8_t* np_cap,     // [NP,C]
    const float* ds,           // [NP,R]
    const float* pool_cap,     // [NP,R] allocatable ceiling (+inf = none)
    const float* e_used,       // [E,R] existing-bin committed resources
    const float* e_alloc,      // [E,R] existing-bin reported allocatable
    const int32_t* e_type,     // [E]
    const int32_t* e_zone,     // [E]
    const int32_t* e_cap,      // [E]
    const int32_t* e_np,       // [E] owning pool (-1 = unknown)
    const int32_t* e_pm,       // [E,A] bound-pod affinity-class counts
    const uint8_t* e_po,       // [E,A] bound pod owns anti-term a
    int max_bins,
    float* out_cost,
    int64_t* out_leftover,
    int32_t* out_chosen_t,
    int32_t* out_chosen_z,
    int32_t* out_chosen_c,
    int32_t* out_e_npods) {    // [E] pods ADDED per existing bin

    if (T <= 0 || Z <= 0 || C <= 0 || R <= 0 || G < 0 || NP <= 0 || E < 0
        || A < 0)
        return -1;
    const int TW = (T + 63) / 64, ZW = (Z + 63) / 64, CW = (C + 63) / 64;
    const int AW = (A + 63) / 64;
    const float EPS = 1e-3f;

    // type t has an available offering within (zmask, cmask)?
    auto type_reachable = [&](int t, const std::vector<uint64_t>& zm,
                              const std::vector<uint64_t>& cm) -> bool {
        const uint8_t* a = avail + (size_t)t * Z * C;
        for (int z = 0; z < Z; z++) {
            if (!bit(zm, z)) continue;
            for (int c = 0; c < C; c++) {
                if (bit(cm, c) && a[z * C + c]) return true;
            }
        }
        return false;
    };

    std::vector<Bin> bins;
    bins.reserve(256 + E);
    int64_t leftover = 0;

    // pre-seed fixed bins from existing capacity (first-fit order: the
    // Python oracle offers existing nodes before any new bin)
    for (int e = 0; e < E; e++) {
        Bin b;
        b.tmask.assign(TW, 0);
        b.zmask.assign(ZW, 0);
        b.cmask.assign(CW, 0);
        b.tmask[e_type[e] >> 6] |= 1ull << (e_type[e] & 63);
        b.zmask[e_zone[e] >> 6] |= 1ull << (e_zone[e] & 63);
        b.cmask[e_cap[e] >> 6] |= 1ull << (e_cap[e] & 63);
        b.cum.assign(e_used + (size_t)e * R, e_used + (size_t)(e + 1) * R);
        if (A > 0) {
            b.pm.assign(e_pm + (size_t)e * A, e_pm + (size_t)(e + 1) * A);
            b.po.assign(AW, 0);
            for (int a = 0; a < A; a++)
                if (e_po[(size_t)e * A + a]) b.po[a >> 6] |= 1ull << (a & 63);
        }
        b.np_idx = e_np[e];
        b.npods = 0;
        b.last_group = -1;
        b.last_group_count = 0;
        b.e_idx = e;
        bins.push_back(std::move(b));
    }

    std::vector<uint64_t> tm(TW), zm(ZW), cm(CW);
    std::vector<uint64_t> owner_bits(AW), match_bits(AW);
    std::vector<int> single_home(G, -1);

    for (int g = 0; g < G; g++) {
        const float* req = g_req + (size_t)g * R;
        const int32_t cap = g_maxper[g];
        const int32_t spread = g_spread[g];
        const bool single = g_single[g] != 0;
        const uint8_t* match = g_match + (size_t)g * A;
        const uint8_t* owner = g_owner + (size_t)g * A;
        const uint8_t* need = g_need + (size_t)g * A;
        bool seed_ok = true;   // a fresh bin satisfies needs by self-seeding
        if (A > 0) {
            for (int w = 0; w < AW; w++) { owner_bits[w] = 0; match_bits[w] = 0; }
            for (int a = 0; a < A; a++) {
                if (owner[a]) owner_bits[a >> 6] |= 1ull << (a & 63);
                if (match[a]) match_bits[a >> 6] |= 1ull << (a & 63);
                if (need[a] && !match[a]) seed_ok = false;
            }
        }
        // first-fit resume point: a bin this group's previous pod skipped is
        // unchanged (only entered bins mutate), so it stays infeasible for
        // the identical next pod — scanning may resume where the last pod
        // landed instead of at bin 0
        size_t resume = 0;
        for (int32_t k = 0; k < g_count[g]; k++) {
            bool placed = false;
            // ---- first-fit over open bins ----
            for (size_t bi = resume; bi < bins.size() && !placed; bi++) {
                Bin& b = bins[bi];
                if (single && single_home[g] >= 0 && (int)bi != single_home[g])
                    continue;
                // unknown-pool fixed bins are pool-agnostic (the gateway
                // declines strict custom-key problems when any exist)
                if (b.np_idx >= 0 && !g_np[(size_t)g * NP + b.np_idx]) continue;
                if (cap != INT32_MAX) {
                    // spread-class caps count the CLASS's pods in the bin
                    // (bound + sibling groups); class-less caps count this
                    // row's own placements
                    int cnt;
                    if (spread >= 0) cnt = b.pm[spread];
                    else cnt = (b.last_group == g) ? b.last_group_count : 0;
                    if (cnt >= cap) continue;
                }
                if (A > 0) {
                    // k8s symmetry: the bin holds no pod we anti-affine
                    // against, no pod anti-affining against us, and every
                    // class we need is present (every bin carries pm/po
                    // state when A > 0 — seeded at creation)
                    bool conflict = false;
                    for (int w = 0; w < AW && !conflict; w++)
                        if (b.po[w] & match_bits[w]) conflict = true;
                    for (int a = 0; a < A && !conflict; a++)
                        if (owner[a] && b.pm[a] > 0) conflict = true;
                    if (conflict) continue;
                    bool need_ok = true;
                    for (int a = 0; a < A && need_ok; a++)
                        if (need[a] && b.pm[a] <= 0) need_ok = false;
                    if (!need_ok) continue;
                }
                if (b.e_idx >= 0) {
                    // fixed node: its own type/zone/captype must satisfy the
                    // group, capacity checks against its reported allocatable
                    if (!g_type[(size_t)g * T + e_type[b.e_idx]] ||
                        !g_zone[(size_t)g * Z + e_zone[b.e_idx]] ||
                        !g_cap[(size_t)g * C + e_cap[b.e_idx]]) continue;
                    const float* al = e_alloc + (size_t)b.e_idx * R;
                    bool fits = true;
                    for (int r = 0; r < R; r++) {
                        if (b.cum[r] + req[r] > al[r] + EPS) { fits = false; break; }
                    }
                    if (!fits) continue;
                    for (int r = 0; r < R; r++) b.cum[r] += req[r];
                    b.npods++;
                    if (A > 0) {
                        for (int a = 0; a < A; a++) b.pm[a] += match[a] ? 1 : 0;
                        for (int w = 0; w < AW; w++) b.po[w] |= owner_bits[w];
                    }
                    if (b.last_group == g) b.last_group_count++;
                    else { b.last_group = g; b.last_group_count = 1; }
                    if (single) single_home[g] = (int)bi;
                    resume = bi;
                    placed = true;
                    continue;
                }
                // intersect masks
                bool tz_any = false;
                for (int w = 0; w < ZW; w++) {
                    zm[w] = b.zmask[w];
                }
                for (int w = 0; w < CW; w++) cm[w] = b.cmask[w];
                for (int z = 0; z < Z; z++)
                    if (bit(zm, z) && !g_zone[(size_t)g * Z + z]) clear_bit(zm, z);
                for (int c = 0; c < C; c++)
                    if (bit(cm, c) && !g_cap[(size_t)g * C + c]) clear_bit(cm, c);
                if (!any(zm) || !any(cm)) continue;
                // per-type: group-compatible, still fits, reachable
                for (int w = 0; w < TW; w++) tm[w] = 0;
                const float* capv = pool_cap + (size_t)b.np_idx * R;
                for (int t = 0; t < T; t++) {
                    if (!bit(b.tmask, t) || !g_type[(size_t)g * T + t]) continue;
                    const float* al = alloc + (size_t)t * R;
                    bool fits = true;
                    for (int r = 0; r < R; r++) {
                        float lim = al[r] < capv[r] ? al[r] : capv[r];
                        if (b.cum[r] + req[r] > lim + EPS) { fits = false; break; }
                    }
                    if (!fits) continue;
                    if (!type_reachable(t, zm, cm)) continue;
                    tm[t >> 6] |= 1ull << (t & 63);
                    tz_any = true;
                }
                if (!tz_any) continue;
                // commit
                b.tmask = tm;
                b.zmask = zm;
                b.cmask = cm;
                for (int r = 0; r < R; r++) b.cum[r] += req[r];
                b.npods++;
                if (A > 0) {
                    for (int a = 0; a < A; a++) b.pm[a] += match[a] ? 1 : 0;
                    for (int w = 0; w < AW; w++) b.po[w] |= owner_bits[w];
                }
                if (b.last_group == g) b.last_group_count++;
                else { b.last_group = g; b.last_group_count = 1; }
                if (single) single_home[g] = (int)bi;
                resume = bi;
                placed = true;
            }
            if (placed) continue;
            // single-bin groups never straddle: once a home exists, a pod
            // that doesn't fit it is unschedulable; a fresh bin satisfies
            // presence needs only by self-seeding
            if (single && single_home[g] >= 0) { leftover++; continue; }
            if (A > 0 && !seed_ok) { leftover++; continue; }
            // ---- open a new bin: highest-weight compatible pool ----
            for (int p = 0; p < NP && !placed; p++) {
                if (!g_np[(size_t)g * NP + p]) continue;
                for (int w = 0; w < ZW; w++) zm[w] = 0;
                for (int w = 0; w < CW; w++) cm[w] = 0;
                for (int z = 0; z < Z; z++)
                    if (np_zone[(size_t)p * Z + z] && g_zone[(size_t)g * Z + z])
                        zm[z >> 6] |= 1ull << (z & 63);
                for (int c = 0; c < C; c++)
                    if (np_cap[(size_t)p * C + c] && g_cap[(size_t)g * C + c])
                        cm[c >> 6] |= 1ull << (c & 63);
                if (!any(zm) || !any(cm)) continue;
                bool tz_any = false;
                for (int w = 0; w < TW; w++) tm[w] = 0;
                const float* dsv = ds + (size_t)p * R;
                const float* capv = pool_cap + (size_t)p * R;
                for (int t = 0; t < T; t++) {
                    if (!np_type[(size_t)p * T + t] || !g_type[(size_t)g * T + t]) continue;
                    const float* al = alloc + (size_t)t * R;
                    bool fits = true;
                    for (int r = 0; r < R; r++) {
                        float lim = al[r] < capv[r] ? al[r] : capv[r];
                        if (dsv[r] + req[r] > lim + EPS) { fits = false; break; }
                    }
                    if (!fits) continue;
                    if (!type_reachable(t, zm, cm)) continue;
                    tm[t >> 6] |= 1ull << (t & 63);
                    tz_any = true;
                }
                if (!tz_any) continue;
                if ((int)bins.size() >= max_bins) { break; }
                Bin b;
                b.tmask = tm;
                b.zmask = zm;
                b.cmask = cm;
                b.cum.assign(dsv, dsv + R);
                for (int r = 0; r < R; r++) b.cum[r] += req[r];
                b.np_idx = p;
                b.npods = 1;
                if (A > 0) {
                    b.pm.assign(A, 0);
                    for (int a = 0; a < A; a++) b.pm[a] = match[a] ? 1 : 0;
                    b.po = owner_bits;
                }
                b.last_group = g;
                b.last_group_count = 1;
                b.e_idx = -1;
                bins.push_back(std::move(b));
                if (single) single_home[g] = (int)bins.size() - 1;
                resume = bins.size() - 1;
                placed = true;
            }
            if (!placed) leftover++;
        }
    }

    // ---- finalize: cheapest available offering per NEW bin (fixed bins
    // report pods-added only; the caller prices retained capacity) ----
    double total = 0.0;
    int n_new = 0;
    for (size_t bi = 0; bi < bins.size(); bi++) {
        const Bin& b = bins[bi];
        if (b.e_idx >= 0) {
            out_e_npods[b.e_idx] = b.npods;
            continue;
        }
        float best = -1.0f;
        int bt = -1, bz = -1, bc = -1;
        for (int t = 0; t < T; t++) {
            if (!bit(b.tmask, t)) continue;
            const float* pr = price + (size_t)t * Z * C;
            const uint8_t* a = avail + (size_t)t * Z * C;
            for (int z = 0; z < Z; z++) {
                if (!bit(b.zmask, z)) continue;
                for (int c = 0; c < C; c++) {
                    if (!bit(b.cmask, c) || !a[z * C + c]) continue;
                    float p = pr[z * C + c];
                    if (best < 0.0f || p < best) { best = p; bt = t; bz = z; bc = c; }
                }
            }
        }
        if (bt < 0) return -2;  // invariant violation: open bin w/o offering
        total += best;
        if (n_new < max_bins) {
            out_chosen_t[n_new] = bt;
            out_chosen_z[n_new] = bz;
            out_chosen_c[n_new] = bc;
        }
        n_new++;
    }
    *out_cost = (float)total;
    *out_leftover = leftover;
    return n_new;
}

}  // extern "C"
